"""ClusterCoreWorker: the per-process runtime in cluster mode.

Reference counterpart: ``src/ray/core_worker/core_worker.h:262`` — the object
ops (Put/Get/Wait), task ops (SubmitTask/CreateActor/SubmitActorTask) and
bookkeeping embedded in every driver and worker process. Implements the same
interface the local-mode LocalRuntime exposes to the public API, but routes:

  placement     -> GCS batch placement service (the kernel)
  task dispatch -> placed node's NodeController
  objects       -> node object stores, located via the GCS directory
  actors        -> GCS actor table + the owning node's controller
"""

from __future__ import annotations

import hashlib
import itertools
import os
import pickle
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from .._private import tracing
from .._private.ids import ActorID, JobID, ObjectID, TaskID
from .._private.runtime import _EventLog, ensure_context
from .._private.serialization import SerializedObject, get_context
from .._private.task_spec import TaskSpec
from ..exceptions import ActorDiedError, GetTimeoutError
from ..object_ref import ObjectRef
from . import wire
from .protocol import ResilientClient, RpcClient

ERR_PREFIX = b"E"
VAL_PREFIX = b"V"

# Shared staleness window for owner-pushed direct refs the owner never
# observed: both the submit-side backlog guard and the lease janitor use it,
# and both confirm with the GCS that the result was actually produced before
# dropping an entry (see _expire_direct_outstanding).
DIRECT_STALE_S = 60.0


class ClusterCoreWorker:
    def __init__(self, gcs_addr: Tuple[str, int],
                 controller_addr: Optional[Tuple[str, int]] = None,
                 role: str = "driver", config=None):
        from .._private.config import get_config

        self.config = config or get_config()
        self.role = role
        self.gcs = ResilientClient(*gcs_addr,
                                   on_reconnect=self._on_gcs_reconnect)
        self.gcs_addr = gcs_addr
        # Random, NOT time-derived: two drivers initialized within the
        # same second would otherwise share a job id — and therefore the
        # whole deterministic task/object id sequence — so the GCS's
        # idempotent submit_task dedupe would silently serve one driver
        # the other's stale results (observed as cross-test contamination
        # against a shared cluster).
        self.job_id = JobID.from_random()
        self.driver_task_id = TaskID.for_driver_task(self.job_id)
        self.events = _EventLog(self.config.event_log_enabled)
        self._thread_scope_counter = itertools.count(1 << 31)
        self._ser = get_context()
        self._exported_fns: set = set()
        self._fn_id_by_obj: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary())
        self._fn_lock = threading.Lock()
        self._controllers: Dict[Tuple[str, int], RpcClient] = {}
        self._controller_lock = threading.Lock()
        self._home_addr = controller_addr  # workers: their own node
        self._actor_addr_cache: Dict[bytes, Tuple[str, int]] = {}
        self._actor_resources: Dict[bytes, Dict[str, float]] = {}
        self._blob_cache: Dict[bytes, bytes] = {}
        self._blob_cache_order: deque = deque()
        # Objects THIS process put that contain no nested ObjectRefs: the
        # only ones safe to inline as task args (inlining a container of
        # refs would drop the dep pin that transitively protects its
        # children — contained refs are not recoverable from the blob).
        self._inline_ok: set = set()
        self._inline_ok_order: deque = deque()
        # Same-host shared-memory arena, when one is reachable (workers get
        # it from their controller's env; drivers attach lazily — shm
        # existence doubles as the same-host check).
        self.local_store = None
        # Same-host result data plane: a per-owner shm completion ring
        # (see _native/completion_ring.py). Consumer side: THIS process's
        # ring, harvested by get()/wait()/the future resolver — O(wave)
        # ring pops instead of O(arena) rescans. Publisher side: rings of
        # OTHER owners this process executes tasks for, opened by name
        # derived from the return oid's job bytes (False = probed absent;
        # re-probed after _PUB_RETRY_S so a late-created ring is found).
        self._ring: Any = None
        self._ring_ready: set = set()          # oids known sealed in arena
        self._ring_ready_order: deque = deque()
        self._pub_rings: Dict[str, Any] = {}
        self._pub_lock = threading.Lock()
        if role == "driver":
            self._ensure_ring()
        # Ownership plane (wire v9): this process OWNS every object its
        # job tree creates. Drivers run an owner table + serve loop and
        # register with the GCS owner directory; controllers then publish
        # results owner-to-owner and the head keeps only the membership
        # row (reference: the per-worker ownership table of
        # reference_count.h — the owner, not the GCS, resolves its refs).
        self._owner_table: Any = None
        self._owner_server: Any = None
        self._transfer_cli: Any = None  # None=unprobed, False=unavailable
        self._transfer_has_store = False
        self._sub_client = None
        # Pipelined task submission: specs buffer here and move to the GCS
        # in batched, idempotent submit_batch calls (reference: the owner's
        # async submission queue in direct_task_transport.h:46).
        self._submit_buf: List[Dict] = []
        self._submit_lock = threading.Lock()
        self._submit_timer: Any = None
        # Driver-side phase profiler cells: name -> [count, seconds]. The
        # three phases measured here (driver_serialize, submit_rpc,
        # driver_fetch) join the four server-side ones (GCS debug_stats)
        # for the 7-phase per-task breakdown scripts/cluster_lat.py prints.
        self.phase_stats: Dict[str, list] = {}
        # Per-task tracing (ISSUE 3): spans recorded in THIS process —
        # driver phases (serialize/submit/fetch) or, inside workers, the
        # exec/register phases — buffered here and flushed to the GCS
        # trace table with the profile events. _trace_by_oid maps a
        # sampled task's return oids to its trace so get() can close the
        # driver_fetch span on arrival.
        self.trace_spans: List[Dict] = []
        self._trace_span_lock = threading.Lock()
        self._trace_by_oid: Dict[bytes, Tuple[bytes, bytes]] = {}
        self._trace_by_oid_order: deque = deque()
        self._bp_event_last = 0.0  # log_event throttle for backpressure
        # Distributed reference counting (reference: reference_count.h:33;
        # the owner<->borrower WaitForRefRemoved protocol of
        # core_worker.proto:322 collapses into holder registration with the
        # GCS, which already owns the object directory + task lifecycle).
        # Every process holding a live ObjectRef is a registered holder;
        # transitions ship as batched one-way ref_updates, and a periodic
        # full-set refresh doubles as a lease so holders that die without
        # dec'ing (SIGKILL) expire at the GCS.
        import uuid as _uuid

        self.worker_uid = _uuid.uuid4().hex
        # Owner worker leases for direct push (reference: the per-
        # SchedulingKey lease map in direct_task_transport.h:46): one lease
        # per resource class; idle leases are returned by a janitor thread.
        self._direct_lock = threading.Lock()
        self._direct_leases: Dict[Tuple, Dict] = {}
        self._direct_outstanding: Dict[bytes, float] = {}  # rid -> push time
        self._direct_expire_last = 0.0
        self._direct_janitor: Any = None
        # Shared as_future resolver (one thread + one directory long-poll
        # for every outstanding future).
        self._future_lock = threading.Lock()
        self._future_waiters: Dict[bytes, list] = {}
        self._future_thread: Any = None
        self._future_event = threading.Event()
        self._future_probe_last = 0.0
        self._ref_lock = threading.Lock()
        self._ref_counts: Dict[bytes, int] = {}
        self._ref_inc: List[bytes] = []
        self._ref_dec: List[bytes] = []
        self._ref_dirty = threading.Event()  # wakes the flusher
        self._ref_flusher: Any = None
        self._ref_refresher: Any = None
        self._ref_shutdown = threading.Event()
        # Driver-side observability flush (flight-recorder drains, result-
        # path counter deltas, phase-histogram deltas to the GCS
        # time-series, trace-sample kv poll) — see _stats_flush_loop.
        self._stats_stop = threading.Event()
        self._stats_thread: Any = None
        self._stats_counter_last: Dict[str, float] = {}
        self._stats_hist_last: Dict[str, Dict] = {}
        if role == "driver":
            self._subscribe_logs()
            try:
                # Attach to a same-host shm arena early: get() then reads
                # results zero-copy instead of over RPC.
                self._home_controller()
            except Exception:  # noqa: BLE001 - no nodes yet; attach lazily
                pass
            if getattr(self.config, "flight_recorder", True):
                from .._private import flight_recorder

                flight_recorder.start("driver")
            self._stats_thread = threading.Thread(
                target=self._stats_flush_loop, daemon=True,
                name="driver-stats-flush")
            self._stats_thread.start()
            if wire.ownership_enabled():
                self._init_ownership()

    # ------------------------------------------------------------- refcount
    def add_local_ref(self, oid) -> None:
        """0->1 transitions register this process as a holder with the GCS
        (batched one-way). Called from ObjectRef.__init__."""
        if not self.config.ref_counting_enabled:
            return
        b = oid.binary()
        with self._ref_lock:
            n = self._ref_counts.get(b, 0) + 1
            self._ref_counts[b] = n
            if n == 1:
                self._ref_inc.append(b)
                self._arm_ref_timer()

    def remove_local_ref(self, oid) -> None:
        if not self.config.ref_counting_enabled:
            return
        b = oid.binary()
        with self._ref_lock:
            n = self._ref_counts.get(b, 0) - 1
            if n > 0:
                self._ref_counts[b] = n
                return
            self._ref_counts.pop(b, None)
            if n == 0:
                self._ref_dec.append(b)
                self._arm_ref_timer()

    def _arm_ref_timer(self) -> None:
        # Caller holds _ref_lock. One persistent flusher thread batches
        # transitions on a 20ms cadence (a Timer per window would churn
        # ~50 OS threads/s under ref-heavy loops).
        self._ref_dirty.set()
        if self._ref_flusher is None:
            self._ref_flusher = threading.Thread(
                target=self._ref_flush_loop, daemon=True)
            self._ref_flusher.start()
        if self._ref_refresher is None:
            self._ref_refresher = threading.Thread(
                target=self._ref_refresh_loop, daemon=True)
            self._ref_refresher.start()

    def _ref_flush_loop(self) -> None:
        while not self._ref_shutdown.is_set():
            self._ref_dirty.wait()
            if self._ref_shutdown.is_set():
                return
            time.sleep(0.02)  # batch the window's transitions
            self._ref_dirty.clear()
            self._flush_refs()

    def _flush_refs(self) -> None:
        with self._ref_lock:
            inc, self._ref_inc = self._ref_inc, []
            dec, self._ref_dec = self._ref_dec, []
        if not inc and not dec:
            return
        try:
            self.gcs.send_oneway({"type": "ref_update",
                                  "worker": self.worker_uid,
                                  "inc": inc, "dec": dec})
        except (ConnectionError, OSError):
            pass  # the next refresh re-asserts the authoritative held set

    def _ref_refresh_loop(self) -> None:
        """Lease heartbeat: periodically re-assert the full held set. The
        GCS treats it as authoritative for this worker (drops stale holds)
        and expires workers that stop refreshing."""
        while not self._ref_shutdown.wait(2.0):
            with self._ref_lock:
                held = list(self._ref_counts)
            try:
                self.gcs.send_oneway({"type": "ref_refresh",
                                      "worker": self.worker_uid,
                                      "held": held})
            except (ConnectionError, OSError):
                pass

    def _report_contained(self, parent_oid: bytes, children: List[bytes]):
        """Refs pickled inside a stored object pin their targets while the
        containing object lives (reference: AddNestedObjectIds)."""
        if children and self.config.ref_counting_enabled:
            try:
                self.gcs.send_oneway({"type": "ref_contained",
                                      "parent": parent_oid,
                                      "children": children})
            except (ConnectionError, OSError):
                pass

    def _on_gcs_reconnect(self, client) -> None:
        """After a re-dial (head restart or failover to the standby):
        re-assert this process's state on the new leader. Everything here
        is idempotent — the GCS treats ref_refresh as the authoritative
        held set, and the log subscription is per-connection so the old
        one died with the old head. Exported functions need no replay:
        put_function is replicated, so the new leader already has them."""
        if self._ref_shutdown.is_set():
            return
        with self._ref_lock:
            held = list(self._ref_counts)
        try:
            client.send_oneway({"type": "ref_refresh",
                               "worker": self.worker_uid, "held": held})
        except (ConnectionError, OSError):
            pass  # the periodic refresh loop re-asserts in <= 2 s
        # Owner directory row: replicated, so a failover restored it — but
        # a cold head restart did not. Registration is idempotent.
        if self._owner_server is not None:
            self._register_owner(client)
        if self._sub_client is not None:
            try:
                self._sub_client.close()
            except Exception:  # noqa: BLE001
                pass
            self._sub_client = None
            self._subscribe_logs()

    def _subscribe_logs(self) -> None:
        """Stream worker stdout/stderr lines to this driver's console
        (reference: worker.py:960 print_logs over redis pubsub)."""
        import sys as _sys

        def on_push(msg):
            if msg.get("type") != "pubsub" or msg.get("channel") != "logs":
                return
            data = msg.get("data", {})
            prefix = f"({data.get('node_id', '')[:8]} pid={data.get('pid')})"
            for line in data.get("lines", []):
                print(f"{prefix} {line}", file=_sys.stderr)

        try:
            # self.gcs.addr, not self.gcs_addr: after a failover the live
            # head is whatever address the ResilientClient rotated to.
            self._sub_client = RpcClient(*self.gcs.addr, push_handler=on_push)
            self._sub_client.call({"type": "subscribe", "channel": "logs"})
        except (ConnectionError, OSError):
            self._sub_client = None

    # ---------------------------------------------------------------- helpers
    def _controller(self, addr: Tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        with self._controller_lock:
            client = self._controllers.get(addr)
            if client is None or client._closed:
                client = RpcClient(*addr,
                                   push_handler=self._on_controller_push)
                self._controllers[addr] = client
            return client

    def _on_controller_push(self, msg: Dict) -> None:
        """Unsolicited controller messages; currently lease-loss
        notifications (the leased worker died while the controller stayed
        reachable, so no connection error tells us)."""
        if msg.get("type") == "lease_lost":
            lease_id = msg.get("lease_id")
            with self._direct_lock:
                for key, lease in list(self._direct_leases.items()):
                    if lease.get("lease_id") == lease_id:
                        del self._direct_leases[key]

    def _home_controller(self) -> RpcClient:
        if self._home_addr is not None:
            return self._controller(self._home_addr)
        nodes = self.gcs.call({"type": "list_nodes"})["nodes"]
        for n in nodes:
            if not n["Alive"]:
                continue
            try:
                client = self._controller(tuple(n["Address"]))
                self._home_addr = tuple(n["Address"])
                if self.local_store is None and n.get("StoreName"):
                    # Attach to the node's shm arena if it exists on this
                    # host (open failure == different host).
                    from .._native import open_store

                    self.local_store = open_store(n["StoreName"])
                return client
            except (ConnectionError, OSError):
                self.gcs.call({"type": "report_node_dead",
                               "node_id": n["NodeID"]})
        from ..exceptions import ClusterUnavailableError

        raise ClusterUnavailableError("no reachable nodes in cluster")

    def _export_fn(self, fn: Callable) -> bytes:
        # Export-once semantics (reference: FunctionActorManager exports at
        # decoration time, not per call): the same function object submitted
        # N times must not pay N cloudpickles — at cluster task rates the
        # serialization dominates driver CPU. Keyed by object identity;
        # a WeakKeyDictionary so defining-and-dropping lambdas can't leak.
        try:
            cached = self._fn_id_by_obj.get(fn)
        except TypeError:  # unhashable/unweakreferenceable callable
            cached = None
        if cached is not None:
            return cached
        blob = cloudpickle.dumps(fn)
        fn_id = hashlib.blake2b(blob, digest_size=16).digest()
        with self._fn_lock:
            if fn_id not in self._exported_fns:
                self.gcs.call({"type": "put_function", "fn_id": fn_id,
                               "blob": blob})
                self._exported_fns.add(fn_id)
        try:
            self._fn_id_by_obj[fn] = fn_id
        except TypeError:
            pass
        return fn_id

    def _pack_value(self, value: Any,
                    pins: Optional[List[bytes]] = None) -> Tuple[str, bytes]:
        sobj = self._ser.serialize(value)
        if pins is not None and sobj.contained_refs:
            pins.extend(sobj.contained_refs)
        return ("value", sobj.to_bytes())

    def _pack_ref_arg(self, oid: bytes, deps: List[bytes]):
        """Ref arg fast path (reference: the dependency resolver's
        small-object inlining, max_direct_call_object_size): a small value
        blob already available locally ships inline in the task spec —
        no directory lookup, no dep staging, no fetch on the other side."""
        limit = self.config.max_direct_call_object_size
        if oid in self._inline_ok:
            blob = self._local_blob(oid)
            if (blob is not None and blob[:1] == VAL_PREFIX
                    and len(blob) - 1 <= limit):
                return ("value", blob[1:])
        deps.append(oid)
        return ("ref", oid)

    def _pack_args(self, spec: TaskSpec):
        args = []
        deps: List[bytes] = []
        pins: List[bytes] = []  # refs nested inside plain-value args
        for kind, payload in spec.args:
            if kind == "ref":
                args.append(self._pack_ref_arg(payload.binary(), deps))
            else:
                args.append(self._pack_value(payload, pins))
        kwargs = {}
        for key, val in spec.metadata.get("kwargs", {}).items():
            if isinstance(val, ObjectRef):
                kwargs[key] = self._pack_ref_arg(val.id.binary(), deps)
            else:
                kwargs[key] = self._pack_value(val, pins)
        return args, kwargs, deps, pins

    def record_trace_span(self, trace: bytes, task_id, phase: str,
                          start_mono: float, end_mono: float,
                          via: str = "") -> None:
        """Buffer one phase span of a sampled task (flushed in batches).
        ``via`` attributes a driver_fetch span to its delivery path
        (ring / inline / inline_push / rpc)."""
        sp = tracing.make_span(trace, task_id, phase, start_mono, end_mono,
                               src=self.role, via=via)
        with self._trace_span_lock:
            self.trace_spans.append(sp)
            if len(self.trace_spans) > 50_000:
                del self.trace_spans[:10_000]

    # ------------------------------------------------ driver stats flush
    def _stats_deltas(self) -> Tuple[Dict[str, float], Dict[str, Dict]]:
        """Per-flush deltas of the driver's phase/result counters and the
        trace_phase_ms histogram — the GCS time-series merges deltas
        additively, so each flush ships only what happened since the last."""
        from ..metrics import histogram_cells

        counters: Dict[str, float] = {}
        for name, cell in list(self.phase_stats.items()):
            if name.startswith("result:"):
                pairs = [(name, float(cell[0]))]
            else:
                # Driver-side phases join the GCS-side phase_* series so
                # the time-series holds the full 7-phase view.
                pairs = [(f"phase_count:{name}", float(cell[0])),
                         (f"phase_seconds:{name}", cell[1])]
            for key, cur in pairs:
                last = self._stats_counter_last.get(key, 0.0)
                if cur > last:
                    counters[key] = cur - last
                self._stats_counter_last[key] = cur
        hists: Dict[str, Dict] = {}
        for tags, cell in histogram_cells("trace_phase_ms").items():
            phase = dict(tags).get("phase") or "unknown"
            name = f"trace_phase_ms:{phase}"
            last = self._stats_hist_last.get(name, {})
            delta_buckets = {
                bound: n - last.get("buckets", {}).get(bound, 0)
                for bound, n in cell["buckets"].items()
                if n - last.get("buckets", {}).get(bound, 0) > 0}
            if delta_buckets:
                hists[name] = {
                    "buckets": delta_buckets,
                    "sum": cell["sum"] - last.get("sum", 0.0),
                    "count": cell["count"] - last.get("count", 0)}
            self._stats_hist_last[name] = cell
        return counters, hists

    def _stats_flush_loop(self) -> None:
        from .._private import flight_recorder, loopmon, tracing

        trace_kv_last: Any = ("\0unset",)
        cpu_sampler = loopmon.cpu_sampler("driver")
        dwell_last = 0.0
        while not self._stats_stop.wait(2.0):
            try:
                msg: Dict[str, Any] = {"type": "driver_stats",
                                       "worker": self.worker_uid}
                counters, hists = self._stats_deltas()
                if counters:
                    msg["counters"] = counters
                if hists:
                    msg["hists"] = hists
                rec = flight_recorder.get()
                if rec is not None:
                    stacks, stacks_cpu = rec.drain_tagged()
                    if stacks:
                        msg["stacks"] = stacks
                        msg["stacks_oncpu"] = stacks_cpu
                        msg["component"] = rec.component
                        msg["samples"] = sum(stacks.values())
                        flight_recorder.flush_metrics(rec, msg["samples"])
                # Observatory ride-alongs: per-thread CPU/ctx-switch
                # window + the GCS-link reader's blocked-in-recv delta
                # (the conservation ledger's socket_dwell numerator).
                if cpu_sampler is not None:
                    tc = cpu_sampler.drain()
                    if tc:
                        tc["component"] = cpu_sampler.component or "driver"
                        msg["thread_cpu"] = tc
                dwell = float(
                    self.gcs.io_stats.get("recv_dwell_s", 0.0))
                if dwell > dwell_last:
                    msg["socket_dwell_s"] = dwell - dwell_last
                    dwell_last = dwell
                if len(msg) > 2:
                    self.gcs.send_oneway(msg)
                # Runtime-adjustable trace sampling: the driver makes the
                # per-task sampling decision, so it polls the kv cell
                # `cli trace --sample` writes.
                resp = self.gcs.call(
                    {"type": "kv_get",
                     "key": tracing.TRACE_SAMPLE_KV_KEY}, timeout=5.0)
                raw = resp.get("value")
                if raw != trace_kv_last:
                    trace_kv_last = raw
                    tracing.apply_kv_rate(raw)
            except (ConnectionError, OSError):
                continue  # GCS restart window: next tick retries
            except Exception:  # noqa: BLE001 - observability never kills
                continue

    def _phase_add(self, name: str, seconds: float, n: int = 1) -> None:
        """Accumulate one phase-profiler cell (GIL-tolerant; a lost sample
        under a rare race is acceptable for a profiler)."""
        cell = self.phase_stats.get(name)
        if cell is None:
            cell = self.phase_stats[name] = [0, 0.0]
        cell[0] += n
        cell[1] += seconds

    # ------------------------------------------------- result data plane
    def _ensure_ring(self):
        """Create this owner's completion ring (idempotent). Drivers do it
        eagerly; worker cores only when they first own results (nested
        submissions), so short-lived workers don't litter /dev/shm."""
        from .._native import completion_ring as cring

        if self._ring is None and cring.ring_enabled():
            try:
                self._ring = cring.CompletionRing(
                    cring.ring_name(self.job_id.binary()), create=True)
            except OSError:
                self._ring = False  # creation failed: old path serves
        return self._ring or None

    def _ring_active(self) -> bool:
        ring = self._ring
        return bool(ring) and not ring.degraded

    def publish_completion(self, oid: bytes, size: int,
                           inline: Optional[bytes] = None) -> bool:
        """Publish one sealed result straight into its owner's completion
        ring (the ring name is derived from the oid's job bytes). Best
        effort: False when the owner is cross-host, the ring is
        full/degraded, or the plane is disabled — the result then reaches
        the owner through the normal directory path."""
        from .._native import completion_ring as cring

        if not cring.ring_enabled() or len(oid) < 16:
            return False
        name = cring.ring_name(oid[12:16])
        with self._pub_lock:
            pub = self._pub_rings.get(name)
            if pub is None or (isinstance(pub, float)
                               and time.monotonic() > pub):
                opened = cring.open_publisher(name)
                if opened is None:
                    # Probed absent: cross-host owner (common) or a ring
                    # created after our probe — re-probe after a beat.
                    self._pub_rings[name] = time.monotonic() + 5.0
                    if len(self._pub_rings) > 256:
                        self._pub_rings.pop(next(iter(self._pub_rings)))
                    return False
                pub = self._pub_rings[name] = opened
            elif isinstance(pub, float):
                return False
        try:
            ok = pub.publish(oid, size, inline=inline)
        except (OSError, ValueError):
            ok = False
        if not ok and pub.degraded:
            with self._pub_lock:
                self._pub_rings[name] = time.monotonic() + 30.0
            pub.close()
        return ok

    def _count_result(self, via: str, n: int = 1, nbytes: int = 0) -> None:
        """Attribute n result deliveries to one path (ring / inline /
        fetch_rpc ...): a phase-stats cell (read by the A/B script and the
        message-count tests) plus the exported metrics."""
        if n <= 0:
            return
        self._phase_add(f"result:{via}", 0.0, n)
        m = getattr(self, "_rp_metrics", None)
        if m is None:
            from ..metrics import result_plane_metrics

            m = self._rp_metrics = result_plane_metrics()
        m["records"].record(n, tags={"via": via})
        if nbytes:
            m["inline_bytes"].record(nbytes)

    def _ring_wait(self, budget_s: float,
                   deadline: Optional[float]) -> bool:
        """Ring-first wait (the plasma notification-socket discipline):
        watch the ring's head word — one mmap read per tick — instead of
        parking on the directory long-poll, so a same-host completion is
        picked up in sub-millisecond time and the GCS never builds a wake
        response for it. Returns True as soon as unpopped records exist;
        False after ``budget_s`` of silence (the caller then falls back to
        the long-poll, which remains the path for cross-host results,
        worker crashes, and ring-full fallbacks)."""
        ring = self._ring
        if not ring or ring.degraded:
            return False
        end = time.monotonic() + budget_s
        if deadline is not None and deadline < end:
            end = deadline
        sleep_s = 0.0002
        while True:
            if ring.has_pending():
                return True
            if time.monotonic() >= end:
                return False
            time.sleep(sleep_s)
            if sleep_s < 0.001:
                sleep_s *= 2

    def _ring_harvest(self, pending: Optional[set] = None
                      ) -> List[Tuple[bytes, bytes, str]]:
        """Drain this owner's completion ring. Records matching ``pending``
        resolve to (oid, blob, via) for the caller; everything else parks
        in the blob cache (inline payloads) or the ring-ready set (arena
        slots) for whichever get()/wait()/future asks next."""
        ring = self._ring
        if not ring or ring.degraded:
            return []
        recs = ring.pop_all()
        if not recs:
            return []
        out: List[Tuple[bytes, bytes, str]] = []
        ready_new: List[bytes] = []
        store = self.local_store
        n_ring = n_inline = inline_bytes = 0
        for oid, flags, size, inline in recs:
            if inline is not None:
                n_inline += 1
                inline_bytes += len(inline)
                if pending is not None and oid in pending:
                    out.append((oid, inline, "inline"))
                else:
                    self._cache_blob(oid, inline)
                continue
            n_ring += 1
            if pending is not None and oid in pending and store is not None:
                blob = store.get_bytes(oid)
                if blob is not None:
                    out.append((oid, blob, "ring"))
                    continue
            ready_new.append(oid)
        if ready_new:
            # Batched bookkeeping: one set.update + one deque.extend + a
            # single trim pass instead of per-record churn (the harvest is
            # on the get() hot path).
            self._ring_ready.update(ready_new)
            self._ring_ready_order.extend(ready_new)
            for _ in range(len(self._ring_ready_order) - 65536):
                self._ring_ready.discard(self._ring_ready_order.popleft())
        if ring.degraded:
            # Torn record detected mid-harvest (worker died mid-publish):
            # everything already popped is intact; the rest of this job
            # rides the RPC/directory path.
            from ..metrics import result_plane_metrics

            result_plane_metrics()["ring_torn"].record(1.0)
        self._count_result("ring", n_ring)
        self._count_result("inline", n_inline, inline_bytes)
        return out

    # ------------------------------------------------------ ownership plane
    def _init_ownership(self) -> None:
        """Stand up this driver's owner table + serve loop and register
        with the GCS owner directory. Failure anywhere (pre-v9 head, bind
        error) leaves ownership off for this driver — results then ride
        the legacy GCS-tracked path, which stays fully supported."""
        from . import ownership

        try:
            if self._gcs_wire_version() < 9:
                return  # pre-v9 head has no owner directory
            table = ownership.OwnerTable()
            server = ownership.OwnerServer(
                table, host="0.0.0.0", on_publish=self._owner_republish)
            server.start()
            self._owner_table = table
            self._owner_server = server
            self._register_owner()
            # Keep the owner lease warm from t0: the ref refresher doubles
            # as the owner heartbeat, and an idle driver (registered but
            # not yet submitting) must not expire before its first task.
            with self._ref_lock:
                self._arm_ref_timer()
        except Exception:  # noqa: BLE001 - ownership is an optimization
            if self._owner_server is not None:
                try:
                    self._owner_server.stop()
                except Exception:  # noqa: BLE001
                    pass
            self._owner_table = None
            self._owner_server = None

    def _owner_address(self) -> list:
        """Routable address of the owner-serve loop: the IP the GCS
        connection uses locally (correct across hosts), loopback when it
        can't be read."""
        host = "127.0.0.1"
        try:
            host = self.gcs._ensure()._sock.getsockname()[0]
            if host in ("0.0.0.0", ""):
                host = "127.0.0.1"
        except Exception:  # noqa: BLE001 - single-host fallback
            pass
        return [host, self._owner_server.port]

    def _register_owner(self, client=None) -> None:
        """Idempotent directory registration (replicated at the GCS, so a
        failover restores it; re-asserted on every reconnect anyway)."""
        if self._owner_server is None:
            return
        msg = {"type": "register_owner",
               "job_id": self.job_id.binary(),
               "address": self._owner_address(),
               "worker": self.worker_uid,
               "node_id": ""}
        try:
            if client is not None:
                client.call(msg, timeout=5.0)
            else:
                self.gcs.call(msg, timeout=10.0)
        except Exception:  # noqa: BLE001 - re-asserted on reconnect
            pass

    def _owner_republish(self, fresh) -> None:
        """Owner-serve callback: a controller just published records into
        this driver's owner table. Blob-bearing records re-enter the ring
        data plane so get()/wait()/futures wake through the exact harvest
        path same-host results already use; blob-less records are
        address-only pointers (the completion ring carried the bytes, or a
        fetch from the named node will) and need no delivery here."""
        for oid, size, blob in fresh:
            if blob is None:
                continue
            try:
                if not self.publish_completion(oid, size, inline=blob):
                    self._cache_blob(oid, blob)
            except Exception:  # noqa: BLE001 - table consult is the backstop
                self._cache_blob(oid, blob)

    def _owner_pointer_fetch(self, oids) -> Dict[bytes, dict]:
        """Locations for pending oids the owner table tracks ADDRESS-ONLY
        (ring record lost to a full/disabled ring): shaped like directory
        infos so _fetch_many pulls them from the holding node directly —
        the GCS never saw these objects."""
        table = self._owner_table
        if table is None or not len(table):
            return {}
        infos: Dict[bytes, dict] = {}
        for oid in oids:
            loc = table.locate(oid)
            if loc is not None and not loc["inline"] \
                    and loc["addr"] is not None:
                infos[oid] = {"addresses": [list(loc["addr"])]}
        return infos

    # ---------------------------------------------------------- submit pipe
    def _queue_submit(self, msg: Dict) -> None:
        with self._submit_lock:
            self._submit_buf.append(msg)
            n = len(self._submit_buf)
            if self._submit_timer is None:
                # Arm a short flush timer so a lone submit still departs
                # quickly even if the caller never get()s.
                self._submit_timer = threading.Timer(
                    0.003, self._flush_submits)
                self._submit_timer.daemon = True
                self._submit_timer.start()
        if n >= 128:
            # Inline (blocking) flush ON PURPOSE: the round trip paces the
            # submitter to what the GCS can absorb. A/B'd against a
            # background pump thread (callers never block, buffer caps of
            # 256 and 2048): both measured WORSE warm 5k throughput
            # (1,083-1,145 vs 1,270 tasks/s) — an unpaced submitter floods
            # the placement/dispatch queues and the whole pipeline pays.
            self._flush_submits()

    def _flush_submits(self) -> None:
        with self._submit_lock:
            timer, self._submit_timer = self._submit_timer, None
            buf, self._submit_buf = self._submit_buf, []
        if timer is not None:
            timer.cancel()
        if not buf:
            return
        msg: Optional[Dict] = None
        if len(buf) > 1 and not wire.pickle_only() \
                and wire.columnar_submit_enabled() \
                and self._gcs_wire_version() >= 8:
            # Columnar hot path: same-template tasks share ONE spec header
            # (fn_id/name/retries/resources encoded once per run); only the
            # task ids, return ids and arg tails travel per task. Falls
            # back to the per-task frames when no run forms.
            t0 = time.perf_counter()
            msg = self._build_columnar_submit(buf)
            self._phase_add("driver_serialize", time.perf_counter() - t0, 0)
        if msg is None:
            if not wire.pickle_only():
                # Serialize each spec ONCE into its wire blob: the submit
                # frame carries these bytes, the GCS keeps them opaque, and
                # the executing worker is the only decoder (zero
                # re-serialization along the relay).
                t0 = time.perf_counter()
                for t in buf:
                    if "_spec" not in t:
                        t["_spec"] = wire.encode_task_spec(t)
                self._phase_add("driver_serialize",
                                time.perf_counter() - t0, 0)
            msg = {"type": "submit_batch", "tasks": buf}
        try:
            t0 = time.perf_counter()
            t0m = time.monotonic()
            self.gcs.call(msg)
            self._phase_add("submit_rpc", time.perf_counter() - t0, len(buf))
            t1m = time.monotonic()
            for t in buf:
                tr = t.get("trace")
                if tr is not None:
                    # The batch RPC carried this sampled task: its
                    # submit_rpc span is the batch's wire window.
                    self.record_trace_span(tr, t["task_id"], "submit_rpc",
                                           t0m, t1m)
        except (ConnectionError, OSError):
            # Put them back and re-arm the retry timer; submit_batch is
            # idempotent per task_id so a re-send is safe. Without the
            # timer, a blocked get() would poll forever for tasks that
            # were never delivered.
            with self._submit_lock:
                self._submit_buf = buf + self._submit_buf
                if self._submit_timer is None:
                    self._submit_timer = threading.Timer(
                        0.25, self._flush_submits)
                    self._submit_timer.daemon = True
                    self._submit_timer.start()

    def _gcs_wire_version(self) -> int:
        """The GCS's advertised wire version, probed once per connection
        and cached on the underlying RpcClient (a reconnect builds a new
        client, so the probe naturally re-runs against a new leader).
        Pre-v8 and unknown peers report 1: the caller keeps the per-task
        legacy frames, which every peer parses."""
        try:
            cli = self.gcs._ensure()
        except Exception:  # noqa: BLE001 - can't dial; legacy path is safe
            return 1
        w = getattr(cli, "_srv_wire", None)
        if w is None:
            try:
                resp = self.gcs.call({"type": "wire_probe"}, timeout=5.0)
                w = int(resp.get("wire", 1)) if resp.get("ok") else 1
            except Exception:  # noqa: BLE001 - old GCS / flaky link => v1
                w = 1
            try:
                cli = self.gcs._ensure()
                cli._srv_wire = w
                if w > cli.peer_wire:
                    # The ResilientClient never handshakes wire versions
                    # (the GCS advertises to nodes/workers at registration
                    # only), so lift the client's peer floor here: without
                    # it encode() would pickle the columnar frame.
                    cli.peer_wire = w
            except Exception:  # noqa: BLE001 - reconnected mid-probe
                pass
        return int(w)

    @staticmethod
    def _template_key(t: Dict) -> Optional[Tuple]:
        """Grouping key for the columnar submit: tasks sharing a key share
        one spec template. None = ineligible (trace/deadline extensions
        need the v2/v3 per-task header; dep/pin lists are almost never
        shared, so they ride the legacy singles rather than fragment the
        runs)."""
        if t.get("trace") is not None or t.get("timeout_s") is not None \
                or t.get("deps") or t.get("pin_refs"):
            return None
        res = t.get("resources") or {}
        return (t.get("fn_id"), t.get("name"), int(t.get("max_retries", 0)),
                tuple(sorted(res.items())))

    def _build_columnar_submit(self, buf: List[Dict]) -> Optional[Dict]:
        """Partition a submit buffer into template runs (>=2 tasks sharing
        a template) + legacy singles; None when no run forms (the per-task
        frame is then strictly better — no run headers to pay for)."""
        groups: Dict[Tuple, List[Dict]] = {}
        singles: List[Dict] = []
        for t in buf:
            key = self._template_key(t)
            if key is None:
                singles.append(t)
            else:
                groups.setdefault(key, []).append(t)
        runs = []
        for ts in groups.values():
            if len(ts) < 2:
                singles.extend(ts)
                continue
            seg_a, seg_b = wire.encode_spec_segments(ts[0])
            runs.append({
                "ver": wire.SPEC_VERSION, "seg_a": seg_a, "seg_b": seg_b,
                "task_ids": [t["task_id"] for t in ts],
                "return_oids": [t.get("return_ids", ()) for t in ts],
                "tails": [wire.encode_spec_tail(t) for t in ts],
            })
        if not runs:
            return None
        for t in singles:
            if "_spec" not in t:
                t["_spec"] = wire.encode_task_spec(t)
        return {"type": "submit_batch_cols", "runs": runs,
                "singles": singles}

    # ------------------------------------------------------------------ tasks
    def next_task_id(self) -> TaskID:
        ctx = ensure_context(self)
        return TaskID.for_normal_task(
            ctx.job_id, ctx.current_task_id, next(ctx.task_counter)
        )

    def _place_and_send(self, resources: Dict[str, float], message: Dict,
                        attempts: int = 5) -> Dict:
        """Request placement and deliver to the granted node; a node that
        refuses connections is reported dead and placement retried."""
        last_err: Optional[BaseException] = None
        for _ in range(attempts):
            placement = self.gcs.call({
                "type": "request_placement", "resources": resources,
                "locality": None, "timeout": 60.0,
            }, timeout=90.0)
            addr = tuple(placement["address"])
            try:
                node = self._controller(addr)
                node.call(message)
                return placement
            except (ConnectionError, OSError, TimeoutError) as e:
                last_err = e
                self.gcs.call({"type": "report_node_dead",
                               "node_id": placement["node_id"]})
        from ..exceptions import ClusterUnavailableError

        raise ClusterUnavailableError(
            f"could not deliver task after {attempts} placements: {last_err}")

    def submit_task(self, fn: Callable, spec: TaskSpec) -> List[ObjectRef]:
        """Submit a task. Two paths (reference: direct task transport vs
        the queued raylet path):

        * **direct push** — dependency-free tasks, while few results are
          outstanding, go straight to a worker this owner leased from a
          node controller (one RPC hop, no GCS queue on the critical
          path); a lineage record is sent to the GCS first so
          worker-death retries / reconstruction still work;
        * **queued** — everything else goes to the GCS task table, which
          owns placement (batch kernel), dispatch, and retry.
        """
        if self._ring is None:
            # Worker cores create their ring on first ownership (nested
            # submissions) — before the spec leaves, so the executing
            # worker's publish probe finds it.
            self._ensure_ring()
        trace = tracing.maybe_sample()
        t0 = time.perf_counter()
        t0m = time.monotonic() if trace is not None else 0.0
        fn_id = self._export_fn(fn)
        args, kwargs, deps, pins = self._pack_args(spec)
        return_ids = [oid.binary() for oid in spec.return_ids()]
        resources = spec.resources.to_dict()
        payload = {
            "task_id": spec.task_id.binary(),
            "name": spec.function.repr_name,
            "fn_id": fn_id, "args": args, "kwargs": kwargs,
            "deps": deps, "pin_refs": pins, "return_ids": return_ids,
            "resources": resources, "max_retries": spec.max_retries,
        }
        if getattr(spec, "timeout_s", None) is not None:
            # Deadline fields ride the spec (wire: v3 header extension) so
            # the controller can enforce expiry; deadline-free tasks keep
            # the v1/v2 bytes.
            payload["timeout_s"] = float(spec.timeout_s)
            if spec.retry_on_timeout:
                payload["retry_on_timeout"] = True
        self._phase_add("driver_serialize", time.perf_counter() - t0)
        if trace is not None:
            # Trace context rides inside the spec (wire: v2 header
            # extension) so every hop can stamp its phase span.
            payload["trace"] = trace
            self.record_trace_span(trace, payload["task_id"],
                                   "driver_serialize", t0m, time.monotonic())
            with self._trace_span_lock:
                for rid in return_ids:
                    self._trace_by_oid[rid] = (trace, payload["task_id"])
                    self._trace_by_oid_order.append(rid)
                while len(self._trace_by_oid_order) > 8192:
                    self._trace_by_oid.pop(
                        self._trace_by_oid_order.popleft(), None)
        if not deps and self.config.direct_call_enabled \
                and self._direct_submit(payload):
            return [ObjectRef(oid) for oid in spec.return_ids()]
        self._queue_submit(payload)
        return [ObjectRef(oid) for oid in spec.return_ids()]

    # ------------------------------------------------------ direct push path
    def _direct_submit(self, payload: Dict) -> bool:
        """Push a dependency-free task to a leased worker; False => caller
        uses the queued path. Never blocks on lease acquisition: a missing
        lease is requested in the background so the NEXT submit hits it."""
        key = tuple(sorted(payload["resources"].items()))
        now = time.monotonic()
        # Backlog guard: a leased worker executes serially, so a large
        # fan-out belongs to the queued path where the kernel spreads it
        # over the cluster. Stale entries (refs never get()ed) expire once
        # the GCS confirms their results exist (outside the lock — the
        # expiry makes an RPC).
        if len(self._direct_outstanding) >= \
                self.config.direct_call_max_outstanding:
            self._expire_direct_outstanding(now)
        with self._direct_lock:
            if len(self._direct_outstanding) >= \
                    self.config.direct_call_max_outstanding:
                return False
            lease = self._direct_leases.get(key)
            if lease is None or lease.get("acquiring"):
                if lease is None:
                    self._direct_leases[key] = {"acquiring": True}
                    threading.Thread(
                        target=self._acquire_lease, args=(key,),
                        daemon=True).start()
                return False
            lease["last_used"] = now
            for rid in payload["return_ids"]:
                self._direct_outstanding[rid] = now
        try:
            # Record BEFORE push: when the leased worker dies mid-task the
            # controller reports task_failed against this record and the
            # GCS re-drives it on the queued path (max_retries preserved).
            self.gcs.send_oneway(dict(
                payload, type="record_direct_task",
                node_id=lease["node_id"]))
            lease["client"].send_oneway(dict(
                payload, type="push_task", lease_id=lease["lease_id"]))
            return True
        except (ConnectionError, OSError):
            with self._direct_lock:
                dead = self._direct_leases.pop(key, None)
                for rid in payload["return_ids"]:
                    self._direct_outstanding.pop(rid, None)
            if dead is not None and not dead.get("acquiring"):
                # Best-effort controller-side release: if only the GCS leg
                # failed, the controller is still holding a worker + shares
                # for this lease (the controller also reaps leases when the
                # owner's connection drops).
                threading.Thread(target=self._release_lease, args=(dead,),
                                 daemon=True).start()
            # The record may already be at the GCS: convert it into a
            # queued task. If the record never arrived either (requeued
            # False), fall back to a normal submission — returning True
            # with no record anywhere would strand the ObjectRefs forever.
            try:
                resp = self.gcs.call({"type": "requeue_task",
                                      "task_id": payload["task_id"],
                                      "node_id": lease["node_id"]})
                return bool(resp.get("requeued"))
            except (ConnectionError, OSError):
                return False

    def _acquire_lease(self, key: Tuple) -> None:
        """Background lease acquisition (one thread per resource class)."""
        import uuid as _uuid

        resources = dict(key)
        placement = None
        leased = False
        try:
            placement = self.gcs.call({
                "type": "request_placement", "resources": resources,
                "locality": None, "timeout": 10.0,
            }, timeout=15.0)
            addr = tuple(placement["address"])
            lease_id = _uuid.uuid4().bytes
            client = self._controller(addr)
            resp = client.call({"type": "lease_worker",
                                "lease_id": lease_id,
                                "resources": resources}, timeout=15.0)
            if not resp.get("ok", True):
                raise RuntimeError(resp.get("error", "lease denied"))
            leased = True
            with self._direct_lock:
                self._direct_leases[key] = {
                    "lease_id": lease_id, "client": client,
                    "addr": addr, "node_id": placement["node_id"],
                    "last_used": time.monotonic(),
                }
            self._start_direct_janitor()
        except Exception:  # noqa: BLE001 - lease denied: queued path serves
            with self._direct_lock:
                self._direct_leases.pop(key, None)
        finally:
            if placement is not None and not leased:
                # Placement reserved a cluster-side share the lease never
                # claimed: give it back.
                try:
                    self.gcs.send_oneway({
                        "type": "release_resources",
                        "node_id": placement["node_id"],
                        "resources": resources})
                except (ConnectionError, OSError):
                    pass

    def _start_direct_janitor(self) -> None:
        with self._direct_lock:
            if self._direct_janitor is not None:
                return
            self._direct_janitor = threading.Thread(
                target=self._direct_janitor_loop, daemon=True)
            self._direct_janitor.start()

    def _direct_janitor_loop(self) -> None:
        """Return idle leases (reference: lease returns on idle in
        direct_task_transport.cc ReturnWorker)."""
        while not self._ref_shutdown.wait(1.0):
            idle_s = self.config.direct_lease_idle_s
            now = time.monotonic()
            to_release = []
            # Expire completed-but-never-observed entries first: an owner
            # that pushes a few tasks and never get()/wait()s their refs
            # must not pin the leased worker and its shares forever.
            self._expire_direct_outstanding(now)
            with self._direct_lock:
                if self._direct_outstanding:
                    # Pushed work may still be running on a leased worker;
                    # releasing now would idle that worker into the queued
                    # dispatch pool mid-task.
                    continue
                for key, lease in list(self._direct_leases.items()):
                    if lease.get("acquiring"):
                        continue
                    if now - lease["last_used"] > idle_s:
                        to_release.append(lease)
                        del self._direct_leases[key]
            for lease in to_release:
                self._release_lease(lease)

    def _expire_direct_outstanding(self, now: float) -> None:
        """Drop outstanding direct refs older than DIRECT_STALE_S that the
        owner never observed — but ONLY once the GCS confirms the result
        (or its error blob) was actually produced. Age alone cannot
        distinguish an unfetched completed task from a long-running one,
        and treating a running task as stale would let the janitor release
        its lease (and the node shares it occupies) mid-execution."""
        if now - self._direct_expire_last < 5.0:
            return  # throttle BEFORE the scan: this runs per submit when
            #         the outstanding window is full
        with self._direct_lock:
            stale = [rid for rid, t in self._direct_outstanding.items()
                     if now - t > DIRECT_STALE_S]
        if not stale:
            return
        self._direct_expire_last = now
        try:
            resp = self.gcs.call({"type": "locations_batch",
                                  "object_ids": stale}, timeout=5.0)
        except Exception:  # noqa: BLE001 - GCS unreachable: keep entries
            return
        produced = resp.get("objects", {})
        if not produced:
            return
        with self._direct_lock:
            for rid in stale:
                if rid in produced:
                    self._direct_outstanding.pop(rid, None)

    def _release_lease(self, lease: Dict) -> None:
        try:
            lease["client"].call({"type": "release_lease",
                                  "lease_id": lease["lease_id"]},
                                 timeout=10.0)
        except Exception:  # noqa: BLE001 - node died: GCS reaps its shares
            pass

    def _direct_observed(self, oid: bytes) -> None:
        """A result arrived: shrink the outstanding window."""
        if self._direct_outstanding:
            with self._direct_lock:
                self._direct_outstanding.pop(oid, None)

    def _release_all_leases(self) -> None:
        with self._direct_lock:
            leases, self._direct_leases = \
                list(self._direct_leases.values()), {}
        for lease in leases:
            if not lease.get("acquiring"):
                self._release_lease(lease)

    # ------------------------------------------------------ placement groups
    def create_placement_group(self, pg_id: bytes, bundles, strategy: str,
                               name: str = "") -> None:
        """Register the group with the GCS; gang admission is async (the
        GCS admits all bundles atomically when capacity allows)."""
        self._flush_submits()
        resp = self.gcs.call({
            "type": "create_placement_group", "pg_id": pg_id,
            "bundles": bundles, "strategy": strategy, "name": name})
        if not resp.get("ok", True):
            raise ValueError(resp.get("error", "create_placement_group"))

    def remove_placement_group(self, pg_id: bytes) -> None:
        self.gcs.call({"type": "remove_placement_group", "pg_id": pg_id})

    def placement_group_wait(self, pg_id: bytes,
                             timeout: Optional[float] = None) -> bool:
        """Long-poll the GCS until the group is CREATED (or the timeout /
        a terminal REMOVED state)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 30.0 if deadline is None else \
                min(30.0, deadline - time.monotonic())
            if step <= 0:
                return False
            resp = self.gcs.call({"type": "wait_placement_group",
                                  "pg_id": pg_id, "timeout": step},
                                 timeout=step + 30.0)
            if resp.get("created"):
                return True
            if resp.get("state") == "REMOVED" \
                    or not resp.get("known", True):
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False

    def placement_group_table(self) -> Dict[str, Dict]:
        return self.gcs.call({"type": "list_placement_groups"})["groups"]

    # ----------------------------------------------------------------- actors
    def create_actor(self, cls: type, spec: TaskSpec, args, kwargs) -> ActorID:
        self._flush_submits()
        actor_id = spec.actor_id
        methods = tuple(n for n in dir(cls) if not n.startswith("_"))
        fn_id = self._export_fn(cls)
        packed_args = []
        deps: List[bytes] = []
        pins: List[bytes] = []
        for a in args:
            if isinstance(a, ObjectRef):
                packed_args.append(self._pack_ref_arg(a.id.binary(), deps))
            else:
                packed_args.append(self._pack_value(a, pins))
        packed_kwargs = {}
        for key, val in (kwargs or {}).items():
            if isinstance(val, ObjectRef):
                packed_kwargs[key] = self._pack_ref_arg(val.id.binary(), deps)
            else:
                packed_kwargs[key] = self._pack_value(val, pins)
        resources = spec.resources.to_dict()
        self._actor_resources[actor_id.binary()] = resources
        self.gcs.call({
            "type": "create_actor", "actor_id": actor_id.binary(),
            "name": spec.name, "class_name": cls.__name__,
            "module": cls.__module__, "methods": methods,
            "fn_id": fn_id, "args": packed_args, "kwargs": packed_kwargs,
            "deps": deps, "pin_refs": pins,
            "return_ids": [spec.return_ids()[0].binary()],
            "resources": resources,
            "max_restarts": spec.max_restarts,
            "max_concurrency": spec.max_concurrency,
            "is_asyncio": spec.is_asyncio,
        })
        return actor_id

    def _actor_address(self, actor_id: bytes) -> Optional[Tuple[str, int]]:
        info = self.gcs.call({"type": "get_actor", "actor_id": actor_id})
        if info.get("state") == "ALIVE" and info.get("address"):
            addr = tuple(info["address"])
            self._actor_addr_cache[actor_id] = addr
            return addr
        return self._actor_addr_cache.get(actor_id) \
            if info.get("state") != "DEAD" else None

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        self._flush_submits()
        actor_id = spec.actor_id.binary()
        args, kwargs, deps, pins = self._pack_args(spec)
        return_ids = [oid.binary() for oid in spec.return_ids()]
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        msg = {
            "type": "actor_call", "actor_id": actor_id,
            "method": spec.function.qualname,
            "args": args, "kwargs": kwargs, "deps": deps,
            "pin_refs": pins, "return_ids": return_ids,
            "name": spec.function.repr_name,
        }
        # Fast path: the cached address (no GCS round trip per call). Only
        # on a miss/failure do we fall into the resolve loop below.
        cached = self._actor_addr_cache.get(actor_id)
        if cached is not None:
            try:
                self._controller(cached).call(msg)
                return refs
            except (ConnectionError, OSError, TimeoutError, RuntimeError):
                self._actor_addr_cache.pop(actor_id, None)
                self._controllers.pop(cached, None)
        # The actor may be restarting or have moved nodes: resolve its
        # address fresh per attempt (reference: handles learn the new
        # address via the actor pubsub channel). An unreachable home node is
        # reported dead so the GCS starts the restart instead of waiting out
        # the heartbeat timeout.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                info = self.gcs.call({"type": "get_actor",
                                      "actor_id": actor_id, "timeout": 30.0},
                                     timeout=45.0)
            except (ConnectionError, OSError, TimeoutError, RuntimeError):
                break
            state = info.get("state")
            if state == "DEAD":
                break
            if state != "ALIVE" or not info.get("address"):
                time.sleep(0.1)     # still PENDING/RESTARTING past the wait
                continue
            addr = tuple(info["address"])
            self._actor_addr_cache[actor_id] = addr
            try:
                self._controller(addr).call(msg)
                return refs
            except (ConnectionError, OSError, TimeoutError):
                self._actor_addr_cache.pop(actor_id, None)
                self._controllers.pop(addr, None)
                if info.get("node_id"):
                    try:
                        self.gcs.call({"type": "report_node_dead",
                                       "node_id": info["node_id"]})
                    except (ConnectionError, OSError):
                        pass
                time.sleep(0.2)
        self._store_error_blobs(
            return_ids, ActorDiedError(spec.actor_id.hex()[:12])
        )
        return refs

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        addr = self._actor_address(actor_id.binary())
        resources = self._actor_resources.get(actor_id.binary(), {})
        if addr is not None:
            self._controller(addr).call({
                "type": "kill_actor", "actor_id": actor_id.binary(),
                "resources": resources, "no_restart": no_restart,
            })
        if no_restart:
            self.gcs.call({"type": "update_actor",
                           "actor_id": actor_id.binary(),
                           "state": "DEAD", "no_restart": True})
        self._actor_addr_cache.pop(actor_id.binary(), None)

    def get_actor(self, name: str) -> ActorID:
        info = self.gcs.call({"type": "get_actor", "name": name})
        return ActorID(info["actor_id"])

    def actor_class_info(self, actor_id: ActorID):
        info = self.gcs.call({"type": "get_actor",
                              "actor_id": actor_id.binary()})
        return info["class_name"], info["module"], tuple(info["methods"])

    def actor_handle_alive(self, actor_id: ActorID) -> bool:
        info = self.gcs.call({"type": "get_actor",
                              "actor_id": actor_id.binary()})
        return info.get("state") == "ALIVE"

    def _store_error_blobs(self, return_ids: List[bytes], err: BaseException):
        blob = ERR_PREFIX + pickle.dumps(err)
        for oid in return_ids:
            self.put_blob(oid, blob)

    # ---------------------------------------------------------------- objects
    def _put_backpressure(self, nbytes: int) -> None:
        """Owner-side bounded wait while this node's arena is over its
        spill high watermark (reference: plasma client create retries under
        quota pressure). Gives the controller's spiller time to make room;
        never blocks past the configured bound — the store-side spill path
        absorbs what still doesn't fit."""
        if self.local_store is None:
            return
        cfg = self.config
        max_wait = getattr(cfg, "put_backpressure_max_wait_s", 0.0)
        if not getattr(cfg, "object_spill_enabled", False) or max_wait <= 0:
            return
        from .._private.spill import put_backpressure

        waited = put_backpressure(
            self.local_store.stats, nbytes,
            high_watermark=getattr(cfg, "object_spill_high_watermark", 0.85),
            max_wait_s=max_wait)
        if waited > 0.05:
            # Lifecycle event (throttled): this owner is being held back by
            # arena pressure — the forensic breadcrumb for "why did puts
            # slow down at 14:03".
            now = time.monotonic()
            if now - self._bp_event_last > 5.0:
                self._bp_event_last = now
                try:
                    self.gcs.send_oneway({
                        "type": "log_event", "kind": "backpressure_engaged",
                        "role": self.role, "waited_s": round(waited, 3),
                        "nbytes": nbytes})
                except (ConnectionError, OSError):
                    pass

    def arena_admits(self, nbytes: int) -> bool:
        """Whether a direct (zero-copy) arena write of ``nbytes`` stays
        under the spill high watermark. Over it, writers must route through
        the controller so pressure lands on the spiller (which preserves
        bytes on disk) instead of the native evictor (which drops them)."""
        if self.local_store is None:
            return False
        if not getattr(self.config, "object_spill_enabled", False):
            return True
        try:
            st = self.local_store.stats()
        except Exception:  # noqa: BLE001 - stats must never fail a put
            return True
        cap = st.get("capacity") or st.get("arena_bytes") or 0
        high = getattr(self.config, "object_spill_high_watermark", 0.85)
        return cap <= 0 or st.get("used_bytes", 0) + nbytes <= cap * high

    def put_blob(self, oid: bytes, blob: bytes) -> None:
        """Store one serialized blob: straight into the same-host shm arena
        (notifying the controller) when attached, else over RPC. The single
        write path for puts, task results, and error blobs."""
        controller = self._home_controller()
        self._put_backpressure(len(blob))
        if self.local_store is not None and self.arena_admits(len(blob)):
            try:
                self.local_store.put(oid, blob)
                # One-way: the blob is already durable in the arena; the
                # notification only wakes waiters / updates the directory.
                controller.send_oneway({"type": "object_added",
                                        "object_id": oid,
                                        "size": len(blob)})
                return
            except ConnectionError:
                raise
            except Exception:  # noqa: BLE001 - arena full: RPC/overflow path
                pass
        controller.call({"type": "store_object", "object_id": oid,
                         "blob": blob, "owner": self.worker_uid})

    def put(self, value: Any) -> ObjectRef:
        ctx = ensure_context(self)
        oid = ObjectID.for_put(ctx.current_task_id, next(ctx.put_counter))
        sobj = self._ser.serialize(value)
        self._report_contained(oid.binary(), sobj.contained_refs)
        if not sobj.contained_refs:
            self._inline_ok.add(oid.binary())
            self._inline_ok_order.append(oid.binary())
            while len(self._inline_ok_order) > 65536:
                self._inline_ok.discard(self._inline_ok_order.popleft())
        controller = self._home_controller()
        if self.local_store is not None:
            # Serialize straight into a created arena slot (plasma
            # create/seal), skipping the intermediate flat bytes copy.
            size = 1 + sobj.framed_size()
            try:
                # Over the high watermark the direct write is skipped and
                # put_blob below takes over (backpressure wait + the
                # controller spill-to-make-room route) instead of the
                # native evictor dropping cold objects.
                view = (self.local_store.create(oid.binary(), size)
                        if self.arena_admits(size) else None)
            except Exception:  # noqa: BLE001 - arena full etc.
                view = None
            if view is not None:
                view[0:1] = VAL_PREFIX
                sobj.write_into(view[1:])
                self.local_store.seal(oid.binary())
                controller.send_oneway({"type": "object_added",
                                        "object_id": oid.binary(),
                                        "size": size})
                return ObjectRef(oid)
        self.put_blob(oid.binary(), VAL_PREFIX + sobj.to_bytes())
        return ObjectRef(oid)

    def _transfer_client(self):
        """Lazy native data-plane client (reference: object manager Pull).
        Bound to this host's arena when one is attached, else buffer mode."""
        if self._transfer_cli is False:  # probed and unavailable
            return None
        if self._transfer_cli is None:
            try:
                from .._native.transfer import TransferClient

                store_name = os.environ.get("RAY_TPU_STORE_NAME") or None
                if store_name is None and self.local_store is not None:
                    store_name = getattr(self.local_store, "name", None)
                self._transfer_cli = TransferClient(store_name)
                self._transfer_has_store = store_name is not None
            except Exception:  # noqa: BLE001
                self._transfer_cli = False
                return None
        return self._transfer_cli

    def _native_fetch(self, taddr, oid: bytes) -> Optional[bytes]:
        cli = self._transfer_client()
        if cli is None or not taddr or not taddr[1]:
            return None
        host, port = taddr[0], int(taddr[1])
        try:
            if self._transfer_has_store and self.local_store is not None:
                if cli.fetch_into_store(host, port, oid):
                    return self.local_store.get_bytes(oid)
                return None
            return cli.fetch_bytes(host, port, oid)
        except Exception:  # noqa: BLE001
            return None

    def _fetch_from(self, oid: bytes, addresses, transfer) -> Optional[bytes]:
        """Fetch one blob given directory addresses: native plane first
        (bulk bytes move C-to-C, GIL released), RPC fallback."""
        for i, addr in enumerate(addresses):
            blob = self._native_fetch(
                transfer[i] if i < len(transfer) else None, oid)
            if blob is not None:
                if not self._transfer_has_store:
                    self._cache_blob(oid, blob)
                return blob
            try:
                fetched = self._controller(tuple(addr)).call(
                    {"type": "fetch_object", "object_id": oid}
                )
                blob = fetched["blob"]
                self._cache_blob(oid, blob)
                return blob
            except (RuntimeError, ConnectionError, TimeoutError):
                continue
        return None

    def _fetch_many(self, infos: Dict[bytes, dict]) -> Dict[bytes, bytes]:
        """Fetch a set of located blobs, coalescing per-node fetch_batch
        RPCs (one reply carries a whole completion wave of small results);
        anything the batch misses — evicted, oversized reply cap, node
        error — falls back to the per-oid path, which also serves the
        native zero-copy plane."""
        out: Dict[bytes, bytes] = {}
        by_addr: Dict[tuple, list] = {}
        for oid, info in infos.items():
            # Inline small result carried in the directory response: the
            # bytes are already in hand, no node round trip needed.
            blob = info.get("inline_blob")
            if blob is not None:
                out[oid] = blob
                self._count_result("inline_push")
                continue
            # Same-host results live in the shared shm arena already — a
            # direct read beats ANY fetch RPC (measured: the 5k-fan-out
            # client previously round-tripped fetch_batch to its own
            # controller for blobs sitting in its own arena).
            blob = self._local_blob(oid)
            if blob is not None:
                out[oid] = blob
                continue
            addrs = info.get("addresses", [])
            if addrs:
                by_addr.setdefault(tuple(addrs[0]), []).append(oid)
        for addr, oids in by_addr.items():
            for i in range(0, len(oids), 1024):
                chunk = oids[i:i + 1024]
                try:
                    resp = self._controller(addr).call(
                        {"type": "fetch_batch", "object_ids": chunk},
                        timeout=60.0)
                except (RuntimeError, ConnectionError, TimeoutError):
                    continue
                fetched = resp.get("blobs", {})
                self._count_result("fetch_rpc", len(fetched))
                for oid, blob in fetched.items():
                    out[oid] = blob
                    self._cache_blob(oid, blob)
        for oid, info in infos.items():
            if oid in out:
                continue
            blob = self._fetch_from(
                oid, info.get("addresses", []),
                info.get("transfer_addresses", []))
            if blob is not None:
                out[oid] = blob
                self._count_result("fetch_rpc")
        return out

    def _fetch_blob(self, oid: bytes, timeout: Optional[float]) -> bytes:
        if self._ring_active():
            self._ring_harvest()  # drain into the caches checked below
        if self.local_store is not None:
            blob = self.local_store.get_bytes(oid)
            if blob is not None:
                return blob
        cached = self._blob_cache.get(oid)
        if cached is not None:
            return cached
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 5.0 if deadline is None else min(5.0, deadline - time.monotonic())
            if step <= 0:
                raise GetTimeoutError(f"object {oid.hex()[:16]} not ready")
            if self._ring_active():
                self._ring_harvest()
            blob = self._local_blob(oid)
            if blob is not None:
                return blob
            infos = self._owner_pointer_fetch([oid])
            if infos:
                blob = self._fetch_many(infos).get(oid)
                if blob is not None:
                    return blob
            resp = self.gcs.call({
                "type": "get_object_locations", "object_id": oid,
                "wait": True, "timeout": step,
            }, timeout=step + 30.0)
            if resp.get("error_blob") is not None:
                # Terminal task failure recorded in the GCS task table
                # (retries exhausted / cancelled): no node holds a copy.
                return resp["error_blob"]
            if resp.get("inline_blob") is not None:
                # Small result carried inline by the directory itself.
                self._count_result("inline_push")
                return resp["inline_blob"]
            blob = self._fetch_from(
                oid, resp.get("addresses", []),
                resp.get("transfer_addresses", []))
            if blob is not None:
                return blob

    def _cache_blob(self, oid: bytes, blob: bytes):
        self._blob_cache[oid] = blob
        self._blob_cache_order.append(oid)
        while len(self._blob_cache_order) > 4096:
            old = self._blob_cache_order.popleft()
            self._blob_cache.pop(old, None)

    def _blob_value(self, blob: bytes) -> Any:
        if blob[:1] == ERR_PREFIX:
            raise pickle.loads(blob[1:])
        return self._ser.deserialize(SerializedObject.from_bytes(blob[1:]))

    def get_blob_value(self, oid: bytes, timeout: Optional[float] = None) -> Any:
        self._flush_submits()
        return self._blob_value(self._fetch_blob(oid, timeout))

    def _local_blob(self, oid: bytes) -> Optional[bytes]:
        if self.local_store is not None:
            blob = self.local_store.get_bytes(oid)
            if blob is not None:
                return blob
        blob = self._blob_cache.get(oid)
        if blob is None and self._owner_table is not None:
            # Owner-published inline result whose ring republish was
            # missed (ring full/disabled): the table itself holds bytes.
            blob = self._owner_table.get_blob(oid)
        return blob

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        """Batched get: one locations_batch poll covers every still-missing
        ref per cycle instead of a blocking directory round trip per ref."""
        self._flush_submits()
        oids = [r.id.binary() for r in refs]
        blobs: Dict[bytes, bytes] = {}
        pending = set(oids)
        deadline = None if timeout is None else time.monotonic() + timeout
        first = True
        last_probe = 0.0
        # Sampled-task fetch spans: arrival of a traced oid closes its
        # driver_fetch span (wait start = this get()'s entry). `traced`
        # empty => one falsy check per arrival, nothing else.
        traced = ({o for o in pending if o in self._trace_by_oid}
                  if self._trace_by_oid else None)
        t_get = time.monotonic() if traced else 0.0

        def _trace_note(oid, via=""):
            traced.discard(oid)
            ent = self._trace_by_oid.pop(oid, None)
            if ent is not None:
                self.record_trace_span(ent[0], ent[1], "driver_fetch",
                                       t_get, time.monotonic(), via=via)

        def _resolve(oid, blob, via=""):
            blobs[oid] = blob
            pending.discard(oid)
            self._direct_observed(oid)
            if traced:
                _trace_note(oid, via)
        ring_hot = False  # ring delivered on the previous cycle
        while pending:
            t0 = time.perf_counter()
            n0 = len(pending)
            store = self.local_store
            ring_on = self._ring_active()
            if ring_on:
                # Result data plane: O(completions-this-wave) ring pops —
                # each record names a sealed (or inline-carried) result,
                # so nothing is scanned and small results need no arena.
                got = self._ring_harvest(pending)
                for oid, blob, via in got:
                    _resolve(oid, blob, via)
                ring_hot = bool(got)
                ring_on = self._ring_active()
            if first or not ring_on:
                # No ring (kill switch / degraded / non-owner results):
                # the full local scan per wake is INTENTIONAL on this
                # path: same-host workers deposit results into the shared
                # arena ahead of the (batched) directory registration, so
                # each long-poll wake harvests the whole arena backlog,
                # not just the registered slice. Two A/Bs confirmed:
                # restricting to direct-push oids measured 14% WORSE warm
                # throughput (CLUSTER_LAT.json 1785482430 vs 1785482520),
                # and a frontier window with a 512-miss cutoff measured
                # 11% worse — a starved scan just shifts the load onto
                # extra directory long-polls. (With the ring carrying the
                # common path, the scan runs once, on entry, to pick up
                # results that landed before this get().)
                if store is not None and hasattr(store, "get_bytes_many"):
                    for oid, blob in store.get_bytes_many(
                            list(pending)).items():
                        _resolve(oid, blob)
                    if self._blob_cache and pending:
                        for oid in list(pending):
                            blob = self._blob_cache.get(oid)
                            if blob is not None:
                                _resolve(oid, blob)
                else:
                    for oid in list(pending):
                        blob = self._local_blob(oid)
                        if blob is not None:
                            _resolve(oid, blob)
            elif pending and not ring_hot:
                # Ring active but quiet this cycle: results can still
                # arrive via other paths (controller-stored blobs, another
                # thread's fetch) — a cache sweep costs dict lookups, not
                # arena syscalls. Skipped while the ring is delivering:
                # with per-record wakeups an O(pending) sweep per cycle
                # would be quadratic over a fan-out.
                for oid in list(pending):
                    blob = self._blob_cache.get(oid)
                    if blob is None and oid in self._ring_ready:
                        self._ring_ready.discard(oid)
                        blob = (store.get_bytes(oid)
                                if store is not None else None)
                        if blob is not None:
                            _resolve(oid, blob, "ring")
                            continue
                    if blob is not None:
                        _resolve(oid, blob)
            self._phase_add("driver_fetch", time.perf_counter() - t0,
                            n0 - len(pending))
            if not pending:
                break
            # LONG-POLL: the GCS parks until one of the requested objects
            # lands (or the window closes) instead of us sleeping and
            # re-asking — at large fan-outs the 50 Hz re-scan of every
            # pending oid dominated GCS CPU. First cycle asks with no wait
            # so an all-ready get never blocks.
            wait_s = 0.0 if first else 1.0
            if len(pending) <= 4 and (ring_on or store is not None) and (
                    not first or all(o in self._direct_outstanding
                                     for o in pending)):
                # Small-get fast path: the result hits the same-host data
                # plane a full worker->controller->GCS->driver chain
                # BEFORE the directory can wake our long-poll — a ~2 ms
                # spin (ring pops when active, else an arena probe) shaves
                # that tail off every serial round trip (A/B'd: removing
                # it measured p50 1.02 ms vs 0.85 ms with it). On the
                # FIRST cycle it only runs when every ref was
                # direct-pushed (the result is expected imminently; the
                # wait_s=0 directory poll would be a wasted round trip).
                spin_end = time.monotonic() + 0.002
                while pending and time.monotonic() < spin_end:
                    if ring_on:
                        for oid, blob, via in self._ring_harvest(pending):
                            _resolve(oid, blob, via)
                        ring_on = self._ring_active()
                    if pending and store is not None:
                        for oid, blob in store.get_bytes_many(
                                list(pending)).items():
                            _resolve(oid, blob)
                    if pending:
                        time.sleep(0.0001)
                if not pending:
                    break
            was_first = first
            first = False
            if not was_first and ring_on and self._ring_wait(
                    0.025 if ring_hot else 0.002, deadline):
                # Ring-first wait paid off: records landed — loop back to
                # harvest them without a directory round trip. The long-
                # poll below only runs once the ring goes quiet (~25 ms
                # while it is delivering, ~2 ms when results are arriving
                # some other way, e.g. cross-host), so the GCS stops
                # building per-wave wake responses for results the ring
                # already delivered.
                continue
            if deadline is not None:
                wait_s = max(0.0, min(wait_s,
                                      deadline - time.monotonic()))
            # Probe lineage recovery at most every 2 s (not per wake): a
            # lost object must be re-driven even while OTHER objects keep
            # completing, but the O(pending) probe can't run per tick.
            now = time.monotonic()
            probe = now - last_probe >= 2.0
            if probe:
                last_probe = now
            # Poll the completion FRONTIER, not the whole pending set: the
            # oldest 1024 unfinished refs in submission order. get() needs
            # every object anyway, so a window only shapes discovery order
            # while capping both the request encode and the GCS park cost
            # at O(window) instead of O(pending) (measured: 5k-oid polls
            # dominated GCS cycles at fan-out).
            ask, seen = [], set()
            for oid in oids:
                if oid in pending and oid not in seen:
                    seen.add(oid)
                    ask.append(oid)
                    if len(ask) >= 1024:
                        break
            infos = self._owner_pointer_fetch(ask)
            if infos:
                # Address-only owner-table pointers (ring record lost):
                # fetch straight from the holding node — the directory
                # has no row for owner-tracked results.
                t0 = time.perf_counter()
                fetched = self._fetch_many(infos)
                for oid, blob in fetched.items():
                    _resolve(oid, blob, "owner")
                self._phase_add("driver_fetch", time.perf_counter() - t0,
                                len(fetched))
                if not pending:
                    break
                ask = [o for o in ask if o in pending]
                if not ask:
                    continue  # window fully owner-served: refill it
            resp = self.gcs.call(
                {"type": "locations_batch", "object_ids": ask,
                 "wait_s": wait_s, "probe": probe,
                 # Wave coalescing only pays off for fan-outs; a small
                 # get() keeps the first-landing wake (serial latency).
                 "wave_s": 0.004 if len(pending) > 64 else 0.0},
                timeout=wait_s + 30.0)
            n_before = len(pending)
            to_fetch = {}
            n_push = 0
            for oid, info in resp.get("objects", {}).items():
                if info.get("error_blob") is not None:
                    blobs[oid] = info["error_blob"]
                    pending.discard(oid)
                    if traced:
                        _trace_note(oid)
                    continue
                blob = info.get("inline_blob")
                if blob is not None:
                    # Inline small result pushed WITH the completion (the
                    # GCS carried the bytes): no fetch RPC at all — this
                    # is how cross-host owners ride the new data plane.
                    if oid in pending:
                        _resolve(oid, blob, "inline_push")
                        n_push += 1
                    continue
                to_fetch[oid] = info
            self._count_result("inline_push", n_push)
            t0 = time.perf_counter()
            fetched = self._fetch_many(to_fetch)
            for oid, blob in fetched.items():
                blobs[oid] = blob
                pending.discard(oid)
                self._direct_observed(oid)
                if traced:
                    _trace_note(oid, "rpc")
            if to_fetch or n_push:
                # inline_push arrivals count as (zero-cost) fetches so the
                # driver_fetch phase cell still reflects every delivery.
                self._phase_add("driver_fetch", time.perf_counter() - t0,
                                len(fetched) + n_push)
            if not pending:
                break
            progressed = len(pending) < n_before
            if deadline is not None and time.monotonic() >= deadline:
                some = next(iter(pending))
                raise GetTimeoutError(
                    f"{len(pending)} objects not ready "
                    f"(e.g. {some.hex()[:16]})")
            if resp.get("objects") and not progressed:
                # Located but unfetchable (holder died / blob evicted
                # before the directory caught up): the long-poll returns
                # instantly on the stale location, so back off here or
                # this loop hot-spins connection attempts until the
                # heartbeat reaper updates the directory.
                time.sleep(0.05)
        t0 = time.perf_counter()
        values: Dict[bytes, Any] = {}
        out = []
        for oid in oids:
            if oid not in values:
                values[oid] = self._blob_value(blobs[oid])
            out.append(values[oid])
        self._phase_add("driver_fetch", time.perf_counter() - t0, 0)
        return out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self._flush_submits()
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = {r.id.binary(): r for r in refs}
        ready: set = set()
        last_probe = 0.0
        while True:
            if self._ring_active():
                # Drain completions into the caches _local_blob consults
                # (inline payloads -> blob cache; slot records are covered
                # by the arena probe itself).
                self._ring_harvest()
            unknown = []
            for oid in list(pending):
                if oid in ready:
                    continue
                if self._local_blob(oid) is not None:
                    ready.add(oid)
                    self._direct_observed(oid)
                    continue
                if self._owner_table is not None \
                        and self._owner_table.locate(oid) is not None:
                    # Owner-tracked pointer: the bytes are one node fetch
                    # away, which is as ready as a directory location.
                    ready.add(oid)
                    self._direct_observed(oid)
                    continue
                unknown.append(oid)
            if unknown:
                # Long-poll only once nothing new is ready this cycle and
                # more readies are still needed (same rationale as get()).
                wait_s = 0.5 if len(ready) < num_returns else 0.0
                if deadline is not None:
                    wait_s = max(0.0, min(wait_s,
                                          deadline - time.monotonic()))
                now = time.monotonic()
                probe = now - last_probe >= 2.0
                if probe:
                    last_probe = now
                resp = self.gcs.call(
                    {"type": "locations_batch", "object_ids": unknown,
                     "wait_s": wait_s, "probe": probe},
                    timeout=wait_s + 30.0)
                ready.update(resp.get("objects", {}).keys())
            expired = deadline is not None and time.monotonic() >= deadline
            if len(ready) >= num_returns or expired:
                # at most num_returns in the ready list, input order preserved
                out_ready = [r for r in refs if r.id.binary() in ready]
                out_ready = out_ready[:num_returns]
                taken = {r.id.binary() for r in out_ready}
                out_rest = [r for r in refs if r.id.binary() not in taken]
                return out_ready, out_rest

    def as_future(self, ref: ObjectRef):
        """Future that resolves when the object lands — via ONE shared
        resolver thread batch-long-polling the directory for every
        outstanding future, not a thread per ref: an async ingress with N
        in-flight requests costs one poll connection, not N threads."""
        from concurrent.futures import Future

        self._flush_submits()   # the producing task may still be buffered
        fut: Future = Future()
        oid = ref.id.binary()
        blob = self._local_blob(oid)
        if blob is not None:
            self._resolve_future(fut, blob)
            self._direct_observed(oid)
            return fut
        with self._future_lock:
            self._future_waiters.setdefault(oid, []).append(fut)
            if self._future_thread is None \
                    or not self._future_thread.is_alive():
                self._future_thread = threading.Thread(
                    target=self._future_resolver_loop, daemon=True,
                    name="future-resolver")
                self._future_thread.start()
        self._future_event.set()
        return fut

    def _resolve_future(self, fut, blob: bytes) -> None:
        """Settle one future; tolerant of caller-side cancellation (e.g.
        asyncio.wait_for timing out wrap_future) — an InvalidStateError
        here must never escape into the SHARED resolver thread, where it
        would strand every other outstanding future."""
        try:
            value, exc = self._blob_value(blob), None
        except BaseException as e:  # noqa: BLE001 - error blob -> exception
            value, exc = None, e
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except Exception:  # noqa: BLE001 - cancelled/already settled
            pass

    def _future_resolver_loop(self) -> None:
        while not self._ref_shutdown.is_set():
            try:
                self._future_resolver_tick()
            except Exception:  # noqa: BLE001 - resolver must survive
                import traceback

                traceback.print_exc()
                time.sleep(0.5)

    def _future_resolver_tick(self) -> None:
        with self._future_lock:
            # Prune futures the caller abandoned (cancelled): their oids
            # must not pin the poll set forever.
            for oid in list(self._future_waiters):
                live = [f for f in self._future_waiters[oid]
                        if not f.cancelled()]
                if live:
                    self._future_waiters[oid] = live
                else:
                    del self._future_waiters[oid]
            pending = dict(self._future_waiters)
        if not pending:
            self._future_event.wait(timeout=5.0)
            self._future_event.clear()
            return

        settled = 0

        def settle(oid: bytes, blob: bytes) -> None:
            nonlocal settled
            with self._future_lock:
                futs = self._future_waiters.pop(oid, [])
            for f in futs:
                self._resolve_future(f, blob)
            self._direct_observed(oid)
            settled += 1

        if self._ring_active():
            self._ring_harvest()  # inline results land in the blob cache
        for oid in list(pending):
            blob = self._local_blob(oid)
            if blob is not None:
                settle(oid, blob)
                del pending[oid]
        if not pending:
            return
        now = time.monotonic()
        probe = now - self._future_probe_last >= 2.0
        if probe:
            self._future_probe_last = now
        try:
            # wait_s = 0.25, not 1.0: a future registered AFTER this poll
            # started is invisible to it (the park cannot be interrupted),
            # so the window is the worst-case added latency for every new
            # request — 4 idle RPCs/s while futures are outstanding buys a
            # 250 ms tail bound.
            resp = self.gcs.call(
                {"type": "locations_batch",
                 "object_ids": list(pending), "wait_s": 0.25,
                 "probe": probe}, timeout=31.0)
        except (ConnectionError, OSError):
            time.sleep(0.5)
            return
        before_rpc = settled
        to_fetch = {}
        for oid, info in resp.get("objects", {}).items():
            if info.get("error_blob") is not None:
                settle(oid, info["error_blob"])
                continue
            to_fetch[oid] = info
        for oid, blob in self._fetch_many(to_fetch).items():
            settle(oid, blob)
        if resp.get("objects") and settled == before_rpc:
            # Located but unfetchable (dead holder / evicted blob): the
            # long-poll returns instantly on the stale location — back off
            # or this loop hot-spins until the reaper fixes the directory.
            # Compared per-RPC (not tick-wide): pre-RPC local settles must
            # not mask the stall (same guard as get()'s progressed flag).
            time.sleep(0.05)

    def free(self, refs: Sequence[ObjectRef]) -> None:
        """Eagerly delete objects cluster-wide: the GCS drops directory
        entries + lineage (no reconstruction) and tells holder nodes to
        evict (reference: ray.internal.free -> FreeObjects broadcast)."""
        self._flush_submits()
        oids = [r.id.binary() for r in refs]
        for oid in oids:
            self._blob_cache.pop(oid, None)
        if self._owner_table is not None:
            self._owner_table.discard(oids)
        try:
            self.gcs.call({"type": "free_objects", "object_ids": oids})
        except (ConnectionError, OSError):
            pass

    def cancel(self, ref: ObjectRef, force: bool = False):
        """Cancel the task producing ``ref`` (reference:
        core_worker.h:588-595): queued tasks fail immediately at the GCS,
        dispatched ones are interrupted on their node."""
        self._flush_submits()
        self.gcs.call({"type": "cancel_task",
                       "object_id": ref.id.binary(), "force": force})

    # ------------------------------------------------------------------ state
    def cluster_resources(self) -> Dict[str, float]:
        return self.gcs.call({"type": "cluster_resources"})["total"]

    def available_resources(self) -> Dict[str, float]:
        return self.gcs.call({"type": "cluster_resources"})["available"]

    def nodes(self) -> List[Dict[str, Any]]:
        return self.gcs.call({"type": "list_nodes"})["nodes"]

    def actors(self) -> Dict[str, Dict[str, Any]]:
        raw = self.gcs.call({"type": "list_actors"})["actors"]
        return {
            aid.hex(): {"ActorID": aid.hex(), "State": info["state"],
                        "Name": info.get("name")}
            for aid, info in raw.items()
        }

    def flush_events(self) -> int:
        """Push locally recorded profile spans to the GCS profile table
        (reference: core_worker/profiling.cc batched flush). Returns count.

        Spans are recorded in time.monotonic() (exact durations) but each
        process has its own monotonic epoch — cross-machine lanes would be
        hours apart. Anchor to wall clock here: the offset is constant per
        process, so durations stay exact while epochs become comparable."""
        offset = time.time() - time.monotonic()
        batch = []
        while self.events.events:
            try:
                kind, name, start, end, extra = self.events.events.popleft()
            except IndexError:
                break
            batch.append({
                "cat": kind, "name": name,
                "start": start + offset, "end": end + offset,
                "extra": {k: v for k, v in extra.items()
                          if isinstance(v, (str, int, float, bool))},
                "origin": self.role,
            })
            if len(batch) >= 10_000:
                break
        if batch:
            try:
                self.gcs.call({"type": "add_profile_data", "events": batch})
            except (ConnectionError, OSError):
                return 0
        self.flush_traces()
        return len(batch)

    def flush_traces(self) -> int:
        """Push buffered per-task trace spans to the GCS trace table."""
        with self._trace_span_lock:
            spans, self.trace_spans = self.trace_spans, []
        if not spans:
            return 0
        try:
            for i in range(0, len(spans), 10_000):
                self.gcs.send_oneway({"type": "add_trace_data",
                                      "spans": spans[i:i + 10_000]})
        except (ConnectionError, OSError):
            return 0
        return len(spans)

    def cluster_profile_events(self, limit: Optional[int] = None):
        msg = {"type": "get_profile_data"}
        if limit:
            msg["limit"] = int(limit)
        return self.gcs.call(msg)["events"]

    def cluster_trace_spans(self, limit: Optional[int] = None):
        """Raw phase spans from the GCS trace table (this process's own
        buffered spans are flushed first so a fresh submit is visible)."""
        self.flush_traces()
        msg = {"type": "get_trace_data"}
        if limit:
            msg["limit"] = int(limit)
        return self.gcs.call(msg)["spans"]

    def cluster_timeseries(self, last: Optional[int] = 60,
                           names: Optional[list] = None) -> Dict[str, Any]:
        """Rollup snapshot from the GCS time-series store (`cli top`,
        dashboard sparklines): {bucket_s, series, driver_totals, ...}."""
        msg: Dict[str, Any] = {"type": "get_timeseries"}
        if last:
            msg["last"] = int(last)
        if names:
            msg["names"] = list(names)
        return self.gcs.call(msg)

    def cluster_profile_stacks(self, component: Optional[str] = None):
        """Cumulative flight-recorder folded-stack counts per component."""
        msg: Dict[str, Any] = {"type": "get_profile_stacks"}
        if component:
            msg["component"] = component
        return self.gcs.call(msg)["components"]

    def cluster_events(self, limit: Optional[int] = None,
                       kind: Optional[str] = None):
        """Structured lifecycle events from the GCS cluster event log."""
        return self.cluster_events_page(limit=limit, kind=kind)["events"]

    def cluster_events_page(self, limit: Optional[int] = None,
                            kind: Optional[str] = None,
                            after_seq: Optional[int] = None
                            ) -> Dict[str, Any]:
        """Full event-log response (events + drop accounting + seq
        cursors). ``after_seq`` makes it a tail read: only events newer
        than the cursor come back (`cli events --follow`)."""
        msg: Dict[str, Any] = {"type": "get_events"}
        if limit:
            msg["limit"] = int(limit)
        if kind:
            msg["kind"] = kind
        if after_seq is not None:
            msg["after_seq"] = int(after_seq)
        return self.gcs.call(msg)

    # ------------------------------------------------------ state API v2
    def list_tasks(self, state: Optional[str] = None,
                   kind: Optional[str] = None,
                   node_id: Optional[str] = None,
                   reason: Optional[str] = None,
                   name_contains: Optional[str] = None,
                   limit: int = 1000, offset: int = 0) -> Dict[str, Any]:
        """Bounded/filterable/paginated query over the GCS task table:
        {tasks, total, truncated}."""
        msg: Dict[str, Any] = {"type": "list_tasks",
                               "limit": int(limit), "offset": int(offset)}
        for key, val in (("state", state), ("kind", kind),
                         ("node_id", node_id), ("reason", reason),
                         ("name_contains", name_contains)):
            if val:
                msg[key] = val
        return self.gcs.call(msg)

    def task_summary(self) -> Dict[str, Any]:
        """Per-state/kind/pending-reason counts over the GCS task table."""
        return self.gcs.call({"type": "task_summary"})

    def get_task(self, task_id: str) -> Dict[str, Any]:
        """One task's full record by id (hex prefix accepted)."""
        return self.gcs.call({"type": "get_task", "task_id": task_id})

    def run_audit(self, verify: bool = True,
                  timeout: float = 120.0) -> Dict[str, Any]:
        """On-demand GCS consistency audit: {findings, summary}."""
        return self.gcs.call({"type": "run_audit", "verify": verify},
                             timeout=timeout)

    def list_jobs(self) -> Dict[str, Any]:
        """Per-job rollup over the GCS task table: {jobs: [...]}, each
        row task/state counts, submit/finish bounds, and — for jobs the
        profiler tick already analyzed — efficiency figures."""
        return self.gcs.call({"type": "list_jobs"})

    def job_profile(self, job_id: Optional[str] = None,
                    include_rows: bool = False,
                    timeout: float = 120.0) -> Dict[str, Any]:
        """Critical-path profile of one job (hex prefix accepted;
        omitted = the only job): {profile, rows?}. ``include_rows``
        pulls every task row too — the Chrome-trace export's input."""
        msg: Dict[str, Any] = {"type": "job_profile",
                               "include_rows": bool(include_rows)}
        if job_id:
            msg["job_id"] = str(job_id)
        return self.gcs.call(msg, timeout=timeout)

    def shutdown(self):
        self._flush_submits()
        self._release_all_leases()
        self._stats_stop.set()
        if self._stats_thread is not None:
            self._stats_thread.join(timeout=0.5)
            self._stats_thread = None
        from .._private import flight_recorder

        rec = flight_recorder.get()
        if rec is not None and self.role == "driver" \
                and rec.component == "driver":
            # Sampler thread must not outlive the runtime (init()/
            # shutdown() cycles restart it; pinned by tests).
            flight_recorder.stop()
        self._ref_shutdown.set()
        self._ref_dirty.set()  # unblock the flusher so it can exit
        self._flush_refs()
        if self._owner_server is not None:
            try:
                self._owner_server.stop()
            except Exception:  # noqa: BLE001
                pass
            self._owner_server = None
            self._owner_table = None
        # Exiting process drops all its holds (reference: owner death).
        with self._ref_lock:
            held, self._ref_counts = list(self._ref_counts), {}
        if held and self.config.ref_counting_enabled:
            try:
                self.gcs.send_oneway({"type": "ref_update",
                                      "worker": self.worker_uid,
                                      "inc": [], "dec": held})
            except (ConnectionError, OSError):
                pass
        self.flush_events()
        if self._ring:
            self._ring.close()  # owner side unlinks the shm segment
        with self._pub_lock:
            pubs, self._pub_rings = list(self._pub_rings.values()), {}
        for pub in pubs:
            if not isinstance(pub, float):
                pub.close()
        for client in self._controllers.values():
            client.close()
        if self._sub_client is not None:
            self._sub_client.close()
        self.gcs.close()
