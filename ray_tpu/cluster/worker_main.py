"""Worker process entry point.

Reference counterpart: ``python/ray/workers/default_worker.py`` + the
core-worker task execution loop (``core_worker.cc:1421 RunTaskExecutionLoop``).
Connects to its NodeController, registers, then executes pushed tasks:
fetch function blob (cached), resolve args from the local store, run, store
returns, report done. Actor workers keep the instance alive and execute
method calls in arrival order.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

ERR_PREFIX = b"E"
VAL_PREFIX = b"V"


class _Inbox:
    """Task inbox fed by the reader thread's frame batches: ``put_many``
    enqueues a whole batch under ONE lock acquisition + ONE wakeup where
    ``queue.Queue`` pays a mutex round-trip per item. Single consumer
    (serve_loop), single producer (the RpcClient reader thread)."""

    def __init__(self):
        self._d: deque = deque()
        self._cv = threading.Condition()

    def put(self, item: Dict) -> None:
        with self._cv:
            self._d.append(item)
            self._cv.notify()

    def put_many(self, items: List[Dict]) -> None:
        with self._cv:
            self._d.extend(items)
            self._cv.notify()

    # raylint: hotpath — serve_loop blocks here between tasks
    def get(self) -> Dict:
        with self._cv:
            while not self._d:
                self._cv.wait()
            return self._d.popleft()


def main():
    from ray_tpu._private.stack_dump import register_stack_dump

    register_stack_dump()
    parser = argparse.ArgumentParser()
    parser.add_argument("--controller", required=True)
    parser.add_argument("--gcs", required=True)
    args = parser.parse_args()

    chost, cport = args.controller.rsplit(":", 1)
    ghost, gport = args.gcs.rsplit(":", 1)

    from ray_tpu._native import completion_ring as cring
    from ray_tpu._native import open_store
    from ray_tpu._private.serialization import get_context
    from ray_tpu.cluster import wire
    from ray_tpu.cluster.core_worker import ClusterCoreWorker
    from ray_tpu.cluster.protocol import RpcClient
    from ray_tpu.exceptions import TaskError

    inbox = _Inbox()
    # Revocation bookkeeping for pipelined executes (the controller may
    # pre-push a second task into this inbox; if the current task blocks,
    # the controller revokes the queued one and re-dispatches it
    # elsewhere). The reader thread answers revokes OUT OF BAND: it knows
    # exactly which executes are still queued (``inbox_ids``) vs already
    # started, so the ack is authoritative and a revoked task can never
    # also run here (at-most-once preserved).
    revoke_lock = threading.Lock()
    inbox_ids: set = set()
    revoked: set = set()

    def on_push(msg: Dict) -> None:
        mtype = msg.get("type")
        if mtype == "set_trace_sample":
            # Runtime-adjustable sampling (cli trace --sample): the
            # controller rebroadcasts the GCS kv cell; nested submissions
            # from task code sample at the new rate.
            from ray_tpu._private import tracing

            tracing.apply_kv_rate(msg.get("raw"))
            return
        if mtype == "revoke_execute":
            tid = msg.get("task_id")
            with revoke_lock:
                ok = tid in inbox_ids
                if ok:
                    inbox_ids.discard(tid)
                    revoked.add(tid)
            try:
                controller.send_oneway({"type": "revoke_ack",
                                        "pid": os.getpid(),
                                        "task_id": tid, "revoked": ok})
            except (ConnectionError, OSError):
                pass
            return
        if mtype == "execute_task" and msg.get("task_id") is not None:
            with revoke_lock:
                inbox_ids.add(msg["task_id"])
        inbox.put(msg)

    # raylint: hotpath — every pushed task enters the worker through here
    def on_push_batch(msgs: List[Dict]) -> None:
        """Batched inbox feed (native frame pump): one recv wakeup's worth
        of pushes lands in the inbox via ONE put_many. Control messages
        (trace sampling, revokes) keep their per-message handling and
        their order relative to surrounding executes — earlier executes
        are flushed first, so a revoke still sees its target queued."""
        pend: List[Dict] = []
        for msg in msgs:
            mtype = msg.get("type")
            if mtype == "set_trace_sample" or mtype == "revoke_execute":
                if pend:
                    inbox.put_many(pend)
                    pend = []
                on_push(msg)
                continue
            if mtype == "execute_task" and msg.get("task_id") is not None:
                with revoke_lock:
                    inbox_ids.add(msg["task_id"])
            pend.append(msg)
        if pend:
            inbox.put_many(pend)

    # A dead controller connection must terminate the worker (otherwise a
    # SIGKILL'd controller leaves its workers orphaned on inbox.get forever).
    controller = RpcClient(
        chost, int(cport), push_handler=on_push,
        push_batch_handler=on_push_batch,
        on_close=lambda: inbox.put({"type": "shutdown"}),
    )

    # Attach to the node's shared-memory arena: results are written straight
    # into shm and dependencies read from it, no blob bytes on the socket.
    store_name = os.environ.get("RAY_TPU_STORE_NAME", "")
    local_store = open_store(store_name) if store_name else None

    # The worker's own core runtime: nested ray_tpu API calls from task code
    # route through the same cluster machinery.
    core = ClusterCoreWorker(
        (ghost, int(gport)), controller_addr=(chost, int(cport)),
        role="worker",
    )
    core.local_store = local_store
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    worker.core = core
    worker.mode = "worker"
    worker.connected = True

    reg = controller.call(
        {"type": "register_worker", "pid": os.getpid(),
         "wire": 0 if wire.pickle_only() else wire.WIRE_VERSION})
    # The controller's advertised wire version gates the v2 inline-result
    # frames on the task_done path (a v1 controller gets pickle instead).
    peer_wire = int(reg.get("wire") or 1)
    controller.peer_wire = peer_wire
    core._controller((chost, int(cport))).peer_wire = peer_wire

    # Continuous stack sampler: this worker's wall-clock profile, drained
    # to the GCS profile-stacks table on the flush cadence below.
    from ray_tpu._private import flight_recorder, loopmon

    flight_recorder.start("worker")
    cpu_sampler = loopmon.cpu_sampler("worker")

    # Periodic profile-span flush to the GCS (reference: profiling.cc's
    # batched AddProfileData timer).
    def flush_loop():
        import time as _time

        while True:
            _time.sleep(2.0)
            try:
                core.flush_events()
                rec = flight_recorder.get()
                msg = None
                if rec is not None:
                    stacks, stacks_cpu = rec.drain_tagged()
                    if stacks:
                        n = sum(stacks.values())
                        msg = {"type": "add_profile_stacks",
                               "component": rec.component,
                               "samples": n, "stacks": stacks,
                               "stacks_oncpu": stacks_cpu}
                        flight_recorder.flush_metrics(rec, n)
                # Off-CPU truth rides the same flush: per-thread CPU and
                # ctx-switch deltas for the worker process (workers have
                # no asyncio loop — thread coverage IS their observatory).
                if cpu_sampler is not None:
                    tc = cpu_sampler.drain()
                    if tc:
                        tc["component"] = "worker"
                        if msg is None:
                            msg = {"type": "add_profile_stacks",
                                   "component": "worker", "samples": 0,
                                   "stacks": {}}
                        msg["thread_cpu"] = tc
                if msg is not None:
                    core.gcs.send_oneway(msg)
            except Exception:  # noqa: BLE001 - shutdown race
                return

    threading.Thread(target=flush_loop, daemon=True,
                     name="profile-flush").start()

    ser = get_context()
    fn_cache: Dict[bytes, Any] = {}
    actor_instance: Optional[Any] = None
    actor_id: Optional[bytes] = None

    def checkpoint_key(aid: bytes) -> str:
        return "__actor_ckpt:" + aid.hex()

    def maybe_save_checkpoint() -> None:
        """After each method: Checkpointable actors persist state to the GCS
        kv so a restart (possibly on another node) can restore it
        (reference: actor.py:972 + GCS checkpoint RPCs)."""
        inst = actor_instance
        if (inst is None or actor_id is None
                or not hasattr(inst, "should_checkpoint")
                or not hasattr(inst, "save_checkpoint")):
            return
        try:
            if inst.should_checkpoint(None):
                core.gcs.call({
                    "type": "kv_put", "key": checkpoint_key(actor_id),
                    "value": pickle.dumps(inst.save_checkpoint()),
                })
        except Exception:  # noqa: BLE001 - checkpointing is best-effort
            pass

    def maybe_restore_checkpoint(msg) -> None:
        inst = actor_instance
        if (inst is None or not msg.get("restart_count")
                or not hasattr(inst, "load_checkpoint")):
            return
        resp = core.gcs.call({"type": "kv_get",
                              "key": checkpoint_key(msg["actor_id"])})
        if resp.get("value") is not None:
            inst.load_checkpoint(pickle.loads(resp["value"]))

    def load_function(fn_id: bytes):
        fn = fn_cache.get(fn_id)
        if fn is None:
            resp = core.gcs.call({"type": "get_function", "fn_id": fn_id})
            fn = pickle.loads(resp["blob"])
            fn_cache[fn_id] = fn
        return fn

    def resolve_args(msg) -> tuple:
        pos = []
        for kind, payload in msg["args"]:
            if kind == "ref":
                pos.append(core.get_blob_value(payload))
            else:
                pos.append(ser.deserialize(
                    type(ser.serialize(None)).from_bytes(payload)))
        kwargs = {}
        for key, (kind, payload) in msg.get("kwargs", {}).items():
            if kind == "ref":
                kwargs[key] = core.get_blob_value(payload)
            else:
                kwargs[key] = ser.deserialize(
                    type(ser.serialize(None)).from_bytes(payload))
        return pos, kwargs

    # Result blobs written by the CURRENT task, registered with the
    # controller inside the task_done message instead of one object_added
    # oneway each — at fan-out rates the per-result socket write was half
    # the worker->controller traffic. Same connection + same FIFO slot, so
    # the registration-before-finish invariant is unchanged. Keyed per
    # thread: concurrent actor methods (max_concurrency/asyncio) each
    # accumulate their own adds.
    _pending_adds: Dict[int, list] = {}
    # Per-thread [exec_s, reg_s, ts_exec_start, ts_exec_end] for the task
    # being finished: monotonic phase durations (the phase profiler's
    # worker-side samples) plus the wall-clock execution window that the
    # job profiler joins against the GCS submit/dispatch/finish stamps —
    # carried inside task_done on EVERY completion, not just traced ones.
    _phase_times: Dict[int, list] = {}
    # Kill switch (RAY_TPU_EXEC_STAMPS=0): suppress the wall-clock window
    # so completions ride the pre-v7 frames — the operational escape hatch
    # and the "off" arm of the stamping-overhead A/B smoke.
    _exec_stamps_on = os.environ.get("RAY_TPU_EXEC_STAMPS", "1") != "0"

    def _store_blob(oid: bytes, blob: bytes, adds: list) -> None:
        """Result store on the new data plane (see ARCHITECTURE.md
        "Result data plane"):

        * **inline** — results at or under RAY_TPU_INLINE_RESULT_MAX ride
          inside the owner's completion-ring record AND inside this task's
          task_done "added" item, so they never touch an arena slot or a
          fetch RPC: the same-host owner pops them from its ring; everyone
          else gets the bytes carried through the GCS directory;
        * **arena** — bigger results keep the zero-copy arena write with
          DEFERRED registration, plus a slot record into the owner's ring
          (same-host owners then read the arena without scanning it);
        * **RPC** — arena unavailable/full (or over the spill high
          watermark, where the controller route spills cold objects to
          disk instead of the native evictor dropping them).
        """
        if 0 < len(blob) <= cring.inline_result_max() \
                and cring.ring_enabled():
            core.publish_completion(oid, len(blob), inline=blob)
            adds.append([oid, len(blob), blob])
            return
        if core.local_store is not None and core.arena_admits(len(blob)):
            try:
                core.local_store.put(oid, blob)
                core.publish_completion(oid, len(blob))
                adds.append([oid, len(blob)])
                return
            except Exception:  # noqa: BLE001 - arena full: RPC path
                pass
        core.put_blob(oid, blob)

    def _adds_list() -> list:
        """This executor thread's pending "added" registrations, resolved
        ONCE per task (the batched-bookkeeping mirror of the GCS
        completion apply): every return object of a task appends to the
        same list without re-paying the ident lookup + setdefault."""
        return _pending_adds.setdefault(threading.get_ident(), [])

    def store_result(oid: bytes, value: Any, adds: list):
        sobj = ser.serialize(value)
        # Refs returned inside the result stay pinned while it lives.
        core._report_contained(oid, sobj.contained_refs)
        _store_blob(oid, VAL_PREFIX + sobj.to_bytes(), adds)

    def store_error(msg, exc: BaseException):
        if not isinstance(exc, TaskError):
            exc = TaskError(msg.get("name", "task"), exc)
        blob = ERR_PREFIX + pickle.dumps(exc)
        adds = _adds_list()
        for oid in msg["return_ids"]:
            _store_blob(oid, blob, adds)

    def run_returns(msg, result):
        oids = msg["return_ids"]
        adds = _adds_list()
        if len(oids) == 1:
            store_result(oids[0], result, adds)
        else:
            if not isinstance(result, tuple) or len(result) != len(oids):
                raise ValueError(
                    f"expected {len(oids)} returns, got {type(result).__name__}"
                )
            for oid, val in zip(oids, result):
                store_result(oid, val, adds)

    # ---- actor method concurrency -----------------------------------------
    # Cluster/local parity (reference: BoundedExecutor for max_concurrency,
    # direct_actor_transport.h:264, and fibers for asyncio actors,
    # core_worker/fiber.h — mirrored locally by _private/runtime.LocalActor):
    #  * plain actors run inline in this thread — per-caller order is the
    #    controller's FIFO dispatch order;
    #  * max_concurrency > 1 runs methods on a bounded thread pool;
    #  * async actors schedule coroutines on ONE persistent event loop
    #    thread, so concurrent awaits genuinely interleave instead of each
    #    call paying a fresh asyncio.run().
    import asyncio

    actor_pool = None   # ThreadPoolExecutor when max_concurrency > 1
    actor_loop: Optional[asyncio.AbstractEventLoop] = None

    def finish(msg) -> bool:
        """Report task completion; returns False when the controller is gone.

        Sends on the SAME connection the result notifications used (core's
        controller client): TCP FIFO guarantees the controller registers the
        objects before it sees task_done, so the GCS can never mark the task
        FINISHED while its outputs are still unindexed (a lost-object false
        positive that would trigger spurious lineage re-execution). Each
        concurrent executor thread stores then finishes on that one locked
        client, so the invariant holds per task regardless of interleaving.
        """
        try:
            phases = _phase_times.pop(threading.get_ident(), None) \
                or (0.0, 0.0)
            core._controller((chost, int(cport))).send_oneway({
                "type": "task_done",
                "pid": os.getpid(),
                "return_ids": msg.get("return_ids", []),
                # This task's result blobs: registered by the controller
                # BEFORE it processes the finish (same message).
                "added": _pending_adds.pop(threading.get_ident(), []),
                # Phase profiler samples (execution / result-store wall).
                "exec_s": phases[0], "reg_s": phases[1],
                # Wall-clock execution window (job profiler timeline).
                "ts_exec_start": (phases[2] if len(phases) > 2
                                  and _exec_stamps_on else 0.0),
                "ts_exec_end": (phases[3] if len(phases) > 3
                                and _exec_stamps_on else 0.0),
            })
            return True
        except (ConnectionError, OSError):
            inbox.put({"type": "shutdown"})  # main loop exits
            return False

    def complete_actor_method(msg, result=None, error=None,
                              exec_s: float = 0.0,
                              exec_win=(0.0, 0.0)) -> None:
        """Store returns (or the error), checkpoint, report task_done.

        The store->finish pair runs in ONE thread so the TCP FIFO invariant
        documented on finish() holds per task. Shared by the inline, pooled,
        and async execution paths — a fix to error storage or the ordering
        applies to all three at once."""
        t1 = time.monotonic()
        try:
            if error is None:
                run_returns(msg, result)
                maybe_save_checkpoint()
            else:
                store_error(msg, error)
        except BaseException as e:  # noqa: BLE001 - completion errors are data
            try:
                store_error(msg, e)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        finally:
            _phase_times[threading.get_ident()] = \
                [exec_s, time.monotonic() - t1, exec_win[0], exec_win[1]]
            finish(msg)

    def record_span(kind: str, name: str, t0: float,
                    id_key: str, id_val) -> None:
        """Execution span for the timeline lanes (reference: profiling.cc
        task spans). Called from success AND failure paths — a failed
        task's span is exactly what a user debugging a job needs to see."""
        ident = id_val or b""
        core.events.record(
            kind, name, t0, time.monotonic(),
            **{id_key: ident.hex() if isinstance(ident, bytes)
               else str(ident),
               "worker_pid": os.getpid(),
               # Cluster-unique lane key: bare OS pids collide across
               # nodes (containers reuse low pids), which would merge two
               # machines' spans into one timeline lane.
               "lane": f"{core.worker_uid[:8]}:{os.getpid()}"})

    def run_actor_method(msg) -> None:
        """One actor method: resolve, run, complete. Used inline (plain
        actors) and from pool threads (max_concurrency)."""
        t0 = time.monotonic()
        w0 = time.time()
        try:
            method = getattr(actor_instance, msg["method"])
            pos, kwargs = resolve_args(msg)
            result = method(*pos, **kwargs)
            if asyncio.iscoroutine(result):
                result = asyncio.run(result)
        except BaseException as e:  # noqa: BLE001 - task errors are data
            complete_actor_method(msg, error=e,
                                  exec_s=time.monotonic() - t0,
                                  exec_win=(w0, time.time()))
            return
        finally:
            record_span("actor_task", msg.get("method", "method"), t0,
                        "actor_id", msg.get("actor_id"))
        complete_actor_method(msg, result, exec_s=time.monotonic() - t0,
                              exec_win=(w0, time.time()))

    async def run_actor_method_async(msg) -> None:
        """Coroutine twin for the persistent loop: the method's coroutine is
        awaited IN PLACE so batch-mates interleave, while the potentially
        BLOCKING pieces (ref-arg resolution, result store / checkpoint /
        task_done RPCs) run via asyncio.to_thread so they never stall the
        loop and re-serialize the in-flight coroutines."""
        t0 = time.monotonic()
        w0 = time.time()
        try:
            pos, kwargs = await asyncio.to_thread(resolve_args, msg)
            method = getattr(actor_instance, msg["method"])
            result = method(*pos, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
        except BaseException as e:  # noqa: BLE001 - task errors are data
            await asyncio.to_thread(
                complete_actor_method, msg, None, e,
                time.monotonic() - t0, (w0, time.time()))
            return
        finally:
            record_span("actor_task", msg.get("method", "method"), t0,
                        "actor_id", msg.get("actor_id"))
        await asyncio.to_thread(
            complete_actor_method, msg, result, None,
            time.monotonic() - t0, (w0, time.time()))

    # The worker inner loop — one of the flight recorder's top burners, so
    # it is a named, hot-path-linted function: no pickle/json or loud
    # logging may creep into the per-task path (raylint hot-path).
    # raylint: hotpath
    def serve_loop() -> None:
        nonlocal actor_instance, actor_id, actor_loop, actor_pool
        while True:
            msg = inbox.get()
            mtype = msg.get("type")
            if mtype == "shutdown":
                break
            if mtype == "execute_task" and msg.get("task_id") is not None:
                with revoke_lock:
                    inbox_ids.discard(msg["task_id"])
                    if msg["task_id"] in revoked:
                        # Revoked while queued: the controller re-dispatched
                        # it elsewhere; executing here too would double-run
                        # it.
                        revoked.discard(msg["task_id"])
                        continue
            if "_spec" in msg and "args" not in msg:
                # Pickle-relayed opaque spec (mixed-wire path): the header
                # dict carries the encoded blob but not the args — the full
                # decode happens here, at the executing worker, exactly
                # like the binary execute_task frame.
                msg = dict(wire.decode_task_spec(msg["_spec"]), type=mtype)
            if mtype == "execute_actor_task" and actor_instance is not None:
                # Dispatch order == controller FIFO order for all three
                # modes; completion may interleave for async/pooled actors
                # (that is their contract). The concurrent paths own their
                # error handling + task_done, so they bypass the serial
                # finally.
                if actor_loop is not None:
                    asyncio.run_coroutine_threadsafe(
                        run_actor_method_async(msg), actor_loop)
                    continue
                if actor_pool is not None:
                    actor_pool.submit(run_actor_method, msg)
                    continue
                run_actor_method(msg)
                continue
            try:
                if mtype == "execute_task":
                    fn = load_function(msg["fn_id"])
                    pos, kwargs = resolve_args(msg)
                    trace = msg.get("trace")  # sampled task: phase spans
                    t0 = time.monotonic()
                    w0 = time.time()
                    try:
                        result = fn(*pos, **kwargs)
                    finally:
                        _phase_times[threading.get_ident()] = \
                            [time.monotonic() - t0, 0.0, w0, time.time()]
                        record_span("task", getattr(fn, "__name__", "task"),
                                    t0, "task_id", msg.get("task_id"))
                        if trace is not None:
                            core.record_trace_span(
                                trace, msg.get("task_id"), "worker_exec",
                                t0, time.monotonic())
                    t1 = time.monotonic()
                    run_returns(msg, result)
                    _phase_times[threading.get_ident()][1] = \
                        time.monotonic() - t1
                    if trace is not None:
                        core.record_trace_span(
                            trace, msg.get("task_id"), "result_register",
                            t1, time.monotonic())
                elif mtype == "create_actor_instance":
                    cls = load_function(msg["fn_id"])
                    pos, kwargs = resolve_args(msg)
                    t0 = time.monotonic()
                    w0 = time.time()
                    try:
                        actor_instance = cls(*pos, **kwargs)
                        actor_id = msg["actor_id"]
                        maybe_restore_checkpoint(msg)
                    finally:
                        # Constructor window: actor-creation completions
                        # carry exec stamps like plain tasks do.
                        _phase_times[threading.get_ident()] = \
                            [time.monotonic() - t0, 0.0, w0, time.time()]
                    if msg.get("is_asyncio"):
                        actor_loop = asyncio.new_event_loop()
                        threading.Thread(
                            target=actor_loop.run_forever, daemon=True,
                            name="actor-asyncio-loop").start()
                    elif int(msg.get("max_concurrency", 1) or 1) > 1:
                        from concurrent.futures import ThreadPoolExecutor

                        actor_pool = ThreadPoolExecutor(
                            max_workers=int(msg["max_concurrency"]),
                            thread_name_prefix="actor-exec")
                    store_result(msg["return_ids"][0], True, _adds_list())
                elif mtype == "execute_actor_task":
                    raise RuntimeError("actor not initialized")
                else:
                    continue
            except BaseException as e:  # noqa: BLE001 - task errors are data
                try:
                    store_error(msg, e)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
            finally:
                if not finish(msg):
                    break

    serve_loop()

    if actor_loop is not None:
        actor_loop.call_soon_threadsafe(actor_loop.stop)
    if actor_pool is not None:
        actor_pool.shutdown(wait=False)


if __name__ == "__main__":
    main()
