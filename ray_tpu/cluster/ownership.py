"""Ownership object plane: owner tables, the consistent-hash owner
directory, and the per-driver owner-serve loop.

The GCS object table was the last hot-path funnel through the head event
loop: every inline result was shipped head-ward, stored under the inline
budget, and served back through ``locations_batch`` long-polls. This
module moves that plane to the edges, following the ownership model Ray's
lineage stores evolved into (arXiv:1712.05889):

- Every object id carries its creating job's 4 id bytes at ``oid[12:16]``
  (task-execution contexts keep the SUBMITTING driver's job, so a whole
  nested job tree shares one owner). The **owner** of an object is the
  driver core_worker of that job.
- Each driver runs an :class:`OwnerServer` — a tiny RPC endpoint on a
  daemon thread — backed by a budget-bounded :class:`OwnerTable`.
  Controllers push completed inline results to it (``owner_publish``)
  and borrowers pull (``owner_fetch``) or probe (``owner_locate``)
  without the head ever seeing the bytes.
- The GCS keeps only membership: a **consistent-hash directory of owner
  shards** (:class:`OwnerRing`) mapping job -> owner endpoint, replicated
  through the epoch-fenced HA log like every other membership table.

Kill switch: ``RAY_TPU_OWNERSHIP=0`` (see ``wire.ownership_enabled``)
stops drivers registering as owners, which reverts every downstream
decision (controller divert, GCS dep staging, recovery) to the legacy
GCS-tracked path per-object.
"""
from __future__ import annotations

import asyncio
import hashlib
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from . import wire
from .protocol import RpcServer

# 4-byte job id suffix length inside a 16-byte object id.
_JOB_BYTES = 4


def owner_key(oid: bytes) -> bytes:
    """The owner-routing key of an object id: its job id bytes. Matches
    the completion-ring naming (``cring.ring_name(oid[12:16])``), so the
    owner endpoint and the owner ring always agree."""
    return oid[12:12 + _JOB_BYTES]


def owner_shards() -> int:
    """Directory shard count (``RAY_TPU_OWNER_SHARDS``). Shards bound the
    per-lookup scan and give the audit a stable unit to reason about;
    they are virtual — one GCS process serves all of them — but the
    consistent-hash split keeps the layout stable as owners come and go
    and is the seam a multi-process directory would split along."""
    try:
        n = int(os.environ.get("RAY_TPU_OWNER_SHARDS", "8"))
    except ValueError:
        n = 8
    return max(1, min(n, 4096))


def owner_table_budget() -> int:
    """Byte budget for one driver's owner table
    (``RAY_TPU_OWNER_TABLE_BUDGET_BYTES``, default 64 MiB — the same
    default the GCS inline budget used, now paid per-driver instead of
    once at the head). Eviction drops the oldest blobs; borrowers that
    miss recover through lineage re-drive."""
    try:
        return int(os.environ.get(
            "RAY_TPU_OWNER_TABLE_BUDGET_BYTES", str(64 << 20)))
    except ValueError:
        return 64 << 20


def owner_grace_s() -> float:
    """Grace window before an owner-missing probe re-drives lineage
    (``RAY_TPU_OWNER_GRACE_S``): a finished task's publish may still be
    in flight controller->owner, so the GCS only reconstructs when the
    finish is older than this."""
    try:
        return float(os.environ.get("RAY_TPU_OWNER_GRACE_S", "1.0"))
    except ValueError:
        return 1.0


class OwnerRing:
    """Consistent-hash ring assigning owner keys (job ids) to directory
    shards. Classic fixed-point construction: each shard projects
    ``replicas`` virtual points onto the 64-bit ring; a key maps to the
    first point clockwise. Adding/removing a shard moves only ~1/N of the
    keyspace, so a resize never reshuffles the whole directory."""

    __slots__ = ("shards", "_points", "_hashes")

    def __init__(self, shards: Optional[int] = None, replicas: int = 64):
        self.shards = shards if shards is not None else owner_shards()
        points: List[Tuple[int, int]] = []
        for shard in range(self.shards):
            for rep in range(replicas):
                digest = hashlib.blake2b(
                    b"owner-shard:%d:%d" % (shard, rep),
                    digest_size=8).digest()
                points.append((int.from_bytes(digest, "big"), shard))
        points.sort()
        self._points = points
        self._hashes = [p[0] for p in points]

    def lookup(self, key: bytes) -> int:
        """Shard index for an owner key."""
        import bisect

        h = int.from_bytes(
            hashlib.blake2b(key, digest_size=8).digest(), "big")
        idx = bisect.bisect_right(self._hashes, h)
        if idx == len(self._points):
            idx = 0
        return self._points[idx][1]


class OwnerTable:
    """One driver's authoritative record of the objects it owns.

    Entries are ``oid -> (size, blob|None, node_addr|None)``: the blob is
    present when the bytes were pushed owner-to-owner (remote producer),
    absent when the same-host completion ring already carried them (then
    ``node_addr`` points at the producing controller's inline stash as the
    fetch fallback). Inserts are idempotent — duplicate deliveries from
    the ring and the publish path collapse onto one entry. Blob bytes are
    budget-bounded with FIFO eviction; tracking entries (size+location)
    are cheap and capped only by count."""

    __slots__ = ("_entries", "_lock", "_budget", "_blob_bytes", "arrived",
                 "inserted", "evicted", "max_entries")

    def __init__(self, budget: Optional[int] = None,
                 max_entries: int = 1 << 20):
        self._entries: "OrderedDict[bytes, Tuple[int, Optional[bytes], Optional[Tuple[str, int]]]]" = OrderedDict()
        self._lock = threading.Lock()
        self._budget = owner_table_budget() if budget is None else budget
        self._blob_bytes = 0
        self.max_entries = max_entries
        # Arrival latch: the driver's get() loop clears+rechecks this
        # instead of burning a GCS long-poll timeout when a publish lands
        # between ring waits.
        self.arrived = threading.Event()
        self.inserted = 0
        self.evicted = 0

    def insert(self, oid: bytes, size: int, blob: Optional[bytes],
               addr: Optional[Tuple[str, int]] = None) -> bool:
        """Record one owned object; returns True when the entry is new or
        was upgraded (gained bytes it lacked)."""
        with self._lock:
            cur = self._entries.get(oid)
            if cur is not None:
                if blob is not None and cur[1] is None:
                    self._entries[oid] = (size, blob, cur[2] or addr)
                    self._blob_bytes += len(blob)
                    self._evict_locked()
                    return True
                return False
            self._entries[oid] = (size, blob, addr)
            if blob is not None:
                self._blob_bytes += len(blob)
            self.inserted += 1
            self._evict_locked()
        return True

    def _evict_locked(self) -> None:
        # Oldest-first blob eviction keeps the tracking entry (size/addr)
        # so locate still answers; a borrower needing the bytes falls back
        # to the node stash or lineage re-drive.
        while self._blob_bytes > self._budget and self._entries:
            for oid, (size, blob, addr) in self._entries.items():
                if blob is None:
                    continue
                self._entries[oid] = (size, None, addr)
                self._blob_bytes -= len(blob)
                self.evicted += 1
                break
            else:
                break
        while len(self._entries) > self.max_entries:
            _, (_, blob, _) = self._entries.popitem(last=False)
            if blob is not None:
                self._blob_bytes -= len(blob)
            self.evicted += 1

    def get_blob(self, oid: bytes) -> Optional[bytes]:
        with self._lock:
            ent = self._entries.get(oid)
            return ent[1] if ent is not None else None

    def locate(self, oid: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            ent = self._entries.get(oid)
        if ent is None:
            return None
        return {"size": ent[0], "inline": ent[1] is not None,
                "addr": ent[2]}

    def discard(self, oids) -> None:
        with self._lock:
            for oid in oids:
                ent = self._entries.pop(oid, None)
                if ent is not None and ent[1] is not None:
                    self._blob_bytes -= len(ent[1])

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries),
                    "blob_bytes": self._blob_bytes,
                    "inserted": self.inserted, "evicted": self.evicted}


class OwnerServer:
    """The driver-side owner-serve loop: an :class:`RpcServer` on its own
    daemon thread answering ``owner_publish`` / ``owner_fetch`` /
    ``owner_locate`` (plus ``wire_probe`` so peers can lift their send
    floor to v9). Handlers touch only the thread-safe
    :class:`OwnerTable` and the optional publish callback, so they never
    contend with the driver's submit/get path."""

    def __init__(self, table: OwnerTable, host: str = "127.0.0.1",
                 on_publish=None):
        self.table = table
        self.host = host
        self.port = 0
        self._on_publish = on_publish
        self._server: Optional[RpcServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.stats: Dict[str, int] = {
            "publishes": 0, "published_items": 0,
            "fetches": 0, "fetch_hits": 0, "locates": 0}

    # ------------------------------------------------------------- lifecycle
    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._run, name="owner-serve", daemon=True)
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("owner-serve loop failed to start")
        return self.port

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        server = RpcServer(host=self.host, port=0)
        self._register(server)
        self._server = server

        async def _up():
            self.port = await server.start()

        loop.run_until_complete(_up())
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(server.stop())
            loop.close()

    def stop(self) -> None:
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -------------------------------------------------------------- handlers
    def _register(self, s: RpcServer) -> None:
        table = self.table
        stats = self.stats

        @s.handler("wire_probe")
        async def wire_probe(msg, conn):
            return {"ok": True, "wire": wire.WIRE_VERSION}

        @s.handler("owner_publish")
        async def owner_publish(msg, conn):
            addr = msg.get("address")
            if addr is not None:
                addr = (str(addr[0]), int(addr[1]))
            items = msg.get("items") or []
            fresh = []
            for ent in items:
                oid, size = ent[0], int(ent[1])
                blob = ent[2] if len(ent) > 2 else None
                if table.insert(oid, size, blob, addr):
                    fresh.append((oid, size, blob))
            stats["publishes"] += 1
            stats["published_items"] += len(items)
            if fresh:
                table.arrived.set()
                if self._on_publish is not None:
                    try:
                        self._on_publish(fresh)
                    except Exception:  # noqa: BLE001 - ring is best-effort
                        pass
            return {"ok": True, "count": len(items)}

        @s.handler("owner_fetch")
        async def owner_fetch(msg, conn):
            blobs: Dict[bytes, bytes] = {}
            locations: Dict[bytes, list] = {}
            for oid in msg.get("object_ids") or []:
                info = table.locate(oid)
                if info is None:
                    continue
                if info["inline"]:
                    blob = table.get_blob(oid)
                    if blob is not None:
                        blobs[oid] = blob
                        continue
                if info["addr"] is not None:
                    locations[oid] = [info["addr"][0], info["addr"][1]]
            stats["fetches"] += 1
            stats["fetch_hits"] += len(blobs) + len(locations)
            return {"ok": True, "blobs": blobs, "locations": locations}

        @s.handler("owner_locate")
        async def owner_locate(msg, conn):
            objects: Dict[bytes, Dict[str, Any]] = {}
            for oid in msg.get("object_ids") or []:
                info = table.locate(oid)
                if info is not None:
                    objects[oid] = {"size": info["size"],
                                    "inline": info["inline"]}
            stats["locates"] += 1
            return {"ok": True, "objects": objects}

        @s.handler("owner_stats")
        async def owner_stats(msg, conn):
            st = dict(self.stats)
            st.update(table.stats())
            return {"ok": True, "stats": st}
