"""Driver connection to a running cluster (reference: worker.connect,
``python/ray/worker.py:1137``)."""

from __future__ import annotations


def connect_driver(address: str, config):
    """address: "host:port" (or "tcp://host:port") of the GCS."""
    from .core_worker import ClusterCoreWorker

    address = address.replace("tcp://", "")
    host, port = address.rsplit(":", 1)
    return ClusterCoreWorker((host, int(port)), role="driver", config=config)
