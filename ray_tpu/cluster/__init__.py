"""Multi-process / multi-node cluster runtime.

The distributed control plane equivalent to the reference's raylet + GCS
(reference: ``src/ray/raylet/``, ``src/ray/gcs/gcs_server/``), re-architected
TPU-first:

  - one GCS head process: tables (nodes/actors/objects/functions), pubsub,
    heartbeat death detection, and the global placement service backed by the
    batch placement kernel (ray_tpu.scheduler.BatchScheduler);
  - one NodeController per host (the raylet equivalent): worker pool, local
    object store, dependency fetching, task dispatch;
  - worker processes executing tasks/actors with the same public API
    (nested submits route through their node controller).

Transport is a length-prefixed pickle protocol over TCP (protocol.py); bulk
object payloads ride the same channel chunked. The shared-memory C++ arena
(ray_tpu/native) backs the local object store when built.

Object tracking is ownership-sharded (ownership.py): each driver owns the
inline results its job creates and serves them from an in-process owner
table over wire-v9 frames; the GCS keeps only membership plus a
consistent-hash directory of owners (kill switch ``RAY_TPU_OWNERSHIP=0``).
"""

from .ownership import OwnerRing, OwnerServer, OwnerTable  # noqa: F401
from .testing import Cluster  # noqa: F401
