"""Pull-based cross-node transfer manager — the data plane's scheduler.

Reference counterpart: src/ray/object_manager/pull_manager.cc (admission-
controlled pulls) + object_buffer_pool.cc (chunked receive). The native
layer (``_native/transfer.py``) moves bytes: a pull is a pipeline of
fixed-size ranges received straight into the destination arena slot, with
the per-chunk offset doubling as a resume cursor. This module decides
WHICH pulls run WHEN:

* **Admission**: at most ``RAY_TPU_TRANSFER_MAX_INFLIGHT`` concurrent
  pulls per SOURCE node (N reducers draining one mapper's output must not
  thundering-herd its transfer server). Excess pulls queue FIFO; equal
  arrival order breaks ties largest-first (big objects hide more latency
  behind them, so they go first — the classic SRPT inversion for
  bandwidth-bound streams). ``RAY_TPU_TRANSFER_SCHED=0`` bypasses
  admission entirely (every pull runs immediately, chunked path intact).

* **Failover**: a sender dying mid-stream surfaces as a broken chunk
  stream; the pull keeps its landed prefix and resumes at the same offset
  against the next holder (counted in ``transfer_chunk_retries``, event-
  logged as ``transfer_sender_death``). Only when every holder is
  exhausted does the pull fail — the controller's fetch loop then re-polls
  the directory, which re-drives lineage if the object is truly gone.

* **Accounting**: ``transfer_bytes_in`` (landed payload bytes, partial
  pulls included), ``transfer_bytes_out`` (served by this node's native
  server), ``transfer_inflight``, ``transfer_queue_depth``,
  ``transfer_chunk_retries`` — all riding the heartbeat's node_stats into
  the head's time-series store and Prometheus. ``inventory()`` is the
  auditor's view: every inflight/queued pull with its source and age, so
  ``run_audit`` can flag stuck and orphaned transfers.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_MAX_INFLIGHT = 4
DEFAULT_CHUNK = 1 << 20
_MAX_EVENTS = 256


def sched_enabled() -> bool:
    """Kill switch: ``RAY_TPU_TRANSFER_SCHED=0`` disables admission (pulls
    run unqueued; the chunked/resumable path itself stays on)."""
    return os.environ.get("RAY_TPU_TRANSFER_SCHED", "") != "0"


def max_inflight_per_source() -> int:
    try:
        v = int(os.environ.get("RAY_TPU_TRANSFER_MAX_INFLIGHT", ""))
        return max(1, v)
    except ValueError:
        return DEFAULT_MAX_INFLIGHT


def chunk_size() -> int:
    try:
        v = int(os.environ.get("RAY_TPU_TRANSFER_CHUNK", ""))
        return max(1 << 12, v)
    except ValueError:
        return DEFAULT_CHUNK


class PullFailedError(Exception):
    """Every candidate source was tried (with resume) and none completed
    the stream. The landed prefix has been aborted; the caller should
    re-poll locations (lineage re-drive happens head-side)."""


class TransferManager:
    """One per controller. Owns admission + failover for chunked pulls.

    ``store`` is the node's (spilling) object store; ``client`` the native
    TransferClient (or any object with ``probe_size``/``fetch_chunks``);
    ``server`` optionally the native TransferServer whose ``stats()``
    supplies bytes_out. All coroutine methods run on the controller's
    event loop; blocking socket work is pushed to worker threads."""

    def __init__(self, store, client, server=None,
                 max_inflight: Optional[int] = None,
                 chunk: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.store = store
        self.client = client
        self.server = server
        self.max_inflight = (max_inflight if max_inflight is not None
                             else max_inflight_per_source())
        self.chunk = chunk if chunk is not None else chunk_size()
        self.enabled = enabled if enabled is not None else sched_enabled()
        self._seq = itertools.count()
        self._tie = itertools.count()
        self._inflight_by_src: Dict[str, int] = {}
        # Per-source admission queue: heap of (seq, -size, tie, entry).
        self._waiting: Dict[str, List[Tuple[int, int, int, dict]]] = {}
        self._inflight_info: Dict[int, Dict[str, Any]] = {}
        self._queued_info: Dict[int, Dict[str, Any]] = {}
        self._token = itertools.count()
        self._events: List[Dict[str, Any]] = []
        # Counters (monotonic; deltas derived head-side).
        self.bytes_in = 0
        self.chunk_retries = 0
        self.sender_deaths = 0
        self.pulls_ok = 0
        self.pulls_failed = 0
        self.queued_total = 0

    # ------------------------------------------------------------ admission
    def _slot_free(self, src: str) -> bool:
        return self._inflight_by_src.get(src, 0) < self.max_inflight

    async def _acquire(self, src: str, size: int, seq: int,
                       deadline: float, oid: bytes) -> int:
        """Take (or queue for) a pull slot against source ``src``. Returns
        an inventory token; raises asyncio.TimeoutError when the deadline
        passes while still queued."""
        token = next(self._token)
        if not self.enabled:
            self._inflight_by_src[src] = self._inflight_by_src.get(src, 0) + 1
            self._inflight_info[token] = {
                "object_id": oid.hex(), "source": src, "ts": time.time(),
                "size": size}
            return token
        heap = self._waiting.setdefault(src, [])
        if self._slot_free(src) and not heap:
            self._inflight_by_src[src] = self._inflight_by_src.get(src, 0) + 1
        else:
            entry = {"event": asyncio.Event(), "cancelled": False,
                     "token": token}
            heapq.heappush(heap, (seq, -size, next(self._tie), entry))
            self.queued_total += 1
            self._queued_info[token] = {
                "object_id": oid.hex(), "source": src, "ts": time.time(),
                "size": size}
            try:
                await asyncio.wait_for(entry["event"].wait(),
                                       max(0.0, deadline - time.time()))
            except asyncio.TimeoutError:
                entry["cancelled"] = True
                self._queued_info.pop(token, None)
                if entry["event"].is_set():
                    # The slot was handed to us in the same tick we gave
                    # up: pass it straight on instead of leaking it.
                    self._release(src, token)
                raise
            finally:
                if not entry["cancelled"]:
                    self._queued_info.pop(token, None)
            # _release incremented the inflight count on our behalf.
        self._inflight_info[token] = {
            "object_id": oid.hex(), "source": src, "ts": time.time(),
            "size": size}
        return token

    def _release(self, src: str, token: int) -> None:
        self._inflight_info.pop(token, None)
        n = self._inflight_by_src.get(src, 0) - 1
        if n <= 0:
            self._inflight_by_src.pop(src, None)
        else:
            self._inflight_by_src[src] = n
        heap = self._waiting.get(src)
        while heap:
            _, _, _, entry = heapq.heappop(heap)
            if entry["cancelled"]:
                continue
            # Hand the freed slot straight to the best waiter (FIFO by
            # seq, largest-first among equals) before anyone new can take
            # it — incrementing here, not in the waiter, closes the race.
            self._inflight_by_src[src] = self._inflight_by_src.get(src, 0) + 1
            entry["event"].set()
            break
        if heap is not None and not heap:
            self._waiting.pop(src, None)

    # ---------------------------------------------------------------- pull
    async def pull(self, object_id: bytes,
                   sources: Sequence[Tuple[str, str, int]],
                   size_hint: int = 0, timeout: float = 30.0,
                   seq: Optional[int] = None) -> bool:
        """Pull ``object_id`` from one of ``sources`` (``(node_id, host,
        transfer_port)`` triples) into the local store, chunked and
        resumable. True when the object is local (sealed or spill-staged)
        on return. Raises PullFailedError when every source failed, and
        asyncio.TimeoutError when the admission queue outwaited
        ``timeout``."""
        if not sources:
            return False
        if seq is None:
            seq = next(self._seq)
        deadline = time.time() + timeout
        pending = list(sources)
        attempts = 0
        max_attempts = 2 * len(sources) + 1
        total: Optional[int] = None
        view = None
        offset = 0
        try:
            while pending and attempts < max_attempts \
                    and time.time() < deadline:
                node_id, host, port = pending.pop(0)
                attempts += 1
                token = await self._acquire(
                    node_id, size_hint or (total or 0), seq, deadline,
                    object_id)
                try:
                    if total is None:
                        total = await asyncio.to_thread(
                            self.client.probe_size, host, port, object_id)
                        if total is None:
                            continue  # stale location: no copy there
                    if view is None:
                        view = self.store.create(object_id, total)
                        if view is None:
                            # Raced another fetcher / already spill-staged.
                            self.pulls_ok += 1
                            return True
                    start = offset
                    self._inflight_info[token]["offset"] = offset
                    await asyncio.to_thread(
                        self.client.fetch_chunks, host, port, object_id,
                        view, offset, self.chunk)
                    self.bytes_in += total - start
                    offset = total
                    view = None  # ownership passes to the store on seal
                    self.store.seal(object_id)
                    self.pulls_ok += 1
                    return True
                except Exception as exc:  # noqa: BLE001
                    name = type(exc).__name__
                    if name == "RemoteMissError":
                        continue  # holder lost the copy; try the next one
                    if name != "TransferBrokenError":
                        raise
                    landed = max(getattr(exc, "offset", offset), offset)
                    self.bytes_in += landed - offset
                    resumed = landed > offset or total is not None
                    offset = landed
                    self.chunk_retries += 1
                    self.sender_deaths += 1
                    self._event("transfer_sender_death",
                                object_id=object_id.hex()[:16],
                                source=node_id, offset=offset,
                                total=total or 0, resumed=bool(resumed))
                    # Second pass: the source may only have blipped.
                    pending.append((node_id, host, port))
                finally:
                    self._release(node_id, token)
        finally:
            if view is not None:
                try:
                    self.store.abort(object_id)
                except Exception:  # noqa: BLE001
                    pass
        self.pulls_failed += 1
        self._event("transfer_pull_failed", object_id=object_id.hex()[:16],
                    sources=len(sources), offset=offset, total=total or 0)
        raise PullFailedError(
            f"pull of {object_id.hex()[:16]} failed after {attempts} "
            f"attempts over {len(sources)} source(s)")

    # ------------------------------------------------------- observability
    def _event(self, kind: str, **data) -> None:
        if len(self._events) < _MAX_EVENTS:
            self._events.append({"kind": kind, "ts": time.time(), **data})

    def drain_events(self) -> List[Dict[str, Any]]:
        out, self._events = self._events, []
        return out

    def stats(self) -> Dict[str, Any]:
        """Counter/gauge snapshot riding node_stats each heartbeat."""
        bytes_out = requests = 0
        if self.server is not None:
            try:
                bytes_out, requests = self.server.stats()
            except Exception:  # noqa: BLE001
                pass
        return {
            "bytes_in": self.bytes_in,
            "bytes_out": bytes_out,
            "requests_served": requests,
            "inflight": len(self._inflight_info),
            "queue_depth": len(self._queued_info),
            "chunk_retries": self.chunk_retries,
            "sender_deaths": self.sender_deaths,
            "pulls_ok": self.pulls_ok,
            "pulls_failed": self.pulls_failed,
            "queued_total": self.queued_total,
            "max_inflight": self.max_inflight,
            "sched_enabled": self.enabled,
        }

    def inventory(self) -> Dict[str, List[Dict[str, Any]]]:
        """The auditor's transfer block: every inflight and queued pull
        with source + age, so the head can flag stuck/orphaned pulls."""
        now = time.time()
        return {
            "inflight": [
                {"object_id": e["object_id"], "source": e["source"],
                 "age_s": round(now - e["ts"], 3),
                 "size": e.get("size", 0), "offset": e.get("offset", 0)}
                for e in self._inflight_info.values()],
            "queued": [
                {"object_id": e["object_id"], "source": e["source"],
                 "age_s": round(now - e["ts"], 3), "size": e.get("size", 0)}
                for e in self._queued_info.values()],
        }

    def close(self) -> None:
        for heap in self._waiting.values():
            while heap:
                _, _, _, entry = heapq.heappop(heap)
                entry["cancelled"] = True
                entry["event"].set()
        self._waiting.clear()
        self._queued_info.clear()
