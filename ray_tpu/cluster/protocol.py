"""Cluster wire protocol: length-prefixed pickled messages over TCP.

Plays the role of the reference's gRPC plumbing (``src/ray/rpc/``): typed
request/response with correlation ids, plus server-push messages (pubsub).
A message is ``[8-byte LE length][pickle bytes]``; payloads are plain dicts
with a ``type`` field. Object payloads are raw bytes inside the pickle — the
pickle module handles them zero-copy-ish via protocol 5 out-of-band buffers
when large.

Server side: asyncio. Client side: a blocking, thread-safe RpcClient (the
runtime's callers are threads, not coroutines).
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

_LEN = struct.Struct("<Q")
MAX_MESSAGE = 1 << 34


def _dumps(msg: Dict[str, Any]) -> bytes:
    body = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(body)) + body


# ---------------------------------------------------------------------------
# asyncio server side
# ---------------------------------------------------------------------------

async def read_message(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    try:
        header = await reader.readexactly(8)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_MESSAGE:
        raise ValueError(f"message too large: {length}")
    body = await reader.readexactly(length)
    return pickle.loads(body)


async def write_message(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    writer.write(_dumps(msg))
    await writer.drain()


class RpcServer:
    """Asyncio TCP server dispatching requests to handler coroutines.

    Handlers are registered per message type; each gets (msg, connection) and
    returns a response dict (or None for one-way messages). Connections are
    tracked so services can push messages (pubsub, task assignment).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_disconnect: Optional[Callable] = None
        self._conns: set = set()
        # Per-message-type {count, cumulative seconds}: the cProfile-free
        # answer to "where do this service's event-loop cycles go".
        self.handler_stats: Dict[str, list] = {}

    def handler(self, msg_type: str):
        def deco(fn):
            self._handlers[msg_type] = fn
            return fn
        return deco

    def on_disconnect(self, fn: Callable) -> None:
        self._on_disconnect = fn

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = Connection(reader, writer)
        self._conns.add(conn)
        try:
            while True:
                msg = await read_message(reader)
                if msg is None:
                    break
                mtype = msg.get("type")
                handler = self._handlers.get(mtype)
                if handler is None:
                    resp = {"ok": False, "error": f"unknown type {mtype}"}
                else:
                    t0 = time.monotonic()
                    try:
                        resp = await handler(msg, conn)
                    except Exception as e:  # noqa: BLE001 - reported to caller
                        import traceback
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}",
                                "traceback": traceback.format_exc()}
                    finally:
                        cell = self.handler_stats.get(mtype)
                        if cell is None:
                            cell = self.handler_stats[mtype] = [0, 0.0]
                        cell[0] += 1
                        cell[1] += time.monotonic() - t0
                if "rpc_id" in msg and resp is not None:
                    resp["rpc_id"] = msg["rpc_id"]
                    await conn.send(resp)
        finally:
            self._conns.discard(conn)
            if self._on_disconnect is not None:
                try:
                    res = self._on_disconnect(conn)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:  # noqa: BLE001
                    pass
            writer.close()

    async def stop(self):
        if self._server is not None:
            self._server.close()
            # Force live client connections shut, else wait_closed() blocks
            # until every client hangs up on its own.
            for conn in list(self._conns):
                try:
                    conn.writer.close()
                except Exception:  # noqa: BLE001
                    pass
            await self._server.wait_closed()


class Connection:
    """One inbound connection; supports locked writes for server push."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.meta: Dict[str, Any] = {}  # handler-attached identity (node id...)
        self._wlock = asyncio.Lock()

    async def send(self, msg: Dict[str, Any]):
        async with self._wlock:
            await write_message(self.writer, msg)


# ---------------------------------------------------------------------------
# blocking client side
# ---------------------------------------------------------------------------

class RpcClient:
    """Thread-safe blocking RPC client with a reader thread.

    Responses are matched by rpc_id; unsolicited messages (server push) go to
    the ``push_handler``.
    """

    def __init__(self, host: str, port: int,
                 push_handler: Optional[Callable[[Dict], None]] = None,
                 timeout: float = 30.0,
                 on_close: Optional[Callable[[], None]] = None):
        self._on_close = on_close
        self.addr = (host, port)
        self._sock = socket.create_connection(self.addr, timeout=timeout)
        self._sock.settimeout(None)
        # Small control messages back-to-back must not wait out Nagle +
        # delayed-ACK (a one-way notification followed by a call would
        # stall ~40 ms).
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._pending: Dict[int, "threading.Event"] = {}
        self._responses: Dict[int, Dict] = {}
        self._counter = itertools.count(1)
        self._push_handler = push_handler
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self):
        try:
            while not self._closed:
                header = self._recv_exact(8)
                if header is None:
                    break
                (length,) = _LEN.unpack(header)
                body = self._recv_exact(length)
                if body is None:
                    break
                msg = pickle.loads(body)
                rpc_id = msg.get("rpc_id")
                if rpc_id is not None and rpc_id in self._pending:
                    self._responses[rpc_id] = msg
                    self._pending[rpc_id].set()
                elif self._push_handler is not None:
                    try:
                        self._push_handler(msg)
                    except Exception:  # noqa: BLE001
                        pass
        except OSError:
            pass
        finally:
            self._closed = True
            for ev in list(self._pending.values()):
                ev.set()
            if self._on_close is not None:
                try:
                    self._on_close()
                except Exception:  # noqa: BLE001
                    pass

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    def call(self, msg: Dict[str, Any], timeout: Optional[float] = 60.0) -> Dict:
        if self._closed:
            raise ConnectionError(f"connection to {self.addr} closed")
        rpc_id = next(self._counter)
        msg = dict(msg, rpc_id=rpc_id)
        ev = threading.Event()
        self._pending[rpc_id] = ev
        with self._wlock:
            self._sock.sendall(_dumps(msg))
        if not ev.wait(timeout):
            self._pending.pop(rpc_id, None)
            raise TimeoutError(f"rpc {msg['type']} to {self.addr} timed out")
        self._pending.pop(rpc_id, None)
        resp = self._responses.pop(rpc_id, None)
        if resp is None:
            raise ConnectionError(f"connection to {self.addr} lost mid-call")
        if resp.get("ok") is False:
            raise RuntimeError(
                f"rpc {msg['type']} failed: {resp.get('error')}\n"
                f"{resp.get('traceback', '')}"
            )
        return resp

    def send_oneway(self, msg: Dict[str, Any]) -> None:
        if self._closed:
            raise ConnectionError(f"connection to {self.addr} closed")
        with self._wlock:
            self._sock.sendall(_dumps(msg))

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class ResilientClient:
    """RpcClient that transparently reconnects across server restarts.

    Used for GCS connections (reference: clients retry against the restarted
    GCS in test_gcs_fault_tolerance.py). A call that hits a dead socket
    re-dials until ``retry_window`` elapses; the GCS restores its tables from
    its snapshot, so retried calls see consistent state.
    """

    def __init__(self, host: str, port: int,
                 push_handler: Optional[Callable[[Dict], None]] = None,
                 retry_window: float = 30.0):
        self.addr = (host, port)
        self._push_handler = push_handler
        self._retry_window = retry_window
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._closed = False
        self._ensure()

    def _ensure(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise ConnectionError(f"client to {self.addr} closed")
            if self._client is None or self._client._closed:
                self._client = RpcClient(
                    *self.addr, push_handler=self._push_handler)
            return self._client

    def _drop(self) -> None:
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None

    def call(self, msg: Dict[str, Any], timeout: Optional[float] = 60.0) -> Dict:
        deadline = time.monotonic() + self._retry_window
        while True:
            try:
                return self._ensure().call(msg, timeout=timeout)
            except (ConnectionError, OSError):
                self._drop()
                if self._closed or time.monotonic() > deadline:
                    raise
                time.sleep(0.25)

    def send_oneway(self, msg: Dict[str, Any]) -> None:
        try:
            self._ensure().send_oneway(msg)
        except (ConnectionError, OSError):
            self._drop()
            # one immediate retry; oneway messages are periodic (heartbeats)
            # so a miss is recovered by the next tick anyway
            try:
                self._ensure().send_oneway(msg)
            except (ConnectionError, OSError):
                pass

    def close(self):
        self._closed = True
        self._drop()
