"""Cluster wire protocol: length-prefixed messages over TCP.

Plays the role of the reference's gRPC plumbing (``src/ray/rpc/``): typed
request/response with correlation ids, plus server-push messages (pubsub).
A frame is ``[8-byte LE length][body]``. Two body encodings share every
socket:

  * **pickle** (default, any message type): a plain dict with a ``type``
    field, protocol-5 out-of-band buffers for large payloads;
  * **binary fast path** (``wire.py``): struct-packed bodies for the
    highest-frequency control-plane types, detected by a magic first byte
    (pickle bodies start with 0x80, binary with 0xBF).

Receivers always understand both, so old pickle-only peers interoperate on
the same socket; binary is only *sent* to peers that advertised/showed
capability, and ``RAY_TPU_WIRE_PICKLE_ONLY=1`` pins a process to pickle.

Server side: asyncio. Client side: a blocking, thread-safe RpcClient (the
runtime's callers are threads, not coroutines). Oneway messages can be
coalesced into a single scatter-write (``send_oneway_many``) so a
completion wave is one sendmsg, not N.
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import wire
from .._native import framepump
from .._private import chaos as _chaos
from .._private.config import get_config

_LEN = struct.Struct("<Q")
MAX_MESSAGE = 1 << 34
# Bulk-read size for the server's recv loop: big enough that a burst of
# small frames arrives as one wakeup, small enough to stay cache-friendly.
_READ_CHUNK = 1 << 18


def _dumps(msg: Dict[str, Any]) -> bytes:
    body = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(body)) + body


def _loads_body(body: bytes) -> Dict[str, Any]:
    if wire.is_binary(body):
        return wire.decode(body)
    return pickle.loads(body)


def _compact(bufs: List[bytes], small: int = 1 << 14) -> List[bytes]:
    """Merge runs of small buffers into one; keep large blobs standalone
    (they pass through unjoined — the zero-copy part of the scatter
    write). Also keeps iovec counts far under IOV_MAX."""
    out: List[bytes] = []
    acc: Optional[bytearray] = None
    for b in bufs:
        if len(b) < small:
            if acc is None:
                acc = bytearray(b)
            else:
                acc += b
        else:
            if acc is not None:
                out.append(bytes(acc))
                acc = None
            out.append(b)
    if acc is not None:
        out.append(bytes(acc))
    return out


def encode_frames(msg: Dict[str, Any], binary_ok: bool,
                  req_type: Optional[str] = None,
                  peer_wire: int = 1) -> List[bytes]:
    """Encode one message into a list of buffers (length header first).

    ``binary_ok`` gates the fast path; ``req_type`` selects a response
    codec (responses carry no ``type`` field of their own); ``peer_wire``
    is the receiver's advertised wire version — frames the peer could not
    parse (v2 inline-result frames to a v1 peer) fall back per-message to
    pickle, as do types without a binary codec."""
    if binary_ok and not wire.pickle_only():
        try:
            bufs = (wire.encode_response(req_type, msg, peer_wire) if req_type
                    else wire.encode(msg, peer_wire))
        except wire.WireError:
            bufs = None
        if bufs is not None:
            total = sum(len(b) for b in bufs)
            return _compact([_LEN.pack(total), *bufs])
    return [_dumps(msg)]


# ---------------------------------------------------------------------------
# asyncio server side
# ---------------------------------------------------------------------------

async def read_frame(reader: asyncio.StreamReader
                     ) -> Optional[tuple]:
    """One frame off the stream: (msg, was_binary), or None at EOF."""
    try:
        header = await reader.readexactly(8)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_MESSAGE:
        raise ValueError(f"message too large: {length}")
    body = await reader.readexactly(length)
    return _loads_body(body), wire.is_binary(body)


async def read_message(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    frame = await read_frame(reader)
    return None if frame is None else frame[0]


async def write_message(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    writer.write(_dumps(msg))
    await writer.drain()


class RpcServer:
    """Asyncio TCP server dispatching requests to handler coroutines.

    Handlers are registered per message type; each gets (msg, connection) and
    returns a response dict (or None for one-way messages). Connections are
    tracked so services can push messages (pubsub, task assignment).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_disconnect: Optional[Callable] = None
        self._conns: set = set()
        # Per-message-type {count, cumulative seconds}: the cProfile-free
        # answer to "where do this service's event-loop cycles go".
        self.handler_stats: Dict[str, list] = {}
        # Frame-pump attribution: socket wakeups vs frames delivered
        # (frames/read >> 1 is the batching win) and whether the native
        # splitter is active. Shipped via debug_stats/stats.
        self.recv_stats: Dict[str, int] = {
            "reads": 0, "frames": 0,
            "native": 1 if framepump.enabled() else 0}

    def handler(self, msg_type: str):
        def deco(fn):
            self._handlers[msg_type] = fn
            return fn
        return deco

    def on_disconnect(self, fn: Callable) -> None:
        self._on_disconnect = fn

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = Connection(reader, writer)
        self._conns.add(conn)
        # Batched recv path (framepump.cc): the loop reads in bulk — one
        # await per socket wakeup, not two per frame — and the splitter
        # (native when built, Python twin otherwise) hands back every
        # complete frame at once. Dispatch below stays strictly in frame
        # order, per frame: chaos injection, __hello__, handlers and the
        # pickle/binary dual decode behave exactly as the per-frame loop
        # did.
        framer = framepump.feed_framer(MAX_MESSAGE)
        recv_stats = self.recv_stats
        try:
            while True:
                try:
                    data = await reader.read(_READ_CHUNK)
                except (ConnectionResetError, OSError):
                    break
                if not data:
                    break  # EOF
                try:
                    bodies = framer.feed(data)
                except framepump.FrameError:
                    break  # oversize frame: corrupt/hostile peer, drop it
                if not bodies:
                    continue
                recv_stats["reads"] += 1
                recv_stats["frames"] += len(bodies)
                for body in bodies:
                    await self._dispatch_frame(conn, body)
        finally:
            framer.close()
            self._conns.discard(conn)
            if self._on_disconnect is not None:
                try:
                    res = self._on_disconnect(conn)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:  # noqa: BLE001
                    pass
            writer.close()

    async def _dispatch_frame(self, conn: "Connection", body) -> None:
        """Decode + handle ONE inbound frame (the per-frame semantics of
        the old read_frame loop, verbatim)."""
        msg = _loads_body(body)
        was_binary = wire.is_binary(body)
        plan = _chaos.get()
        if plan is not None:
            # Fault injection (off unless a chaos plan is installed;
            # the common path pays one module-global None check).
            delay = plan.frame_delay_s()
            if delay > 0.0:
                await asyncio.sleep(delay)
            if plan.should_drop_frame(conn.meta):
                return
        if was_binary:
            # Observed capability: this peer talks binary, so
            # responses/pushes to it may too — but only v1 frames
            # are PROVEN; higher versions must be advertised.
            if not conn.meta.get("wire"):
                conn.meta["wire"] = 1
        mtype = msg.get("type")
        if mtype == "__hello__":
            # Connection-level capability advertisement (sent once
            # by RpcClient on connect): the peer can DECODE this
            # wire version, so responses/pushes may use its frames.
            conn.meta["wire"] = int(msg.get("wire") or 1)
            return
        handler = self._handlers.get(mtype)
        if handler is None:
            resp = {"ok": False, "error": f"unknown type {mtype}"}
        else:
            t0 = time.monotonic()
            try:
                resp = await handler(msg, conn)
            except Exception as e:  # noqa: BLE001 - reported to caller
                import traceback
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "traceback": traceback.format_exc()}
            finally:
                cell = self.handler_stats.get(mtype)
                if cell is None:
                    cell = self.handler_stats[mtype] = [0, 0.0]
                cell[0] += 1
                cell[1] += time.monotonic() - t0
        if "rpc_id" in msg and resp is not None:
            resp["rpc_id"] = msg["rpc_id"]
            await conn.send(resp, req_type=mtype)

    async def stop(self):
        if self._server is not None:
            self._server.close()
            # Force live client connections shut, else wait_closed() blocks
            # until every client hangs up on its own.
            for conn in list(self._conns):
                try:
                    conn.writer.close()
                except Exception:  # noqa: BLE001
                    pass
            await self._server.wait_closed()


class Connection:
    """One inbound connection; supports locked writes for server push."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.meta: Dict[str, Any] = {}  # handler-attached identity (node id...)
        self._wlock = asyncio.Lock()

    async def send(self, msg: Dict[str, Any],
                   req_type: Optional[str] = None):
        """Push/respond on this connection. Binary fast-path encoding is
        used when the peer has advertised or shown wire capability
        (``meta["wire"]``); ``req_type`` selects a response codec."""
        peer = int(self.meta.get("wire") or 0)
        bufs = encode_frames(msg, binary_ok=bool(peer), req_type=req_type,
                             peer_wire=peer or 1)
        async with self._wlock:
            self.writer.writelines(bufs)
            await self.writer.drain()

    def send_nowait(self, msg: Dict[str, Any]) -> None:
        """Synchronous push from the event-loop thread: buffers into the
        transport without awaiting drain. For small high-rate pushes whose
        peer demonstrably consumes (e.g. execute_task to a local worker) —
        the await-per-send of the locked path was pure overhead there.
        writelines() is atomic into the transport buffer, so interleaving
        with concurrent send() calls is safe."""
        peer = int(self.meta.get("wire") or 0)
        bufs = encode_frames(msg, binary_ok=bool(peer),
                             peer_wire=peer or 1)
        self.writer.writelines(bufs)


# ---------------------------------------------------------------------------
# blocking client side
# ---------------------------------------------------------------------------

class RpcClient:
    """Thread-safe blocking RPC client with a reader thread.

    Responses are matched by rpc_id; unsolicited messages (server push) go to
    the ``push_handler``.
    """

    def __init__(self, host: str, port: int,
                 push_handler: Optional[Callable[[Dict], None]] = None,
                 timeout: float = 30.0,
                 on_close: Optional[Callable[[], None]] = None,
                 binary: Optional[bool] = None,
                 io_stats: Optional[Dict[str, int]] = None,
                 push_batch_handler: Optional[
                     Callable[[List[Dict]], None]] = None):
        self._on_close = on_close
        self.addr = (host, port)
        # Send-side wire choice: binary fast path by default (the codec is
        # part of this release; receivers always decode both), pinnable to
        # pickle per client or process-wide via RAY_TPU_WIRE_PICKLE_ONLY.
        self._binary = (not wire.pickle_only()) if binary is None else binary
        # frames/writes counters: the coalescing regression guard reads
        # these (one write per completion wave, not one per frame).
        # late_drops counts responses that arrived after their call()
        # timed out and unregistered — dropped, never misrouted to the
        # push handler (node_stats ships this dict, so doctor bundles and
        # handler-stats readers see it).
        self.io_stats = io_stats if io_stats is not None else {
            "frames_sent": 0, "writes": 0}
        self.io_stats.setdefault("late_drops", 0)
        # Reader-thread seconds blocked waiting for bytes (recv/pump
        # wait). The observatory's socket-dwell bucket: time a client
        # spends off-CPU waiting on its peer, which the old wall-clock
        # sampler used to report as self-time.
        self.io_stats.setdefault("recv_dwell_s", 0.0)
        self._sock = socket.create_connection(self.addr, timeout=timeout)
        self._sock.settimeout(None)
        # Small control messages back-to-back must not wait out Nagle +
        # delayed-ACK (a one-way notification followed by a call would
        # stall ~40 ms).
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Native frame pump (framepump.cc): the reader thread's recv +
        # frame split run in C with the GIL released, frames delivered in
        # batches; None pins the pure-Python per-frame loop
        # (RAY_TPU_NATIVE_FRAMEPUMP=0 or no toolchain). The send twin
        # gates _send_buffers' native scatter-gather path.
        self._pump = framepump.reader_pump(self._sock.fileno(), MAX_MESSAGE)
        self._native_send = framepump.enabled()
        self._wlock = threading.Lock()
        self._pending: Dict[int, "threading.Event"] = {}
        self._responses: Dict[int, Dict] = {}
        self._counter = itertools.count(1)
        self._push_handler = push_handler
        # Optional batched push delivery: a run of consecutive pushes in
        # one recv batch is handed over in ONE call (the worker inbox
        # feed), with order relative to interleaved responses preserved.
        self._push_batch_handler = push_batch_handler
        self._closed = False
        # The highest wire version the SERVER side of this connection can
        # parse: conservative v1 until a handshake (register_* response)
        # reports better — v2-only frames fall back to pickle until then.
        self.peer_wire = 1
        # Advertise our own decode capability so server->client pushes and
        # responses may use this wire version's frames (decode support is
        # unconditional, so this holds even for pickle-pinned senders).
        try:
            with self._wlock:
                self._send_buffers(
                    [_dumps({"type": "__hello__",
                             "wire": wire.WIRE_VERSION})], 1)
        except OSError:
            pass
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self):
        try:
            io_stats = self.io_stats
            if self._pump is not None:
                # Native arm: recv + frame split run in C with the GIL
                # released; each wakeup hands back a whole batch of bodies.
                while not self._closed:
                    t0 = time.perf_counter()
                    batch = self._pump.pump()
                    io_stats["recv_dwell_s"] += time.perf_counter() - t0
                    if batch is None:
                        break  # EOF / socket error / oversize frame
                    self._dispatch_frames(batch)
            else:
                while not self._closed:
                    t0 = time.perf_counter()
                    header = self._recv_exact(8)
                    io_stats["recv_dwell_s"] += time.perf_counter() - t0
                    if header is None:
                        break
                    (length,) = _LEN.unpack(header)
                    if length > MAX_MESSAGE:
                        break  # corrupt/hostile peer: drop the connection
                    body = self._recv_exact(length)
                    if body is None:
                        break
                    self._dispatch_frames((body,))
        except OSError:
            pass
        finally:
            # The pump handle is destroyed HERE, by the one thread that
            # pumps it, never from close() racing a blocked recv.
            if self._pump is not None:
                self._pump.close()
            # Benign race: GIL-atomic latch flag, writers on both sides
            # only ever store True; readers tolerate either order.
            # raylint: disable=thread-shared-state
            self._closed = True
            for ev in list(self._pending.values()):
                ev.set()
            if self._on_close is not None:
                try:
                    self._on_close()
                except Exception:  # noqa: BLE001
                    pass

    # raylint: hotpath — every inbound client frame funnels through here
    def _dispatch_frames(self, bodies) -> None:
        """Route a batch of frame bodies strictly in order. Consecutive
        pushes coalesce into one ``push_batch_handler`` call when one is
        installed, but the batch is always flushed before a later
        response's caller is woken, so global frame order is preserved."""
        push_batch: List[Dict] = []
        batch_h = self._push_batch_handler
        for body in bodies:
            msg = _loads_body(body)
            rpc_id = msg.get("rpc_id")
            if rpc_id is not None:
                if push_batch:
                    self._flush_push_batch(push_batch)
                    push_batch = []
                ev = self._pending.get(rpc_id)
                if ev is not None:
                    self._responses[rpc_id] = msg
                    ev.set()
                else:
                    # Response landed after call() timed out and
                    # unregistered: drop it — routing it to the push
                    # handler would hand an RPC reply to code expecting
                    # server pushes. (Binary pushes never carry rpc_id:
                    # wire.decode strips it when 0, and servers only set
                    # it when echoing a request.)
                    self._responses.pop(rpc_id, None)
                    # Benign race: stats counter bumped off-lock from the
                    # reader thread; a lost increment under contention
                    # costs one tick of a diagnostic number, never a
                    # protocol fault.
                    # raylint: disable=thread-shared-state
                    self.io_stats["late_drops"] += 1
            elif batch_h is not None:
                push_batch.append(msg)
            elif self._push_handler is not None:
                try:
                    self._push_handler(msg)
                except Exception:  # noqa: BLE001
                    pass
        if push_batch:
            self._flush_push_batch(push_batch)

    def _flush_push_batch(self, msgs: List[Dict]) -> None:
        try:
            self._push_batch_handler(msgs)
        except Exception:  # noqa: BLE001
            pass

    # raylint: hotpath — 14% of head / 60% of worker self-time (PR 6 profile)
    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    # raylint: hotpath — every frame every client sends funnels through here
    def _send_buffers(self, bufs: List[bytes], frames: int) -> None:
        """One scatter-gather write for any number of frames. Caller holds
        ``_wlock``. Partial sendmsg results are continued manually."""
        self.io_stats["frames_sent"] += frames
        self.io_stats["writes"] += 1
        if self._native_send and framepump.sendv(self._sock.fileno(), bufs):
            return
        try:
            sendmsg = self._sock.sendmsg
        except AttributeError:  # platform without sendmsg
            self._sock.sendall(b"".join(bufs))
            return
        views = [memoryview(b) for b in bufs]
        while views:
            # Stay well under IOV_MAX per syscall (EMSGSIZE otherwise).
            sent = sendmsg(views[:512])
            while views and sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            if sent:
                views[0] = views[0][sent:]

    def call(self, msg: Dict[str, Any], timeout: Optional[float] = 60.0) -> Dict:
        if self._closed:
            raise ConnectionError(f"connection to {self.addr} closed")
        rpc_id = next(self._counter)
        msg = dict(msg, rpc_id=rpc_id)
        ev = threading.Event()
        self._pending[rpc_id] = ev
        bufs = encode_frames(msg, binary_ok=self._binary,
                             peer_wire=self.peer_wire)
        with self._wlock:
            self._send_buffers(bufs, 1)
        if not ev.wait(timeout):
            self._pending.pop(rpc_id, None)
            # The reader may have stored the response between the wait
            # expiring and the pop above; reap it so _responses can't
            # accumulate entries nobody will ever claim.
            self._responses.pop(rpc_id, None)
            raise TimeoutError(f"rpc {msg['type']} to {self.addr} timed out")
        self._pending.pop(rpc_id, None)
        resp = self._responses.pop(rpc_id, None)
        if resp is None:
            raise ConnectionError(f"connection to {self.addr} lost mid-call")
        if resp.get("ok") is False:
            raise RuntimeError(
                f"rpc {msg['type']} failed: {resp.get('error')}\n"
                f"{resp.get('traceback', '')}"
            )
        return resp

    def send_oneway(self, msg: Dict[str, Any]) -> None:
        if self._closed:
            raise ConnectionError(f"connection to {self.addr} closed")
        bufs = encode_frames(msg, binary_ok=self._binary,
                             peer_wire=self.peer_wire)
        with self._wlock:
            self._send_buffers(bufs, 1)

    def probe_wire(self, timeout: float = 5.0) -> int:
        """Probe the server's advertised wire version (any server exposing
        a ``wire_probe`` handler) and lift this client's send floor to it.
        Cached per connection — peers that don't answer stay at the
        conservative v1 floor, so every frame they get is parseable."""
        w = getattr(self, "_srv_wire", None)
        if w is None:
            try:
                resp = self.call({"type": "wire_probe"}, timeout=timeout)
                w = int(resp.get("wire", 1)) if resp.get("ok") else 1
            except Exception:  # noqa: BLE001 - old peer / flaky link => v1
                w = 1
            self._srv_wire = w
            if w > self.peer_wire:
                self.peer_wire = w
        return int(w)

    def send_oneway_many(self, msgs: List[Dict[str, Any]]) -> None:
        """Coalesced oneways: N frames, ONE locked scatter-write. FIFO
        order within the list is preserved on the wire, so e.g. a wave's
        object registrations still precede its task_done batch."""
        if not msgs:
            return
        if self._closed:
            raise ConnectionError(f"connection to {self.addr} closed")
        bufs: List[bytes] = []
        for msg in msgs:
            bufs.extend(encode_frames(msg, binary_ok=self._binary,
                                      peer_wire=self.peer_wire))
        with self._wlock:
            self._send_buffers(bufs, len(msgs))

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def _parse_addr_list(spec: str) -> List[Tuple[str, int]]:
    """Parse "host:port,host:port" (the ``gcs_addrs`` config knob /
    RAY_TPU_GCS_ADDRS) into an address list; malformed entries are skipped."""
    out: List[Tuple[str, int]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        host, _, port = part.rpartition(":")
        try:
            out.append((host, int(port)))
        except ValueError:
            continue
    return out


class ResilientClient:
    """RpcClient that transparently reconnects across server restarts.

    Used for GCS connections (reference: clients retry against the restarted
    GCS in test_gcs_fault_tolerance.py). A call that hits a dead socket
    re-dials with jittered exponential backoff — sleep =
    min(cap, base * 2^attempt) * uniform[0.5, 1.5) — until ``retry_window``
    elapses; window and backoff shape come from RayConfig
    (``gcs_retry_window_s`` / ``gcs_retry_backoff_base_s`` / ``_cap_s``).

    For head HA the client holds a multi-address list (primary + warm
    standbys, extended by ``addrs`` and the ``gcs_addrs`` knob) and rotates
    through it on every failed dial, so a promoted standby is found without
    reconfiguration. A ``NOT_LEADER`` rejection from a fenced or demoted
    head is treated like a dead socket: drop, rotate, retry. After every
    successful RE-dial (not the first connect) ``on_reconnect`` fires with
    the live client so callers can idempotently re-register themselves
    (re-publish inventory, re-arm rings and long-polls) with the new leader.
    """

    def __init__(self, host: str, port: int,
                 push_handler: Optional[Callable[[Dict], None]] = None,
                 retry_window: Optional[float] = None,
                 addrs: Optional[Sequence[Tuple[str, int]]] = None,
                 on_reconnect: Optional[Callable[["RpcClient"], None]] = None):
        cfg = get_config()
        self._retry_window = (cfg.gcs_retry_window_s if retry_window is None
                              else retry_window)
        self._backoff_base = max(1e-3, cfg.gcs_retry_backoff_base_s)
        self._backoff_cap = max(self._backoff_base, cfg.gcs_retry_backoff_cap_s)
        self._addrs: List[Tuple[str, int]] = [(host, int(port))]
        for cand in list(addrs or []) + _parse_addr_list(cfg.gcs_addrs):
            cand = (cand[0], int(cand[1]))
            if cand not in self._addrs:
                self._addrs.append(cand)
        self._addr_idx = 0
        self.addr = self._addrs[0]  # currently-targeted address
        self._push_handler = push_handler
        self._on_reconnect = on_reconnect
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._closed = False
        self._ever_connected = False
        # Reentrancy latch: an on_reconnect callback typically calls back
        # through this client; a failure inside it must not recurse into
        # another callback invocation.
        self._reconnect_tls = threading.local()
        # Shared across reconnects so coalescing counters survive re-dials.
        self.io_stats: Dict[str, int] = {"frames_sent": 0, "writes": 0}
        self._ensure()

    def _ensure(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise ConnectionError(f"client to {self.addr} closed")
            if self._client is not None and not self._client._closed:
                return self._client
            self.addr = self._addrs[self._addr_idx]
            self._client = RpcClient(
                *self.addr, push_handler=self._push_handler,
                io_stats=self.io_stats)
            client = self._client
            is_reconnect = self._ever_connected
            self._ever_connected = True
        if (is_reconnect and self._on_reconnect is not None
                and not getattr(self._reconnect_tls, "active", False)):
            # Outside the lock: the callback re-registers through this very
            # client (call() -> _ensure() would deadlock otherwise).
            self._reconnect_tls.active = True
            try:
                self._on_reconnect(client)
            except Exception:  # noqa: BLE001 - re-registration is best-effort
                pass
            finally:
                self._reconnect_tls.active = False
        return client

    def _drop(self, rotate: bool = False) -> None:
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None
            if rotate and len(self._addrs) > 1:
                self._addr_idx = (self._addr_idx + 1) % len(self._addrs)

    def _backoff(self, attempt: int) -> None:
        sleep = min(self._backoff_cap, self._backoff_base * (2 ** attempt))
        time.sleep(sleep * (0.5 + random.random()))

    def call(self, msg: Dict[str, Any], timeout: Optional[float] = 60.0) -> Dict:
        deadline = time.monotonic() + self._retry_window
        attempt = 0
        while True:
            try:
                return self._ensure().call(msg, timeout=timeout)
            except (ConnectionError, OSError, TimeoutError):
                # TimeoutError is retried too: a paused head or a chaos-
                # dropped frame looks like a hang, and every GCS mutation
                # is idempotent/deduped so re-sending is safe.
                self._drop(rotate=True)
                if self._closed or time.monotonic() > deadline:
                    raise
            except RuntimeError as e:
                # A fenced/demoted head rejects mutations with NOT_LEADER;
                # the real leader is (or will be) at another address.
                if "NOT_LEADER" not in str(e):
                    raise
                self._drop(rotate=True)
                if self._closed or time.monotonic() > deadline:
                    raise
            self._backoff(attempt)
            attempt += 1

    def send_oneway(self, msg: Dict[str, Any]) -> None:
        try:
            self._ensure().send_oneway(msg)
        except (ConnectionError, OSError):
            self._drop(rotate=True)
            # one immediate retry; oneway messages are periodic (heartbeats)
            # so a miss is recovered by the next tick anyway
            try:
                self._ensure().send_oneway(msg)
            except (ConnectionError, OSError):
                pass

    def send_oneway_many(self, msgs: List[Dict[str, Any]]) -> None:
        try:
            self._ensure().send_oneway_many(msgs)
        except (ConnectionError, OSError):
            self._drop(rotate=True)
            try:
                self._ensure().send_oneway_many(msgs)
            except (ConnectionError, OSError):
                pass

    def close(self):
        self._closed = True
        self._drop()
