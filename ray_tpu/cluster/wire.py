"""Binary hot-path wire codec for the cluster control plane.

The default wire format (``protocol.py``) is a length-prefixed pickle —
general, but on the per-task hot path the pickle of nested dicts is the
single largest control-plane CPU line on both ends of every edge. This
module gives the highest-frequency message types a compact struct-packed
encoding:

  * ``submit_batch``      driver -> GCS      (task specs, the submit wave)
  * ``task_done_batch``   controller -> GCS  (completion wave)
  * ``locations_batch``   driver -> GCS      (+ its response; the get() loop)
  * ``fetch_batch``       driver -> node     (+ its response; result blobs)
  * ``object_added``      worker/driver -> controller (arena registrations)

plus the two relay messages that carry task specs onward:

  * ``assign_batch``      GCS -> controller  (raw spec blobs, forwarded)
  * ``execute_task``      controller -> worker (one raw spec blob)
  * ``task_done``         worker -> controller (singular completion)

**Frame layout.** The transport frame stays ``[8-byte LE length][body]``.
A binary body begins with ``MAGIC`` (0xBF) + a message-code byte; anything
else (pickle bodies start with 0x80) is decoded as pickle. Receivers always
understand both, so a pickle-only peer can share a socket with a
binary-capable one; senders only emit binary for the types above, and only
once the peer is known-capable (advertised ``wire`` version on
register_node/register_worker, or observed binary traffic on the
connection). ``RAY_TPU_WIRE_PICKLE_ONLY=1`` pins a process to pickle on the
send side (rolling-upgrade escape hatch); decode support is unconditional.

**Opaque task-spec relay.** ``encode_task_spec`` packs a task payload once
on the driver. The GCS decodes only the fixed header (ids, deps, resources —
what placement and lineage need) and keeps the original bytes in
``payload["_spec"]``; the dispatch path forwards those bytes verbatim inside
``assign_batch``/``execute_task`` frames, so the args/kwargs blobs are
deserialized exactly once, at the executing worker. Zero task-spec
re-serializations happen on the GCS (pinned by ``relay:opaque`` /
``relay:pickled`` counters in its handler stats).

Encoders return a *list of buffers* so callers can scatter-write
(``sendmsg`` / ``writelines``) without copying large blobs (protocol-5
out-of-band spirit: result blobs and spec bytes are passed through, not
re-joined).
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, List, Optional, Tuple

MAGIC = 0xBF
# v2 adds the inline-result frames (TASK_DONE2 / TASK_DONE_BATCH2 and the
# _LOC_INLINE location flag); v3 adds the PROFILE_STACKS stats frame; v4
# adds the state-API frames (LIST_TASKS / LIST_TASKS_RESP); v5 adds the
# head-HA frames (REPL_RECORD / REPL_TAIL / REPL_TAIL_RESP / HA_STATUS /
# HA_STATUS_RESP); v6 adds the cancellation frame (CANCEL_TASK), the
# deadline fields of task-spec v3, and the forensics task-row frame
# (LIST_TASKS_RESP2); v7 adds the exec-stamp completion twins
# (TASK_DONE3 / TASK_DONE_BATCH3): every completion carries worker-side
# wall-clock ts_exec_start/ts_exec_end so the job profiler can attribute
# queue vs exec vs registration time exactly, not just on the 1/64 trace
# sample; v8 adds the columnar hot-path frames (SUBMIT_BATCH_COLS /
# DISPATCH_WAVE): a homogeneous submit wave travels as ONE spec template
# (shared header segments) plus packed per-task columns (ids, return ids,
# arg tails) instead of N per-task structs, and the GCS relays each node's
# whole wave the same way — receivers rebuild byte-identical spec blobs by
# concatenating the template segments around the varying columns;
# v9 adds the ownership frames (OWNER_LOCATE / OWNER_FETCH /
# OWNER_PUBLISH and their responses): object results are tracked by the
# driver that created them (the owner) instead of the GCS object table —
# controllers publish completed results owner-to-owner and borrowers
# locate/fetch from the owner, so the head keeps only membership.
# v10 adds the data-plane frames (GET_OBJ_LOCATIONS /
# GET_OBJ_LOCATIONS_RESP): the per-pull directory lookup — the hottest RPC
# of a shuffle's reduce wave — carries the object id and its holders'
# native transfer endpoints (plus the directory's size column, the transfer
# scheduler's largest-first key) without pickle.
# Senders emit each frame only to peers that advertised a wire version
# that can parse it; everything else still goes out as older frames or
# pickle, so mixed-version peers interoperate per-message.
WIRE_VERSION = 10

# Message codes (one byte each). Codes are part of the wire contract:
# never renumber, only append.
SUBMIT_BATCH = 0x01
SUBMIT_BATCH_RESP = 0x02
TASK_DONE_BATCH = 0x03
LOCATIONS_BATCH = 0x04
LOCATIONS_BATCH_RESP = 0x05
FETCH_BATCH = 0x06
FETCH_BATCH_RESP = 0x07
OBJECT_ADDED = 0x08
ASSIGN_BATCH = 0x09
EXECUTE_TASK = 0x0A
TASK_DONE = 0x0B
# v2 twins of the completion frames: each "added" registration item may
# carry the serialized result inline (the small-result data plane).
TASK_DONE2 = 0x0C
TASK_DONE_BATCH2 = 0x0D
# Placement-group control ops (create / remove / status+list). Rare
# messages, but framed so a binary-only deployment never needs pickle for
# the pg control surface.
PG_CREATE = 0x0E
PG_REMOVE = 0x0F
PG_STATUS = 0x10
PG_OK = 0x11
PG_STATUS_RESP = 0x12
# Stats frame: a flight-recorder drain (folded stacks + counts) shipped to
# the GCS profile-stacks table on the 2 s stats cadence. Framed so the
# periodic observability traffic never re-enters pickle on busy links.
PROFILE_STACKS = 0x13
# State-API frames (v4): the bounded/filterable/paginated task-table query
# and its row response — framed so dashboards and `cli tasks` polling a
# busy head never re-enter pickle on the state path.
LIST_TASKS = 0x14
LIST_TASKS_RESP = 0x15
# Head-HA frames (v5). REPL_RECORD wraps one state-mutating RPC body with
# its (epoch, seq) fencing header — the unit of both the on-disk
# replication log and the over-the-wire standby tail. REPL_TAIL is the
# standby's cursor poll; its response either carries the records after the
# cursor or a full-snapshot resync when the leader's ring no longer covers
# it. HA_STATUS is the leadership probe (`cli status`, monitor, peers
# learning the leader).
REPL_RECORD = 0x16
REPL_TAIL = 0x17
REPL_TAIL_RESP = 0x18
HA_STATUS = 0x19
HA_STATUS_RESP = 0x1A
# Cancellation frame (v6): driver->GCS carries the object id of the ref
# being cancelled; GCS->controller carries the resolved task id. Framed so
# a cancel storm (a driver tearing down a large batch) doesn't re-enter
# pickle on the control path.
CANCEL_TASK = 0x1B
# v6 twin of LIST_TASKS_RESP: each row additionally carries the failure
# forensics pair (failure_cause, failure_error) — who killed the task and
# why, attributed by the containment machinery.
LIST_TASKS_RESP2 = 0x1C
# v7 twins of the completion frames: every completion additionally carries
# the worker's wall-clock execution window (ts_exec_start/ts_exec_end, two
# f64 epoch stamps) so per-job timeline assembly is exact on all tasks.
# Both use the v2 "added" item layout (has-blob flag), so they subsume the
# inline-result twins when the peer speaks v7.
TASK_DONE3 = 0x1D
TASK_DONE_BATCH3 = 0x1E
# v7 twin of LIST_TASKS_RESP2: each row additionally carries the exec
# window (ts_exec_start/ts_exec_end f64 pair) and exec_s, so the state
# API and the job profiler see worker-side stamps without pickle.
LIST_TASKS_RESP3 = 0x1F
# Columnar hot-path frames (v8). SUBMIT_BATCH_COLS carries a driver's
# submit flush as template runs (one shared spec header per run of
# same-function/same-options tasks + packed task-id / return-id / arg-tail
# columns) plus any non-conforming tasks as legacy per-task spec blobs —
# one frame either way. DISPATCH_WAVE is its GCS->controller twin: each
# node's whole dispatch wave rides as runs + singles in ONE scatter frame
# that the controller explodes locally into byte-identical spec blobs.
SUBMIT_BATCH_COLS = 0x20
DISPATCH_WAVE = 0x21
# Ownership frames (v9). The object plane moves out of the GCS: each
# driver owns the objects its job tree creates and serves them from an
# in-process owner table. OWNER_PUBLISH is the controller->owner push of
# completed inline results (bytes when the owner is remote, size+location
# only when the completion ring on the same host already carried the
# bytes); OWNER_FETCH is the borrower's pull (answered with bytes or a
# node location redirect); OWNER_LOCATE is the lightweight existence /
# size probe the consistency auditor and doctor use to verify owner-shard
# invariants without moving payloads.
OWNER_LOCATE = 0x22
OWNER_LOCATE_RESP = 0x23
OWNER_FETCH = 0x24
OWNER_FETCH_RESP = 0x25
OWNER_PUBLISH = 0x26
OWNER_PUBLISH_RESP = 0x27
# Data-plane frames (v10). GET_OBJ_LOCATIONS is the controller's per-pull
# directory lookup (object id + wait/timeout); its response carries the
# holder node ids, their RPC addresses, their native transfer endpoints
# (port 0 = no native plane: spilled/python-store holders restore over
# RPC), and the directory's size column — or the error/inline blob
# short-circuits the directory already serves.
GET_OBJ_LOCATIONS = 0x28
GET_OBJ_LOCATIONS_RESP = 0x29

# Minimum peer wire version able to parse each frame — the declarative
# manifest the static lint (raylint wire-discipline) audits: every frame
# must appear here, encoders emitting a >v1 frame must gate on peer_wire
# with a pickle fallback, and max(values) must equal WIRE_VERSION (adding
# a frame without bumping the version is a lint error).
FRAME_MIN_WIRE = {
    SUBMIT_BATCH: 1,
    SUBMIT_BATCH_RESP: 1,
    TASK_DONE_BATCH: 1,
    LOCATIONS_BATCH: 1,
    LOCATIONS_BATCH_RESP: 1,
    FETCH_BATCH: 1,
    FETCH_BATCH_RESP: 1,
    OBJECT_ADDED: 1,
    ASSIGN_BATCH: 1,
    EXECUTE_TASK: 1,
    TASK_DONE: 1,
    TASK_DONE2: 2,
    TASK_DONE_BATCH2: 2,
    PG_CREATE: 1,
    PG_REMOVE: 1,
    PG_STATUS: 1,
    PG_OK: 1,
    PG_STATUS_RESP: 1,
    PROFILE_STACKS: 3,
    LIST_TASKS: 4,
    LIST_TASKS_RESP: 4,
    REPL_RECORD: 5,
    REPL_TAIL: 5,
    REPL_TAIL_RESP: 5,
    HA_STATUS: 5,
    HA_STATUS_RESP: 5,
    CANCEL_TASK: 6,
    LIST_TASKS_RESP2: 6,
    TASK_DONE3: 7,
    TASK_DONE_BATCH3: 7,
    LIST_TASKS_RESP3: 7,
    SUBMIT_BATCH_COLS: 8,
    DISPATCH_WAVE: 8,
    OWNER_LOCATE: 9,
    OWNER_LOCATE_RESP: 9,
    OWNER_FETCH: 9,
    OWNER_FETCH_RESP: 9,
    OWNER_PUBLISH: 9,
    OWNER_PUBLISH_RESP: 9,
    GET_OBJ_LOCATIONS: 10,
    GET_OBJ_LOCATIONS_RESP: 10,
}

_PG_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")
_PG_STATES = ("PENDING", "CREATED", "RESCHEDULING", "REMOVED")
_TASK_STATES = ("PENDING", "DISPATCHED", "FINISHED", "FAILED")
_TASK_KINDS = ("task", "actor")

# Task-spec versions. v1 is the base header; v2 appends a trace context
# (sampled tasks only — unsampled specs still encode as v1, so the hot
# path's bytes are unchanged and pre-tracing decoders keep reading them);
# v3 appends the deadline fields (timeout_s + retry_on_timeout), emitted
# only for tasks that set a deadline — deadline-free specs keep their v1/v2
# bytes so pre-v6 decoders and the hot path are unchanged.
SPEC_VERSION = 1
SPEC_VERSION_TRACED = 2
SPEC_VERSION_DEADLINE = 3
# v3 flag bits.
SPEC_F_TRACE = 1
SPEC_F_RETRY_ON_TIMEOUT = 2

# Hard caps, enforced on decode: a corrupt count/length field must fail the
# frame instead of driving a multi-GB allocation.
MAX_ITEMS = 1 << 22
MAX_BLOB = 1 << 34

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I32 = struct.Struct("<i")
_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


def pickle_only() -> bool:
    """Send-side kill switch (decode support is unconditional)."""
    return os.environ.get("RAY_TPU_WIRE_PICKLE_ONLY", "") not in ("", "0")


def columnar_submit_enabled() -> bool:
    """Driver-side kill switch for the columnar submit path
    (``RAY_TPU_COLUMNAR_SUBMIT=0`` forces the per-task legacy frames —
    results must be byte-identical either way)."""
    return os.environ.get("RAY_TPU_COLUMNAR_SUBMIT", "1") != "0"


def dispatch_wave_enabled() -> bool:
    """GCS-side kill switch for columnar dispatch relay
    (``RAY_TPU_DISPATCH_WAVE=0`` materializes per-task spec blobs and
    relays legacy assign_batch frames instead)."""
    return os.environ.get("RAY_TPU_DISPATCH_WAVE", "1") != "0"


def ownership_enabled() -> bool:
    """Kill switch for the ownership object plane
    (``RAY_TPU_OWNERSHIP=0`` reverts to GCS-tracked results: drivers stop
    registering as owners, so controllers fall back to the legacy
    inline-to-GCS registration path per-object)."""
    return os.environ.get("RAY_TPU_OWNERSHIP", "1") != "0"


class WireError(ValueError):
    """Malformed binary frame (truncated, garbage, or over a cap)."""


# --------------------------------------------------------------------------
# primitive readers (all raise WireError on truncation)
# --------------------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes, off: int = 0):
        self.buf = buf
        self.off = off

    def _take(self, st: struct.Struct):
        try:
            (v,) = st.unpack_from(self.buf, self.off)
        except struct.error as e:
            raise WireError(f"truncated frame: {e}") from None
        self.off += st.size
        return v

    def u8(self) -> int:
        return self._take(_U8)

    def u16(self) -> int:
        return self._take(_U16)

    def u32(self) -> int:
        return self._take(_U32)

    def u64(self) -> int:
        return self._take(_U64)

    def i32(self) -> int:
        return self._take(_I32)

    def f32(self) -> float:
        return self._take(_F32)

    def f64(self) -> float:
        return self._take(_F64)

    def raw(self, n: int) -> bytes:
        if n < 0 or n > MAX_BLOB:
            raise WireError(f"blob length {n} out of range")
        end = self.off + n
        if end > len(self.buf):
            raise WireError("truncated frame: blob overruns body")
        out = self.buf[self.off:end]
        self.off = end
        return bytes(out) if not isinstance(out, bytes) else out

    def b8(self) -> bytes:          # small id: u8 length prefix
        return self.raw(self.u8())

    def b32(self) -> bytes:         # payload blob: u32 length prefix
        return self.raw(self.u32())

    def b64(self) -> bytes:         # large blob: u64 length prefix
        return self.raw(self.u64())

    def s(self) -> str:             # short utf-8 string
        try:
            return self.raw(self.u16()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"bad utf-8 in frame: {e}") from None

    def count(self, n: int) -> int:
        if n > MAX_ITEMS:
            raise WireError(f"item count {n} over cap")
        return n

    def done(self) -> None:
        if self.off != len(self.buf):
            raise WireError(
                f"{len(self.buf) - self.off} trailing bytes after frame")


def _b8(b: bytes) -> bytes:
    if len(b) > 255:
        raise WireError(f"id too long for u8 prefix: {len(b)}")
    return _U8.pack(len(b)) + b


def _s(v: str) -> bytes:
    raw = v.encode("utf-8")
    return _U16.pack(len(raw)) + raw


def _resources(res: Dict[str, float]) -> bytes:
    parts = [_U8.pack(len(res))]
    for k in res:
        parts.append(_s(k))
        parts.append(_F64.pack(float(res[k])))
    return b"".join(parts)


def _read_resources(r: _Reader) -> Dict[str, float]:
    n = r.u8()
    return {r.s(): r.f64() for _ in range(n)}


def _read_id_list(r: _Reader, n: int) -> List[bytes]:
    """Fast parse of n u8-length-prefixed ids: direct offset arithmetic
    (the per-id _Reader method chain dominated decode of 1k-oid polls)."""
    buf, off = r.buf, r.off
    end = len(buf)
    out = []
    for _ in range(n):
        if off >= end:
            raise WireError("truncated frame: id list overruns body")
        ln = buf[off]
        off += 1
        nxt = off + ln
        if nxt > end:
            raise WireError("truncated frame: id overruns body")
        out.append(bytes(buf[off:nxt]))
        off = nxt
    r.off = off
    return out


def _read_oids(r: _Reader) -> List[bytes]:
    return _read_id_list(r, r.count(r.u16()))


def _oids(ids) -> bytes:
    parts = [_U16.pack(len(ids))]
    for oid in ids:
        parts.append(_b8(oid))
    return b"".join(parts)


# --------------------------------------------------------------------------
# task spec codec
# --------------------------------------------------------------------------

def encode_spec_segments(p: Dict[str, Any]) -> Tuple[bytes, bytes]:
    """The two spec-header segments shared by every task of a columnar run:
    ``seg_a`` (fn_id | name | max_retries — the bytes between the task id
    and the return ids) and ``seg_b`` (deps | pin_refs | resources — the
    bytes between the return ids and the args tail). Only v1 specs (no
    trace, no deadline extension) split this way; the columnar path keeps
    traced/deadline tasks on the per-task frames."""
    seg_a = b"".join((
        _b8(p.get("fn_id", b"")),
        _s(p.get("name", "") or ""),
        _I32.pack(int(p.get("max_retries", 0))),
    ))
    seg_b = b"".join((
        _oids(p.get("deps", ())),
        _oids(p.get("pin_refs", ())),
        _resources(p.get("resources", {})),
    ))
    return seg_a, seg_b


def encode_spec_tail(p: Dict[str, Any]) -> bytes:
    """The per-task varying suffix of a spec: the args + kwargs sections."""
    args = p.get("args", ())
    parts = [_U16.pack(len(args))]
    for kind, payload in args:
        parts.append(_U8.pack(1 if kind == "ref" else 0))
        parts.append(_U32.pack(len(payload)))
        parts.append(payload)
    kwargs = p.get("kwargs", {}) or {}
    parts.append(_U16.pack(len(kwargs)))
    for key, (kind, payload) in kwargs.items():
        parts.append(_s(key))
        parts.append(_U8.pack(1 if kind == "ref" else 0))
        parts.append(_U32.pack(len(payload)))
        parts.append(payload)
    return b"".join(parts)


def build_spec(ver: int, seg_a: bytes, seg_b: bytes, task_id: bytes,
               return_ids, tail: bytes) -> bytes:
    """Reassemble one task's full spec bytes from its run template —
    byte-identical to ``encode_task_spec`` of the original payload (the
    run is just the spec split at its task-varying fields)."""
    return b"".join((_U8.pack(ver), _b8(task_id), seg_a,
                     _oids(return_ids), seg_b, tail))


def build_spec_from_run(run: Dict[str, Any], i: int) -> bytes:
    """Task ``i`` of a decoded columnar run, as full spec bytes."""
    return build_spec(int(run.get("ver", SPEC_VERSION)),
                      run["seg_a"], run["seg_b"], run["task_ids"][i],
                      run["return_oids"][i], run["tails"][i])


def encode_task_spec(p: Dict[str, Any]) -> bytes:
    """Pack a task payload once, on the owner. Header fields (what the GCS
    and controllers need) come first so relays parse them without touching
    the args; args/kwargs blobs are appended verbatim. A sampled task's
    trace context rides as a versioned header extension (v2)."""
    trace = p.get("trace")
    timeout_s = p.get("timeout_s")
    if timeout_s is not None:
        ver = SPEC_VERSION_DEADLINE
    elif trace:
        ver = SPEC_VERSION_TRACED
    else:
        ver = SPEC_VERSION
    seg_a, seg_b = encode_spec_segments(p)
    parts = [
        _U8.pack(ver),
        _b8(p["task_id"]),
        seg_a,
        _oids(p.get("return_ids", ())),
        seg_b,
    ]
    if ver == SPEC_VERSION_DEADLINE:
        flags = (SPEC_F_TRACE if trace else 0) \
            | (SPEC_F_RETRY_ON_TIMEOUT if p.get("retry_on_timeout") else 0)
        parts.append(_U8.pack(flags))
        parts.append(_F64.pack(float(timeout_s)))
        if trace:
            parts.append(_b8(trace))
    elif trace:
        parts.append(_b8(trace))
    parts.append(encode_spec_tail(p))
    return b"".join(parts)


def _decode_spec_header(r: _Reader) -> Dict[str, Any]:
    ver = r.u8()
    if ver not in (SPEC_VERSION, SPEC_VERSION_TRACED, SPEC_VERSION_DEADLINE):
        raise WireError(f"unknown task-spec version {ver}")
    out = {
        "task_id": r.b8(),
        "fn_id": r.b8(),
        "name": r.s(),
        "max_retries": r.i32(),
        "return_ids": _read_oids(r),
        "deps": _read_oids(r),
        "pin_refs": _read_oids(r),
        "resources": _read_resources(r),
    }
    if ver == SPEC_VERSION_DEADLINE:
        flags = r.u8()
        out["timeout_s"] = r.f64()
        if flags & SPEC_F_RETRY_ON_TIMEOUT:
            out["retry_on_timeout"] = True
        if flags & SPEC_F_TRACE:
            out["trace"] = r.b8()
    elif ver == SPEC_VERSION_TRACED:
        out["trace"] = r.b8()
    return out


def decode_task_spec_header(blob: bytes) -> Dict[str, Any]:
    """Relay-side parse: ids/deps/resources only; the original bytes ride
    along as ``_spec`` so dispatch can forward them without re-encoding."""
    out = _decode_spec_header(_Reader(blob))
    out["_spec"] = blob
    return out


def decode_task_spec(blob: bytes) -> Dict[str, Any]:
    """Executing-worker parse: the full spec, args included."""
    r = _Reader(blob)
    out = _decode_spec_header(r)
    n_args = r.count(r.u16())
    out["args"] = [("ref" if r.u8() else "value", r.b32())
                   for _ in range(n_args)]
    n_kw = r.count(r.u16())
    kwargs = {}
    for _ in range(n_kw):
        key = r.s()
        kwargs[key] = ("ref" if r.u8() else "value", r.b32())
    out["kwargs"] = kwargs
    r.done()
    return out


# --------------------------------------------------------------------------
# message encoders — each returns a list of buffers (no length header)
# --------------------------------------------------------------------------

def _head(code: int, rpc_id) -> bytes:
    return struct.pack("<BBQ", MAGIC, code, int(rpc_id or 0))


def _enc_submit_batch(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    tasks = msg["tasks"]
    out = [_head(SUBMIT_BATCH, msg.get("rpc_id")), _U32.pack(len(tasks))]
    for t in tasks:
        blob = t.get("_spec") if isinstance(t, dict) else t
        if blob is None:
            blob = encode_task_spec(t)
        out.append(_U32.pack(len(blob)))
        out.append(blob)
    return out


def _dec_submit_batch(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    tasks = [decode_task_spec_header(r.b32()) for _ in range(n)]
    r.done()
    return {"type": "submit_batch", "tasks": tasks, "rpc_id": rpc_id}


def _enc_submit_batch_resp(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    return [_head(SUBMIT_BATCH_RESP, msg.get("rpc_id")),
            _U32.pack(int(msg.get("count", 0)))]


def _dec_submit_batch_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    count = r.u32()
    r.done()
    return {"ok": True, "count": count, "rpc_id": rpc_id}


def _added_has_blob(added) -> bool:
    return any(len(ent) > 2 and ent[2] is not None for ent in added)


def _enc_added_v1(out: List[bytes], added) -> None:
    out.append(_U16.pack(len(added)))
    for ent in added:
        out.append(_b8(ent[0]))
        out.append(_U64.pack(int(ent[1])))


def _enc_added_v2(out: List[bytes], added) -> None:
    """v2 added item: oid, size, has-blob flag, optional inline result."""
    out.append(_U16.pack(len(added)))
    for ent in added:
        out.append(_b8(ent[0]))
        out.append(_U64.pack(int(ent[1])))
        blob = ent[2] if len(ent) > 2 else None
        if blob is None:
            out.append(_U8.pack(0))
        else:
            out.append(_U8.pack(1))
            out.append(_U32.pack(len(blob)))
            out.append(blob)    # pass-through buffer: no copy on encode


def _dec_added_v1(r: _Reader) -> list:
    n = r.count(r.u16())
    return [[r.b8(), r.u64()] for _ in range(n)]


def _dec_added_v2(r: _Reader) -> list:
    n = r.count(r.u16())
    out = []
    for _ in range(n):
        oid = r.b8()
        size = r.u64()
        blob = r.b32() if r.u8() else None
        out.append([oid, size, blob])
    return out


def _enc_task_done_batch(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    items = msg["items"]
    v3 = any(float(it.get("ts_exec_end") or 0.0) > 0.0 for it in items)
    if v3 and peer_wire < 7:
        return None  # pre-v7 peer can't parse exec stamps: pickle carries it
    v2 = any(_added_has_blob(it.get("added") or ()) for it in items)
    if v2 and peer_wire < 2:
        return None  # v1 peer can't parse inline items: pickle carries it
    code = TASK_DONE_BATCH3 if v3 \
        else (TASK_DONE_BATCH2 if v2 else TASK_DONE_BATCH)
    out = [_head(code, msg.get("rpc_id")), _s(msg["node_id"]),
           _U32.pack(len(items))]
    enc_added = _enc_added_v2 if (v2 or v3) else _enc_added_v1
    for it in items:
        out.append(_b8(it.get("task_id") or b""))
        out.append(_resources(it.get("resources") or {}))
        out.append(_F32.pack(float(it.get("exec_s", 0.0))))
        out.append(_F32.pack(float(it.get("reg_s", 0.0))))
        if v3:
            out.append(_F64.pack(float(it.get("ts_exec_start") or 0.0)))
            out.append(_F64.pack(float(it.get("ts_exec_end") or 0.0)))
        enc_added(out, it.get("added") or ())
    return out


def _dec_task_done_batch(r: _Reader, rpc_id, v2: bool = False,
                         v3: bool = False) -> Dict[str, Any]:
    node_id = r.s()
    n = r.count(r.u32())
    dec_added = _dec_added_v2 if (v2 or v3) else _dec_added_v1
    items = []
    for _ in range(n):
        tid = r.b8()
        item = {"task_id": tid or None,
                "resources": _read_resources(r),
                "exec_s": r.f32(), "reg_s": r.f32()}
        if v3:
            item["ts_exec_start"] = r.f64()
            item["ts_exec_end"] = r.f64()
        item["added"] = dec_added(r)
        items.append(item)
    r.done()
    return {"type": "task_done_batch", "node_id": node_id, "items": items,
            "rpc_id": rpc_id}


def _dec_task_done_batch2(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_task_done_batch(r, rpc_id, v2=True)


def _dec_task_done_batch3(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_task_done_batch(r, rpc_id, v3=True)


def _enc_locations_batch(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    oids = msg["object_ids"]
    out = [_head(LOCATIONS_BATCH, msg.get("rpc_id")),
           _F64.pack(float(msg.get("wait_s") or 0.0)),
           _F32.pack(float(msg.get("wave_s") or 0.0)),
           _U8.pack(1 if msg.get("probe", True) else 0),
           _U32.pack(len(oids))]
    for oid in oids:
        out.append(_b8(oid))
    return out


def _dec_locations_batch(r: _Reader, rpc_id) -> Dict[str, Any]:
    wait_s = r.f64()
    wave_s = r.f32()
    probe = bool(r.u8())
    oids = _read_id_list(r, r.count(r.u32()))
    r.done()
    return {"type": "locations_batch", "object_ids": oids,
            "wait_s": wait_s, "wave_s": wave_s, "probe": probe,
            "rpc_id": rpc_id}


_LOC_ERROR = 1
_LOC_SPILLED = 2
_LOC_INLINE = 4


def _enc_locations_batch_resp(msg, peer_wire: int = WIRE_VERSION
                              ) -> List[bytes]:
    objects = msg.get("objects", {})
    if peer_wire < 2 and any(info.get("inline_blob") is not None
                             for info in objects.values()):
        return None  # v1 peer can't parse _LOC_INLINE: pickle carries it
    out = [_head(LOCATIONS_BATCH_RESP, msg.get("rpc_id")),
           _U32.pack(len(objects))]
    for oid, info in objects.items():
        out.append(_b8(oid))
        blob = info.get("error_blob")
        if blob is not None:
            out.append(_U8.pack(_LOC_ERROR))
            out.append(_U64.pack(len(blob)))
            out.append(blob)
            continue
        blob = info.get("inline_blob")
        if blob is not None:
            # Inline small result: the bytes ride the completion push —
            # the caller needs no address and no fetch RPC at all.
            out.append(_U8.pack(_LOC_INLINE))
            out.append(_U64.pack(len(blob)))
            out.append(blob)
            continue
        out.append(_U8.pack(_LOC_SPILLED if info.get("spilled") else 0))
        addrs = info.get("addresses", [])
        transfer = info.get("transfer_addresses", [])
        out.append(_U8.pack(len(addrs)))
        for i, addr in enumerate(addrs):
            t = transfer[i] if i < len(transfer) else [addr[0], 0]
            out.append(_s(addr[0]))
            out.append(_U32.pack(int(addr[1])))
            out.append(_s(t[0]))
            out.append(_U32.pack(int(t[1])))
    return out


def _dec_locations_batch_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    objects = {}
    for _ in range(n):
        oid = r.b8()
        flags = r.u8()
        if flags & _LOC_ERROR:
            objects[oid] = {"error_blob": r.b64()}
            continue
        if flags & _LOC_INLINE:
            objects[oid] = {"inline_blob": r.b64()}
            continue
        n_addr = r.u8()
        addrs, transfer = [], []
        for _ in range(n_addr):
            addrs.append([r.s(), r.u32()])
            transfer.append([r.s(), r.u32()])
        info = {"addresses": addrs, "transfer_addresses": transfer}
        if flags & _LOC_SPILLED:
            info["spilled"] = True
        objects[oid] = info
    r.done()
    return {"ok": True, "objects": objects, "rpc_id": rpc_id}


def _enc_fetch_batch(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    oids = msg["object_ids"]
    out = [_head(FETCH_BATCH, msg.get("rpc_id")), _U32.pack(len(oids))]
    for oid in oids:
        out.append(_b8(oid))
    return out


def _dec_fetch_batch(r: _Reader, rpc_id) -> Dict[str, Any]:
    oids = _read_id_list(r, r.count(r.u32()))
    r.done()
    return {"type": "fetch_batch", "object_ids": oids, "rpc_id": rpc_id}


def _enc_fetch_batch_resp(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    blobs = msg.get("blobs", {})
    out = [_head(FETCH_BATCH_RESP, msg.get("rpc_id")), _U32.pack(len(blobs))]
    for oid, blob in blobs.items():
        out.append(_b8(oid))
        out.append(_U64.pack(len(blob)))
        out.append(blob)    # pass-through buffer: no copy on encode
    return out


def _dec_fetch_batch_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    blobs = {}
    for _ in range(n):
        oid = r.b8()
        blobs[oid] = r.b64()
    r.done()
    return {"ok": True, "blobs": blobs, "rpc_id": rpc_id}


def _enc_object_added(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    return [_head(OBJECT_ADDED, msg.get("rpc_id")),
            _b8(msg["object_id"]), _U64.pack(int(msg.get("size", 0)))]


def _dec_object_added(r: _Reader, rpc_id) -> Dict[str, Any]:
    oid = r.b8()
    size = r.u64()
    r.done()
    return {"type": "object_added", "object_id": oid, "size": size,
            "rpc_id": rpc_id}


def _enc_assign_batch(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    tasks = msg["tasks"]
    blobs = []
    for t in tasks:
        blob = t.get("_spec")
        if blob is None:
            return None  # mixed batch: pickle carries it
        blobs.append(blob)
    out = [_head(ASSIGN_BATCH, msg.get("rpc_id")), _U32.pack(len(blobs))]
    for blob in blobs:
        out.append(_U32.pack(len(blob)))
        out.append(blob)    # raw relay: spec bytes forwarded verbatim
    return out


def _dec_assign_batch(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    tasks = [decode_task_spec_header(r.b32()) for _ in range(n)]
    r.done()
    return {"type": "assign_batch", "tasks": tasks, "rpc_id": rpc_id}


def _enc_execute_task(msg, peer_wire: int = WIRE_VERSION) -> Optional[List[bytes]]:
    blob = msg.get("_spec")
    if blob is None:
        return None
    return [_head(EXECUTE_TASK, msg.get("rpc_id")),
            _U64.pack(len(blob)), blob]


def _dec_execute_task(r: _Reader, rpc_id) -> Dict[str, Any]:
    blob = r.b64()
    r.done()
    # Terminal hop: the executing worker is the only receiver, so the full
    # spec (args included) is decoded here — the one decode in the relay.
    out = decode_task_spec(blob)
    out["type"] = "execute_task"
    out["rpc_id"] = rpc_id
    return out


def _enc_task_done(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    added = msg.get("added", ())
    v3 = float(msg.get("ts_exec_end") or 0.0) > 0.0
    if v3 and peer_wire < 7:
        return None  # pre-v7 peer can't parse exec stamps: pickle carries it
    v2 = _added_has_blob(added)
    if v2 and peer_wire < 2:
        return None  # v1 peer can't parse inline items: pickle carries it
    code = TASK_DONE3 if v3 else (TASK_DONE2 if v2 else TASK_DONE)
    out = [_head(code, msg.get("rpc_id")),
           _U32.pack(int(msg.get("pid", 0))),
           _oids(msg.get("return_ids", ()))]
    (_enc_added_v2 if (v2 or v3) else _enc_added_v1)(out, added)
    out.append(_F32.pack(float(msg.get("exec_s", 0.0))))
    out.append(_F32.pack(float(msg.get("reg_s", 0.0))))
    if v3:
        out.append(_F64.pack(float(msg.get("ts_exec_start") or 0.0)))
        out.append(_F64.pack(float(msg.get("ts_exec_end") or 0.0)))
    return out


def _dec_task_done(r: _Reader, rpc_id, v2: bool = False,
                   v3: bool = False) -> Dict[str, Any]:
    pid = r.u32()
    return_ids = _read_oids(r)
    added = (_dec_added_v2 if (v2 or v3) else _dec_added_v1)(r)
    exec_s = r.f32()
    reg_s = r.f32()
    out = {"type": "task_done", "pid": pid, "return_ids": return_ids,
           "added": added, "exec_s": exec_s, "reg_s": reg_s,
           "rpc_id": rpc_id}
    if v3:
        out["ts_exec_start"] = r.f64()
        out["ts_exec_end"] = r.f64()
    r.done()
    return out


def _dec_task_done2(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_task_done(r, rpc_id, v2=True)


def _dec_task_done3(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_task_done(r, rpc_id, v3=True)


def _enc_pg_create(msg, peer_wire: int = WIRE_VERSION) -> Optional[List[bytes]]:
    try:
        strat = _PG_STRATEGIES.index(msg.get("strategy", "PACK"))
    except ValueError:
        return None  # unknown strategy: let pickle carry it (server errors)
    out = [_head(PG_CREATE, msg.get("rpc_id")), _b8(msg["pg_id"]),
           _U8.pack(strat), _s(msg.get("name") or ""),
           _U16.pack(len(msg.get("bundles", ())))]
    for bundle in msg.get("bundles", ()):
        out.append(_resources(bundle))
    return out


def _dec_pg_create(r: _Reader, rpc_id) -> Dict[str, Any]:
    pg_id = r.b8()
    strat = r.u8()
    if strat >= len(_PG_STRATEGIES):
        raise WireError(f"unknown pg strategy code {strat}")
    name = r.s()
    n = r.count(r.u16())
    bundles = [_read_resources(r) for _ in range(n)]
    r.done()
    return {"type": "create_placement_group", "pg_id": pg_id,
            "strategy": _PG_STRATEGIES[strat], "name": name,
            "bundles": bundles, "rpc_id": rpc_id}


def _enc_pg_remove(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    return [_head(PG_REMOVE, msg.get("rpc_id")), _b8(msg["pg_id"])]


def _dec_pg_remove(r: _Reader, rpc_id) -> Dict[str, Any]:
    pg_id = r.b8()
    r.done()
    return {"type": "remove_placement_group", "pg_id": pg_id,
            "rpc_id": rpc_id}


def _enc_pg_status(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    return [_head(PG_STATUS, msg.get("rpc_id"))]


def _dec_pg_status(r: _Reader, rpc_id) -> Dict[str, Any]:
    r.done()
    return {"type": "list_placement_groups", "rpc_id": rpc_id}


def _enc_pg_ok(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    return [_head(PG_OK, msg.get("rpc_id")),
            _U8.pack(1 if msg.get("removed") else 0)]


def _dec_pg_ok(r: _Reader, rpc_id) -> Dict[str, Any]:
    removed = r.u8()
    r.done()
    return {"ok": True, "removed": bool(removed), "rpc_id": rpc_id}


def _enc_profile_stacks(msg, peer_wire: int = WIRE_VERSION
                        ) -> Optional[List[bytes]]:
    stacks = msg.get("stacks") or {}
    if peer_wire < 3 or len(stacks) > 0xFFFF:
        # Pre-v3 peer (can't parse 0x13) or an absurd drain: pickle
        # carries it instead.
        return None
    if msg.get("stacks_oncpu") or msg.get("thread_cpu"):
        # Observatory-era drains (on-CPU stack weights, per-thread CPU
        # window) exceed what the 0x13 frame carries; the pickle body is
        # the designated ride-along path for new stats payloads — no new
        # frame id for a 2 s cadence message.
        return None
    out = [_head(PROFILE_STACKS, msg.get("rpc_id")),
           _s(msg.get("component") or ""),
           _U32.pack(int(msg.get("samples") or 0)),
           _U16.pack(len(stacks))]
    for stack, n in stacks.items():
        if len(stack) > 0xFFF0:
            # One pathological stack must not fail the whole drain.
            stack = stack[-0xFF00:]
        out.append(_s(stack))
        out.append(_U32.pack(int(n)))
    return out


def _dec_profile_stacks(r: _Reader, rpc_id) -> Dict[str, Any]:
    component = r.s()
    samples = r.u32()
    n = r.count(r.u16())
    stacks = {}
    for _ in range(n):
        key = r.s()
        stacks[key] = stacks.get(key, 0) + r.u32()
    r.done()
    return {"type": "add_profile_stacks", "component": component,
            "samples": samples, "stacks": stacks, "rpc_id": rpc_id}


def _enc_list_tasks(msg, peer_wire: int = WIRE_VERSION
                    ) -> Optional[List[bytes]]:
    if peer_wire < 4:
        return None  # pre-v4 peer: pickle carries the query
    return [_head(LIST_TASKS, msg.get("rpc_id")),
            _s(msg.get("state") or ""),
            _s(msg.get("kind") or ""),
            _s(msg.get("node_id") or ""),
            _s(msg.get("reason") or ""),
            _s(msg.get("name_contains") or ""),
            _U32.pack(int(msg.get("limit") or 0)),
            _U32.pack(int(msg.get("offset") or 0))]


def _dec_list_tasks(r: _Reader, rpc_id) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": "list_tasks", "rpc_id": rpc_id}
    for key in ("state", "kind", "node_id", "reason", "name_contains"):
        val = r.s()
        if val:
            out[key] = val
    limit = r.u32()
    offset = r.u32()
    r.done()
    if limit:
        out["limit"] = limit
    if offset:
        out["offset"] = offset
    return out


def _enc_list_tasks_resp(msg, peer_wire: int = WIRE_VERSION
                         ) -> Optional[List[bytes]]:
    if peer_wire < 4:
        return None
    # v7 peers get the exec-window twin (ts_exec_start/ts_exec_end/exec_s
    # per row); v6 peers get the forensics twin (failure_cause/
    # failure_error); v4-v5 peers still parse the original layout.
    forensic = peer_wire >= 6
    stamped = peer_wire >= 7
    if stamped:
        code = LIST_TASKS_RESP3
    elif forensic:
        code = LIST_TASKS_RESP2
    else:
        code = LIST_TASKS_RESP
    tasks = msg.get("tasks", ())
    out = [_head(code, msg.get("rpc_id")),
           _U32.pack(int(msg.get("total", 0))),
           _U8.pack(1 if msg.get("truncated") else 0),
           _U32.pack(len(tasks))]
    for t in tasks:
        try:
            state = _TASK_STATES.index(t["state"])
            kind = _TASK_KINDS.index(t["kind"])
            tid = bytes.fromhex(t["task_id"])
        except ValueError:
            return None  # unknown enum/id shape: pickle carries it
        out.append(_b8(tid))
        out.append(_U8.pack(kind))
        out.append(_U8.pack(state))
        out.append(_s(t.get("name") or ""))
        out.append(_s(t.get("node_id") or ""))
        out.append(_s(t.get("pending_reason") or ""))
        out.append(_I32.pack(int(t.get("retries_left", 0))))
        out.append(_U8.pack(1 if t.get("cancelled") else 0))
        out.append(_F64.pack(float(t.get("ts_submit", 0.0))))
        out.append(_F64.pack(float(t.get("ts_dispatch", 0.0))))
        out.append(_F64.pack(float(t.get("ts_finish", 0.0))))
        if forensic:
            out.append(_s(t.get("failure_cause") or ""))
            out.append(_s(t.get("failure_error") or ""))
        if stamped:
            out.append(_F64.pack(float(t.get("ts_exec_start", 0.0))))
            out.append(_F64.pack(float(t.get("ts_exec_end", 0.0))))
            out.append(_F64.pack(float(t.get("exec_s", 0.0))))
    return out


def _dec_list_tasks_resp_rows(r: _Reader, rpc_id, forensic: bool,
                              stamped: bool = False) -> Dict[str, Any]:
    total = r.u32()
    truncated = bool(r.u8())
    n = r.count(r.u32())
    tasks = []
    for _ in range(n):
        tid = r.b8()
        kind = r.u8()
        state = r.u8()
        if kind >= len(_TASK_KINDS) or state >= len(_TASK_STATES):
            raise WireError("bad task kind/state code")
        row = {
            "task_id": tid.hex(), "kind": _TASK_KINDS[kind],
            "state": _TASK_STATES[state], "name": r.s(),
            "node_id": r.s(), "pending_reason": r.s(),
            "retries_left": r.i32(), "cancelled": bool(r.u8()),
            "ts_submit": r.f64(), "ts_dispatch": r.f64(),
            "ts_finish": r.f64(),
        }
        if forensic:
            row["failure_cause"] = r.s()
            row["failure_error"] = r.s()
        if stamped:
            row["ts_exec_start"] = r.f64()
            row["ts_exec_end"] = r.f64()
            row["exec_s"] = r.f64()
        tasks.append(row)
    r.done()
    return {"ok": True, "tasks": tasks, "total": total,
            "truncated": truncated, "rpc_id": rpc_id}


def _dec_list_tasks_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_list_tasks_resp_rows(r, rpc_id, forensic=False)


def _dec_list_tasks_resp2(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_list_tasks_resp_rows(r, rpc_id, forensic=True)


def _dec_list_tasks_resp3(r: _Reader, rpc_id) -> Dict[str, Any]:
    return _dec_list_tasks_resp_rows(r, rpc_id, forensic=True, stamped=True)


def _enc_pg_status_resp(msg, peer_wire: int = WIRE_VERSION) -> List[bytes]:
    groups = msg.get("groups", {})
    out = [_head(PG_STATUS_RESP, msg.get("rpc_id")),
           _U16.pack(len(groups))]
    for pg_hex, info in groups.items():
        out.append(_b8(bytes.fromhex(pg_hex)))
        out.append(_U8.pack(_PG_STATES.index(info["state"])))
        out.append(_U8.pack(_PG_STRATEGIES.index(info["strategy"])))
        out.append(_s(info.get("name") or ""))
        out.append(_s(info.get("reason") or ""))
        out.append(_U16.pack(len(info.get("bundles", ()))))
        for bundle in info.get("bundles", ()):
            out.append(_resources(bundle))
        nodes = info.get("nodes", ())
        out.append(_U16.pack(len(nodes)))
        for nid in nodes:
            out.append(_s(nid))
    return out


def _dec_pg_status_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u16())
    groups = {}
    for _ in range(n):
        pg_id = r.b8()
        state = r.u8()
        strat = r.u8()
        if state >= len(_PG_STATES) or strat >= len(_PG_STRATEGIES):
            raise WireError("bad pg state/strategy code")
        name = r.s()
        reason = r.s()
        bundles = [_read_resources(r) for _ in range(r.count(r.u16()))]
        nodes = [r.s() for _ in range(r.count(r.u16()))]
        groups[pg_id.hex()] = {
            "state": _PG_STATES[state], "strategy": _PG_STRATEGIES[strat],
            "name": name, "reason": reason, "bundles": bundles,
            "nodes": nodes}
    r.done()
    return {"ok": True, "groups": groups, "rpc_id": rpc_id}


# --------------------------------------------------------------------------
# head-HA frames (v5)
# --------------------------------------------------------------------------

def _enc_repl_record(msg, peer_wire: int = WIRE_VERSION
                     ) -> Optional[List[bytes]]:
    """One replication-log entry: the (epoch, seq) fencing header plus the
    original mutating RPC's frame body, carried opaquely. This is the
    record envelope on the standby's over-the-wire tail (repl_tail
    responses); the on-disk log carries the same fields in the
    persistence layer's own fenced header."""
    if peer_wire < 5:
        return None
    body = msg["body"]
    return [_head(REPL_RECORD, msg.get("rpc_id")),
            _U32.pack(int(msg["epoch"])),
            _U64.pack(int(msg["seq"])),
            _U32.pack(len(body)), body]


def _dec_repl_record(r: _Reader, rpc_id) -> Dict[str, Any]:
    epoch = r.u32()
    seq = r.u64()
    body = r.b32()
    r.done()
    return {"type": "repl_record", "epoch": epoch, "seq": seq,
            "body": body, "rpc_id": rpc_id}


def _enc_repl_tail(msg, peer_wire: int = WIRE_VERSION
                   ) -> Optional[List[bytes]]:
    if peer_wire < 5:
        return None
    return [_head(REPL_TAIL, msg.get("rpc_id")),
            _U64.pack(int(msg.get("after_seq") or 0)),
            _U32.pack(int(msg.get("max_records") or 0))]


def _dec_repl_tail(r: _Reader, rpc_id) -> Dict[str, Any]:
    after = r.u64()
    max_records = r.u32()
    r.done()
    return {"type": "repl_tail", "after_seq": after,
            "max_records": max_records, "rpc_id": rpc_id}


def _enc_repl_tail_resp(msg, peer_wire: int = WIRE_VERSION
                        ) -> Optional[List[bytes]]:
    if peer_wire < 5:
        return None
    records = msg.get("records") or []
    snapshot = msg.get("snapshot")
    out = [_head(REPL_TAIL_RESP, msg.get("rpc_id")),
           _U32.pack(int(msg.get("epoch") or 0)),
           _U64.pack(int(msg.get("last_seq") or 0)),
           _U8.pack(1 if msg.get("resync") else 0),
           _U8.pack(1 if snapshot is not None else 0)]
    if snapshot is not None:
        out.append(_U64.pack(len(snapshot)))
        out.append(snapshot)
        out.append(_U64.pack(int(msg.get("snapshot_seq") or 0)))
    out.append(_U32.pack(len(records)))
    for rec in records:
        out.append(_U32.pack(len(rec)))
        out.append(rec)
    return out


def _dec_repl_tail_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    epoch = r.u32()
    last_seq = r.u64()
    resync = bool(r.u8())
    snapshot = None
    snapshot_seq = 0
    if r.u8():
        snapshot = r.b64()
        snapshot_seq = r.u64()
    n = r.count(r.u32())
    records = [r.b32() for _ in range(n)]
    r.done()
    return {"ok": True, "epoch": epoch, "last_seq": last_seq,
            "resync": resync, "snapshot": snapshot,
            "snapshot_seq": snapshot_seq, "records": records,
            "rpc_id": rpc_id}


def _enc_ha_status(msg, peer_wire: int = WIRE_VERSION
                   ) -> Optional[List[bytes]]:
    if peer_wire < 5:
        return None
    return [_head(HA_STATUS, msg.get("rpc_id"))]


def _dec_ha_status(r: _Reader, rpc_id) -> Dict[str, Any]:
    r.done()
    return {"type": "ha_status", "rpc_id": rpc_id}


def _enc_ha_status_resp(msg, peer_wire: int = WIRE_VERSION
                        ) -> Optional[List[bytes]]:
    if peer_wire < 5:
        return None
    peers = msg.get("peers") or []
    if len(peers) > 0xFF:
        return None
    out = [_head(HA_STATUS_RESP, msg.get("rpc_id")),
           _U32.pack(int(msg.get("epoch") or 0)),
           _U8.pack(1 if msg.get("is_leader") else 0),
           _s(msg.get("role") or ""),
           _U32.pack(int(msg.get("failover_count") or 0)),
           _U64.pack(int(msg.get("standby_lag_bytes") or 0)),
           _F64.pack(float(msg.get("time_to_recover_s") or 0.0)),
           _U64.pack(int(msg.get("repl_seq") or 0)),
           _U8.pack(len(peers))]
    for p in peers:
        out.append(_s(p))
    return out


def _dec_ha_status_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    epoch = r.u32()
    is_leader = bool(r.u8())
    role = r.s()
    failover_count = r.u32()
    lag = r.u64()
    ttr = r.f64()
    repl_seq = r.u64()
    peers = [r.s() for _ in range(r.u8())]
    r.done()
    return {"ok": True, "epoch": epoch, "is_leader": is_leader,
            "role": role, "failover_count": failover_count,
            "standby_lag_bytes": lag, "time_to_recover_s": ttr,
            "repl_seq": repl_seq, "peers": peers, "rpc_id": rpc_id}


# CANCEL_TASK field-presence flags.
_CANCEL_TASK_ID = 1
_CANCEL_OBJECT_ID = 2
_CANCEL_FORCE = 4


def _enc_cancel_task(msg, peer_wire: int = WIRE_VERSION
                     ) -> Optional[List[bytes]]:
    if peer_wire < 6:
        return None  # pre-v6 peer can't parse 0x1B: pickle carries it
    task_id = msg.get("task_id")
    object_id = msg.get("object_id")
    flags = ((_CANCEL_TASK_ID if task_id is not None else 0)
             | (_CANCEL_OBJECT_ID if object_id is not None else 0)
             | (_CANCEL_FORCE if msg.get("force") else 0))
    out = [_head(CANCEL_TASK, msg.get("rpc_id")), _U8.pack(flags)]
    if task_id is not None:
        out.append(_b8(task_id))
    if object_id is not None:
        out.append(_b8(object_id))
    return out


def _dec_cancel_task(r: _Reader, rpc_id) -> Dict[str, Any]:
    flags = r.u8()
    out: Dict[str, Any] = {"type": "cancel_task",
                           "force": bool(flags & _CANCEL_FORCE),
                           "rpc_id": rpc_id}
    if flags & _CANCEL_TASK_ID:
        out["task_id"] = r.b8()
    if flags & _CANCEL_OBJECT_ID:
        out["object_id"] = r.b8()
    r.done()
    return out


def _enc_spec_runs(out: List[bytes], runs, singles) -> None:
    """Shared body of the columnar frames: template runs (one header per
    run, columnar task ids / return ids / arg tails) followed by legacy
    per-task spec blobs for tasks that didn't fit a template."""
    out.append(_U16.pack(len(runs)))
    for run in runs:
        task_ids = run["task_ids"]
        return_oids = run["return_oids"]
        tails = run["tails"]
        out.append(_U8.pack(int(run.get("ver", SPEC_VERSION))))
        seg_a = run["seg_a"]
        out.append(_U32.pack(len(seg_a)))
        out.append(seg_a)
        seg_b = run["seg_b"]
        out.append(_U32.pack(len(seg_b)))
        out.append(seg_b)
        out.append(_U32.pack(len(task_ids)))
        for tid in task_ids:
            out.append(_b8(tid))
        for oids in return_oids:
            out.append(_oids(oids))
        for tail in tails:
            out.append(_U32.pack(len(tail)))
            out.append(tail)
    out.append(_U32.pack(len(singles)))
    for t in singles:
        blob = t.get("_spec") if isinstance(t, dict) else t
        if blob is None:
            blob = encode_task_spec(t)
        out.append(_U32.pack(len(blob)))
        out.append(blob)


def _dec_spec_runs(r: _Reader) -> Tuple[List[Dict[str, Any]],
                                        List[Dict[str, Any]]]:
    n_runs = r.count(r.u16())
    runs: List[Dict[str, Any]] = []
    for _ in range(n_runs):
        ver = r.u8()
        if ver != SPEC_VERSION:
            raise WireError("columnar run requires v1 specs, got %d" % ver)
        seg_a = r.b32()
        seg_b = r.b32()
        n = r.count(r.u32())
        task_ids = [r.b8() for _ in range(n)]
        return_oids = [_read_oids(r) for _ in range(n)]
        tails = [r.b32() for _ in range(n)]
        ra = _Reader(seg_a)
        fn_id = ra.b8()
        name = ra.s()
        max_retries = ra.i32()
        ra.done()
        rb = _Reader(seg_b)
        deps = _read_oids(rb)
        pin_refs = _read_oids(rb)
        resources = _read_resources(rb)
        rb.done()
        runs.append({
            "ver": ver, "seg_a": seg_a, "seg_b": seg_b,
            "fn_id": fn_id, "name": name, "max_retries": max_retries,
            "deps": deps, "pin_refs": pin_refs, "resources": resources,
            "task_ids": task_ids, "return_oids": return_oids,
            "tails": tails,
        })
    n_singles = r.count(r.u32())
    singles = [decode_task_spec_header(r.b32()) for _ in range(n_singles)]
    return runs, singles


def _enc_submit_batch_cols(msg, peer_wire: int = WIRE_VERSION
                           ) -> Optional[List[bytes]]:
    if peer_wire < 8:
        return None  # pre-v8 peer can't parse 0x20: pickle carries it
    out = [_head(SUBMIT_BATCH_COLS, msg.get("rpc_id"))]
    _enc_spec_runs(out, msg["runs"], msg.get("singles") or ())
    return out


def _dec_submit_batch_cols(r: _Reader, rpc_id) -> Dict[str, Any]:
    runs, singles = _dec_spec_runs(r)
    r.done()
    return {"type": "submit_batch_cols", "runs": runs,
            "singles": singles, "rpc_id": rpc_id}


def _enc_dispatch_wave(msg, peer_wire: int = WIRE_VERSION
                       ) -> Optional[List[bytes]]:
    if peer_wire < 8:
        return None  # pre-v8 peer can't parse 0x21: pickle carries it
    out = [_head(DISPATCH_WAVE, msg.get("rpc_id"))]
    _enc_spec_runs(out, msg["runs"], msg.get("singles") or ())
    return out


def _dec_dispatch_wave(r: _Reader, rpc_id) -> Dict[str, Any]:
    runs, singles = _dec_spec_runs(r)
    r.done()
    return {"type": "dispatch_wave", "runs": runs,
            "singles": singles, "rpc_id": rpc_id}


def _enc_owner_locate(msg, peer_wire: int = WIRE_VERSION
                      ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x22: pickle carries it
    oids = msg["object_ids"]
    out = [_head(OWNER_LOCATE, msg.get("rpc_id")), _U32.pack(len(oids))]
    for oid in oids:
        out.append(_b8(oid))
    return out


def _dec_owner_locate(r: _Reader, rpc_id) -> Dict[str, Any]:
    oids = _read_id_list(r, r.count(r.u32()))
    r.done()
    return {"type": "owner_locate", "object_ids": oids, "rpc_id": rpc_id}


def _enc_owner_locate_resp(msg, peer_wire: int = WIRE_VERSION
                           ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x23: pickle carries it
    objects = msg.get("objects", {})
    out = [_head(OWNER_LOCATE_RESP, msg.get("rpc_id")),
           _U32.pack(len(objects))]
    for oid, info in objects.items():
        out.append(_b8(oid))
        out.append(_U64.pack(int(info.get("size", 0))))
        out.append(_U8.pack(1 if info.get("inline") else 0))
    return out


def _dec_owner_locate_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    objects = {}
    for _ in range(n):
        oid = r.b8()
        objects[oid] = {"size": r.u64(), "inline": bool(r.u8())}
    r.done()
    return {"ok": True, "objects": objects, "rpc_id": rpc_id}


def _enc_owner_fetch(msg, peer_wire: int = WIRE_VERSION
                     ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x24: pickle carries it
    oids = msg["object_ids"]
    out = [_head(OWNER_FETCH, msg.get("rpc_id")), _U32.pack(len(oids))]
    for oid in oids:
        out.append(_b8(oid))
    return out


def _dec_owner_fetch(r: _Reader, rpc_id) -> Dict[str, Any]:
    oids = _read_id_list(r, r.count(r.u32()))
    r.done()
    return {"type": "owner_fetch", "object_ids": oids, "rpc_id": rpc_id}


def _enc_owner_fetch_resp(msg, peer_wire: int = WIRE_VERSION
                          ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x25: pickle carries it
    blobs = msg.get("blobs", {})
    locations = msg.get("locations", {})
    out = [_head(OWNER_FETCH_RESP, msg.get("rpc_id")), _U32.pack(len(blobs))]
    for oid, blob in blobs.items():
        out.append(_b8(oid))
        out.append(_U64.pack(len(blob)))
        out.append(blob)    # pass-through buffer: no copy on encode
    out.append(_U32.pack(len(locations)))
    for oid, addr in locations.items():
        out.append(_b8(oid))
        out.append(_s(str(addr[0])))
        out.append(_U16.pack(int(addr[1])))
    return out


def _dec_owner_fetch_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    n = r.count(r.u32())
    blobs = {}
    for _ in range(n):
        oid = r.b8()
        blobs[oid] = r.b64()
    m = r.count(r.u32())
    locations = {}
    for _ in range(m):
        oid = r.b8()
        locations[oid] = [r.s(), r.u16()]
    r.done()
    return {"ok": True, "blobs": blobs, "locations": locations,
            "rpc_id": rpc_id}


def _enc_owner_publish(msg, peer_wire: int = WIRE_VERSION
                       ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x26: pickle carries it
    items = msg["items"]
    addr = msg.get("address")
    out = [_head(OWNER_PUBLISH, msg.get("rpc_id")),
           _s(msg.get("node_id") or "")]
    if addr:
        out.append(_U8.pack(1))
        out.append(_s(str(addr[0])))
        out.append(_U16.pack(int(addr[1])))
    else:
        out.append(_U8.pack(0))
    out.append(_U32.pack(len(items)))
    for ent in items:
        out.append(_b8(ent[0]))
        out.append(_U64.pack(int(ent[1])))
        blob = ent[2] if len(ent) > 2 else None
        if blob is None:
            out.append(_U8.pack(0))
        else:
            out.append(_U8.pack(1))
            out.append(_U32.pack(len(blob)))
            out.append(blob)    # pass-through buffer: no copy on encode
    return out


def _dec_owner_publish(r: _Reader, rpc_id) -> Dict[str, Any]:
    node_id = r.s()
    addr = [r.s(), r.u16()] if r.u8() else None
    n = r.count(r.u32())
    items = []
    for _ in range(n):
        oid = r.b8()
        size = r.u64()
        blob = r.b32() if r.u8() else None
        items.append([oid, size, blob])
    r.done()
    return {"type": "owner_publish", "node_id": node_id, "address": addr,
            "items": items, "rpc_id": rpc_id}


def _enc_owner_publish_resp(msg, peer_wire: int = WIRE_VERSION
                            ) -> Optional[List[bytes]]:
    if peer_wire < 9:
        return None  # pre-v9 peer can't parse 0x27: pickle carries it
    return [_head(OWNER_PUBLISH_RESP, msg.get("rpc_id")),
            _U32.pack(int(msg.get("count", 0)))]


def _dec_owner_publish_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    count = r.u32()
    r.done()
    return {"ok": True, "count": count, "rpc_id": rpc_id}


def _enc_get_obj_locations(msg, peer_wire: int = WIRE_VERSION
                           ) -> Optional[List[bytes]]:
    if peer_wire < 10:
        return None  # pre-v10 peer can't parse 0x28: pickle carries it
    return [_head(GET_OBJ_LOCATIONS, msg.get("rpc_id")),
            _b8(msg["object_id"]),
            _U8.pack(1 if msg.get("wait") else 0),
            _F64.pack(float(msg.get("timeout", 60.0)))]


def _dec_get_obj_locations(r: _Reader, rpc_id) -> Dict[str, Any]:
    oid = r.b8()
    wait = bool(r.u8())
    timeout = r.f64()
    r.done()
    return {"type": "get_object_locations", "object_id": oid,
            "wait": wait, "timeout": timeout, "rpc_id": rpc_id}


def _enc_get_obj_locations_resp(msg, peer_wire: int = WIRE_VERSION
                                ) -> Optional[List[bytes]]:
    if peer_wire < 10:
        return None  # pre-v10 peer can't parse 0x29: pickle carries it
    blob = msg.get("error_blob")
    if blob is not None:
        return [_head(GET_OBJ_LOCATIONS_RESP, msg.get("rpc_id")),
                _U8.pack(1), _U64.pack(len(blob)), blob]
    blob = msg.get("inline_blob")
    if blob is not None:
        return [_head(GET_OBJ_LOCATIONS_RESP, msg.get("rpc_id")),
                _U8.pack(2), _U64.pack(len(blob)), blob]
    locations = msg.get("locations", [])
    addrs = msg.get("addresses", [])
    transfer = msg.get("transfer_addresses", [])
    out = [_head(GET_OBJ_LOCATIONS_RESP, msg.get("rpc_id")), _U8.pack(0),
           _U32.pack(len(locations))]
    for nid in locations:
        out.append(_s(str(nid)))
    out.append(_U32.pack(len(addrs)))
    for host, port in addrs:
        out.append(_s(str(host)))
        out.append(_U16.pack(int(port)))
    out.append(_U32.pack(len(transfer)))
    for host, port in transfer:
        out.append(_s(str(host)))
        out.append(_U16.pack(int(port)))
    out.append(_U64.pack(int(msg.get("size") or 0)))
    return out


def _dec_get_obj_locations_resp(r: _Reader, rpc_id) -> Dict[str, Any]:
    flag = r.u8()
    if flag == 1:
        blob = r.b64()
        r.done()
        return {"ok": True, "locations": [], "addresses": [],
                "error_blob": blob, "rpc_id": rpc_id}
    if flag == 2:
        blob = r.b64()
        r.done()
        return {"ok": True, "locations": [], "addresses": [],
                "inline_blob": blob, "rpc_id": rpc_id}
    locations = [r.s() for _ in range(r.count(r.u32()))]
    addrs = [[r.s(), r.u16()] for _ in range(r.count(r.u32()))]
    transfer = [[r.s(), r.u16()] for _ in range(r.count(r.u32()))]
    size = r.u64()
    r.done()
    return {"ok": True, "locations": locations, "addresses": addrs,
            "transfer_addresses": transfer, "size": size, "rpc_id": rpc_id}


# Request/push encoders keyed by message "type".
_ENCODERS = {
    "submit_batch": _enc_submit_batch,
    "task_done_batch": _enc_task_done_batch,
    "locations_batch": _enc_locations_batch,
    "fetch_batch": _enc_fetch_batch,
    "object_added": _enc_object_added,
    "assign_batch": _enc_assign_batch,
    "execute_task": _enc_execute_task,
    "task_done": _enc_task_done,
    "create_placement_group": _enc_pg_create,
    "remove_placement_group": _enc_pg_remove,
    "list_placement_groups": _enc_pg_status,
    "add_profile_stacks": _enc_profile_stacks,
    "list_tasks": _enc_list_tasks,
    "repl_record": _enc_repl_record,
    "repl_tail": _enc_repl_tail,
    "ha_status": _enc_ha_status,
    "cancel_task": _enc_cancel_task,
    "submit_batch_cols": _enc_submit_batch_cols,
    "dispatch_wave": _enc_dispatch_wave,
    "owner_locate": _enc_owner_locate,
    "owner_fetch": _enc_owner_fetch,
    "owner_publish": _enc_owner_publish,
    "get_object_locations": _enc_get_obj_locations,
}

# Response encoders keyed by the *request* type they answer.
_RESP_ENCODERS = {
    "submit_batch": _enc_submit_batch_resp,
    "locations_batch": _enc_locations_batch_resp,
    "fetch_batch": _enc_fetch_batch_resp,
    "create_placement_group": _enc_pg_ok,
    "remove_placement_group": _enc_pg_ok,
    "list_placement_groups": _enc_pg_status_resp,
    "list_tasks": _enc_list_tasks_resp,
    "repl_tail": _enc_repl_tail_resp,
    "ha_status": _enc_ha_status_resp,
    "submit_batch_cols": _enc_submit_batch_resp,
    "owner_locate": _enc_owner_locate_resp,
    "owner_fetch": _enc_owner_fetch_resp,
    "owner_publish": _enc_owner_publish_resp,
    "get_object_locations": _enc_get_obj_locations_resp,
}

_DECODERS = {
    SUBMIT_BATCH: _dec_submit_batch,
    SUBMIT_BATCH_RESP: _dec_submit_batch_resp,
    TASK_DONE_BATCH: _dec_task_done_batch,
    LOCATIONS_BATCH: _dec_locations_batch,
    LOCATIONS_BATCH_RESP: _dec_locations_batch_resp,
    FETCH_BATCH: _dec_fetch_batch,
    FETCH_BATCH_RESP: _dec_fetch_batch_resp,
    OBJECT_ADDED: _dec_object_added,
    ASSIGN_BATCH: _dec_assign_batch,
    EXECUTE_TASK: _dec_execute_task,
    TASK_DONE: _dec_task_done,
    TASK_DONE2: _dec_task_done2,
    TASK_DONE3: _dec_task_done3,
    TASK_DONE_BATCH2: _dec_task_done_batch2,
    TASK_DONE_BATCH3: _dec_task_done_batch3,
    PG_CREATE: _dec_pg_create,
    PG_REMOVE: _dec_pg_remove,
    PG_STATUS: _dec_pg_status,
    PG_OK: _dec_pg_ok,
    PG_STATUS_RESP: _dec_pg_status_resp,
    PROFILE_STACKS: _dec_profile_stacks,
    LIST_TASKS: _dec_list_tasks,
    LIST_TASKS_RESP: _dec_list_tasks_resp,
    LIST_TASKS_RESP2: _dec_list_tasks_resp2,
    LIST_TASKS_RESP3: _dec_list_tasks_resp3,
    REPL_RECORD: _dec_repl_record,
    REPL_TAIL: _dec_repl_tail,
    REPL_TAIL_RESP: _dec_repl_tail_resp,
    HA_STATUS: _dec_ha_status,
    HA_STATUS_RESP: _dec_ha_status_resp,
    CANCEL_TASK: _dec_cancel_task,
    SUBMIT_BATCH_COLS: _dec_submit_batch_cols,
    DISPATCH_WAVE: _dec_dispatch_wave,
    OWNER_LOCATE: _dec_owner_locate,
    OWNER_LOCATE_RESP: _dec_owner_locate_resp,
    OWNER_FETCH: _dec_owner_fetch,
    OWNER_FETCH_RESP: _dec_owner_fetch_resp,
    OWNER_PUBLISH: _dec_owner_publish,
    OWNER_PUBLISH_RESP: _dec_owner_publish_resp,
    GET_OBJ_LOCATIONS: _dec_get_obj_locations,
    GET_OBJ_LOCATIONS_RESP: _dec_get_obj_locations_resp,
}


def encode(msg: Dict[str, Any],
           peer_wire: int = WIRE_VERSION) -> Optional[List[bytes]]:
    """Binary-encode a request/push message; None when the type has no
    fast-path codec (caller falls back to pickle). ``peer_wire`` is the
    receiver's advertised wire version: messages that would need a frame
    the peer cannot parse (e.g. inline-result items to a v1 peer) return
    None so the universally-decodable pickle body carries them."""
    enc = _ENCODERS.get(msg.get("type"))
    if enc is None:
        return None
    return enc(msg, peer_wire)


def encode_response(req_type: str, msg: Dict[str, Any],
                    peer_wire: int = WIRE_VERSION) -> Optional[List[bytes]]:
    """Binary-encode a response to ``req_type``; only ok-responses have a
    binary form (error dicts carry tracebacks and stay pickled)."""
    if msg.get("ok") is False:
        return None
    enc = _RESP_ENCODERS.get(req_type)
    if enc is None:
        return None
    return enc(msg, peer_wire)


def is_binary(body) -> bool:
    return len(body) > 0 and body[0] == MAGIC


def decode(body: bytes) -> Dict[str, Any]:
    """Decode one binary frame body into the dict the pickle path would
    have produced. Raises WireError on truncated/garbage frames."""
    if len(body) < 10:
        raise WireError(f"binary frame too short: {len(body)} bytes")
    if body[0] != MAGIC:
        raise WireError(f"bad magic byte 0x{body[0]:02x}")
    code = body[1]
    dec = _DECODERS.get(code)
    if dec is None:
        raise WireError(f"unknown message code 0x{code:02x}")
    (rpc_id,) = _U64.unpack_from(body, 2)
    msg = dec(_Reader(body, 10), rpc_id or None)
    if msg.get("rpc_id") is None:
        msg.pop("rpc_id", None)
    return msg
