"""Placement groups: all-or-nothing gang scheduling.

Reference: ``python/ray/util/placement_group.py`` (a post-snapshot Ray
feature, rebuilt here TPU-first). A placement group reserves a set of
resource *bundles* atomically — either every bundle is granted, or none is
and the group stays PENDING. Bundles materialize as group-scoped custom
resources on their nodes (``CPU_group_<i>_<id>``), so tasks and actors
submitted with ``.options(placement_group=pg, placement_group_bundle_index=i)``
flow through the ordinary placement machinery and can only land on the
bundle's node, consuming the bundle's reservation rather than the node's
free pool.

Strategies:

  PACK           prefer one node for every bundle; fall back to spreading.
  SPREAD         rotate bundles across feasible nodes (best effort).
  STRICT_PACK    every bundle on ONE node, or the group is not placed.
  STRICT_SPREAD  every bundle on a DISTINCT node; a group with more
                 bundles than nodes is INFEASIBLE (reported, never a hang).

The cluster-mode gang admission is a data-parallel prefix-sum pass in the
batch placement kernel (``ray_tpu/scheduler/kernel.py::admit_gangs``),
mirrored bit-for-bit by ``scheduler/reference.py::admit_gangs_reference``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ._private.resources import translate_pg_demand
from ._private.worker import global_worker

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

_READY_FN = None  # lazily-built probe RemoteFunction (one export per proc)


class PlacementGroup:
    """Handle to a placement group (serializable; identity is the id)."""

    __slots__ = ("id", "bundle_specs", "strategy", "name")

    def __init__(self, pg_id: bytes, bundle_specs: Sequence[Dict[str, float]],
                 strategy: str = "PACK", name: str = ""):
        self.id = pg_id
        self.bundle_specs = [dict(b) for b in bundle_specs]
        self.strategy = strategy
        self.name = name

    @property
    def hex(self) -> str:
        return self.id.hex()

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self):
        """ObjectRef that resolves once every bundle is reserved: a
        zero-resource probe task pinned into the group via the bundle
        marker (reference: bundle_reservation_check_func)."""
        global _READY_FN
        if _READY_FN is None:
            from .remote_function import RemoteFunction

            _READY_FN = RemoteFunction(_bundle_reservation_check,
                                       num_cpus=0, max_retries=0)
        return _READY_FN.options(
            num_cpus=0, placement_group=self,
            placement_group_bundle_index=-1).remote(self.hex)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the group is CREATED; False on timeout (the group
        stays pending and may still be created later)."""
        worker = global_worker()
        worker.check_connected()
        return bool(worker.core.placement_group_wait(self.id, timeout))

    def translated_resources(self, resources: Dict[str, float],
                             bundle_index: int = -1) -> Dict[str, float]:
        if bundle_index >= len(self.bundle_specs):
            raise ValueError(
                f"placement_group_bundle_index {bundle_index} out of range "
                f"for {len(self.bundle_specs)} bundles")
        return translate_pg_demand(resources, self.hex, bundle_index)

    def __reduce__(self):
        return (PlacementGroup,
                (self.id, self.bundle_specs, self.strategy, self.name))

    def __eq__(self, other):
        return isinstance(other, PlacementGroup) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return (f"PlacementGroup({self.hex[:12]}, "
                f"{len(self.bundle_specs)} bundles, {self.strategy})")


def _bundle_reservation_check(pg_hex: str) -> str:
    """Probe executed inside the group once reservation lands."""
    return pg_hex


def _validate_bundles(bundles: Sequence[Dict[str, float]]) -> None:
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for i, bundle in enumerate(bundles):
        if not isinstance(bundle, dict) or not bundle:
            raise ValueError(f"bundle {i} must be a non-empty resource dict")
        for k, v in bundle.items():
            if not isinstance(k, str) or v < 0:
                raise ValueError(f"bundle {i} has invalid entry {k!r}: {v}")
        if all(v == 0 for v in bundle.values()):
            raise ValueError(f"bundle {i} is all-zero")


def placement_group(bundles: Sequence[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    """Create a placement group (async — creation is all-or-nothing gang
    admission on the cluster; use ``pg.ready()`` / ``pg.wait()`` to block
    until every bundle is reserved)."""
    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}")
    _validate_bundles(bundles)
    worker = global_worker()
    worker.check_connected()
    pg_id = os.urandom(8)
    pg = PlacementGroup(pg_id, bundles, strategy, name)
    worker.core.create_placement_group(
        pg_id, [dict(b) for b in bundles], strategy, name)
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release every bundle of the group (the resources return to their
    nodes' free pools). Tasks still pending on the group fail with
    PlacementGroupError; running tasks finish."""
    worker = global_worker()
    worker.check_connected()
    worker.core.remove_placement_group(
        pg.id if isinstance(pg, PlacementGroup) else pg)


def placement_group_table(
        pg: Optional[PlacementGroup] = None) -> Dict[str, Dict]:
    """State of all (or one) placement groups: state, strategy, bundles,
    per-bundle node ids, and the pending reason when not yet created."""
    worker = global_worker()
    worker.check_connected()
    table = worker.core.placement_group_table()
    if pg is not None:
        key = pg.id.hex() if isinstance(pg, PlacementGroup) else pg.hex()
        return {key: table[key]} if key in table else {}
    return table
