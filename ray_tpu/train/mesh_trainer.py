"""Single-controller SPMD trainer over a device mesh.

The TPU-native replacement for the reference's data-parallel trainer stack
(``util/sgd/torch/distributed_torch_runner.py:35-70``'s process-group world):
instead of N processes each owning a model replica and allreducing grads,
ONE program is pjit-compiled over a Mesh; parameters, optimizer state and
batches carry NamedShardings and XLA emits the dp-psum / tp-collectives.

Works with any (init_fn, loss_fn) pair; shardings are optional (replicated
by default) so it also serves as the plain single-chip trainer.
"""

from __future__ import annotations

import pickle
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0

    def save(self, path: str) -> None:
        host = jax.tree_util.tree_map(
            lambda leaf: jax.device_get(leaf), (self.params, self.opt_state))
        with open(path, "wb") as f:
            pickle.dump({"params": host[0], "opt_state": host[1],
                         "step": self.step}, f)

    @classmethod
    def load(cls, path: str) -> "TrainState":
        with open(path, "rb") as f:
            data = pickle.load(f)
        return cls(params=data["params"], opt_state=data["opt_state"],
                   step=data["step"])


class MeshTrainer:
    def __init__(
        self,
        init_fn: Callable[[jax.Array], Any],        # rng -> params
        loss_fn: Callable[[Any, Any], jax.Array],   # (params, batch) -> loss
        *,
        optimizer=None,                             # optax tx (default adamw)
        learning_rate: float = 3e-4,
        mesh: Optional[Mesh] = None,
        param_shardings: Optional[Any] = None,      # pytree of NamedSharding
        batch_spec: Optional[P] = None,             # e.g. P("dp") on axis 0
        seed: int = 0,
        donate: bool = True,
    ):
        import optax

        self.mesh = mesh
        self.tx = optimizer or optax.adamw(learning_rate)
        self.loss_fn = loss_fn

        params = init_fn(jax.random.PRNGKey(seed))
        if mesh is not None and param_shardings is not None:
            params = jax.tree_util.tree_map(
                jax.device_put, params, param_shardings)
        opt_state = self.tx.init(params)
        self.state = TrainState(params=params, opt_state=opt_state)
        self._batch_sharding = (
            NamedSharding(mesh, batch_spec)
            if mesh is not None and batch_spec is not None else None
        )

        def step_fn(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        donate_args = (0, 1) if donate else ()
        self._step = jax.jit(step_fn, donate_argnums=donate_args)
        self._eval_step = jax.jit(loss_fn)

    # ------------------------------------------------------------------ train
    def _device_batch(self, batch):
        if self._batch_sharding is None:
            return batch
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, self._batch_sharding), batch)

    def _train_step_async(self, batch):
        """One step; returns the loss as an unmaterialized device scalar so
        host dispatch overlaps device execution."""
        batch = self._device_batch(batch)
        params, opt_state, loss = self._step(
            self.state.params, self.state.opt_state, batch)
        self.state = TrainState(params, opt_state, self.state.step + 1)
        return loss

    def train_step(self, batch) -> float:
        return float(self._train_step_async(batch))

    def train(self, data: Iterable, num_steps: int) -> Dict[str, float]:
        """Runs ``num_steps`` over ``data``; returns throughput stats
        (mirrors TorchTrainer.train's stats dict). Losses stay on device
        until the end of the loop — no per-step host sync."""
        it = iter(data)
        losses = []
        t0 = time.perf_counter()
        for _ in range(num_steps):
            losses.append(self._train_step_async(next(it)))
        jax.block_until_ready(self.state.params)
        dt = time.perf_counter() - t0
        losses = [float(l) for l in losses]
        return {
            "loss": sum(losses) / max(len(losses), 1),
            "last_loss": losses[-1] if losses else float("nan"),
            "num_steps": num_steps,
            "steps_per_s": num_steps / dt if dt > 0 else float("inf"),
            "time_s": dt,
        }

    def evaluate(self, data: Iterable, num_batches: int) -> Dict[str, float]:
        it = iter(data)
        total = 0.0
        for _ in range(num_batches):
            total += float(self._eval_step(self.state.params,
                                           self._device_batch(next(it))))
        return {"val_loss": total / max(num_batches, 1)}

    # ------------------------------------------------------------- checkpoint
    def save(self, path: str) -> None:
        self.state.save(path)

    def restore(self, path: str) -> None:
        loaded = TrainState.load(path)
        # Re-shard onto the live mesh layout.
        loaded.params = jax.tree_util.tree_map(
            lambda new, old: jax.device_put(
                new, old.sharding if hasattr(old, "sharding") else None),
            loaded.params, self.state.params)
        loaded.opt_state = jax.tree_util.tree_map(
            lambda new, old: jax.device_put(
                new, old.sharding if hasattr(old, "sharding") else None),
            loaded.opt_state, self.state.opt_state)
        self.state = loaded
