"""Elastic actor-based data-parallel trainer.

Mirrors the reference's TorchTrainer contract
(``python/ray/util/sgd/torch/torch_trainer.py:39``): N worker actors each
hold a data shard and compute gradients; the trainer synchronizes, applies
the optimizer, and survives worker death (``max_retries`` + elastic resize,
reference ``torch_trainer.py:382,688``). Where the reference wraps models in
torch DDP over gloo/NCCL, gradients here move through the object store as
jax pytrees and the update itself is a jitted optax step on the driver.

For peak TPU throughput use MeshTrainer (one jax runtime, GSPMD
collectives); this class exists for the multi-process actor topology — CPU
fleets, heterogeneous hosts, or per-host jax runtimes.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import jax

from .. import api as _api
from ..exceptions import (
    ActorDiedError,
    ClusterUnavailableError,
    NodeDiedError,
    ObjectLostError,
    RayTpuError,
    WorkerCrashedError,
)
from ..remote_function import remote


def _make_worker_class(num_cpus: float):
    @remote(num_cpus=num_cpus)
    class TrainWorker:
        """One data-parallel rank: builds params deterministically (same
        seed everywhere), iterates its data shard, returns gradients."""

        def setup(self, init_fn, loss_fn, data_creator, rank, world_size,
                  config, seed):
            import jax as _jax

            self.rank = rank
            self.world_size = world_size
            self.config = config
            self.params = init_fn(_jax.random.PRNGKey(seed))
            self.loss_fn = loss_fn
            self._grad = _jax.jit(_jax.value_and_grad(loss_fn))
            self._data = iter(data_creator(rank, world_size, config))
            return rank

        def set_params(self, params):
            self.params = params
            return True

        def compute_grads(self, params=None):
            """One local batch -> (loss, grads). The trainer may push fresh
            params inline to save a round trip."""
            if params is not None:
                self.params = params
            batch = next(self._data)
            loss, grads = self._grad(self.params, batch)
            return float(loss), jax.device_get(grads)

        def evaluate(self, num_batches):
            total = 0.0
            for _ in range(num_batches):
                total += float(self.loss_fn(self.params, next(self._data)))
            return total / max(num_batches, 1)

        def shutdown(self):
            return True

    return TrainWorker


class TPUTrainer:
    def __init__(
        self,
        init_fn: Callable,                   # rng -> params
        loss_fn: Callable,                   # (params, batch) -> scalar loss
        data_creator: Callable,              # (rank, world, config) -> iter
        *,
        optimizer=None,                      # optax tx (default adamw)
        learning_rate: float = 3e-4,
        num_workers: int = 2,
        config: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        max_retries: int = 3,
        num_cpus_per_worker: float = 1,
    ):
        import optax

        self.init_fn = init_fn
        self.loss_fn = loss_fn
        self.data_creator = data_creator
        self.config = config or {}
        self.seed = seed
        self.max_retries = max_retries
        self.num_workers = num_workers
        self._worker_cls = _make_worker_class(num_cpus_per_worker)

        self.tx = optimizer or optax.adamw(learning_rate)
        self.params = init_fn(jax.random.PRNGKey(seed))
        self.opt_state = self.tx.init(self.params)
        self.step = 0

        def apply_update(params, opt_state, grads):
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(apply_update)
        self.workers: List[Any] = []
        self._start_workers(num_workers)

    # ---------------------------------------------------------------- workers
    def _start_workers(self, count: int):
        """(Re)build the worker set at ``count`` ranks — the reference's
        ``_start_workers``/``_resize_workers`` (torch_trainer.py:298,688)."""
        for w in self.workers:
            try:
                _api.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = [self._worker_cls.remote() for _ in range(count)]
        _api.get([
            w.setup.remote(self.init_fn, self.loss_fn, self.data_creator,
                           rank, count, self.config, self.seed)
            for rank, w in enumerate(self.workers)
        ])
        self._sync_params()

    def _sync_params(self):
        params_ref = _api.put(jax.device_get(self.params))
        _api.get([w.set_params.remote(params_ref) for w in self.workers])

    # ------------------------------------------------------------------ train
    def _try_one_step(self) -> float:
        params_ref = _api.put(jax.device_get(self.params))
        futures = [w.compute_grads.remote(params_ref) for w in self.workers]
        results = _api.get(futures)
        losses = [loss for loss, _ in results]
        grad_trees = [grads for _, grads in results]
        mean_grads = jax.tree_util.tree_map(
            lambda *gs: sum(gs) / len(gs), *grad_trees)
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, mean_grads)
        self.step += 1
        return sum(losses) / len(losses)

    def train(self, num_steps: int = 1) -> Dict[str, float]:
        """Runs synchronous DP steps; on worker failure, rebuilds the worker
        set and retries (up to max_retries per train call)."""
        losses = []
        retries = 0
        t0 = time.perf_counter()
        while len(losses) < num_steps:
            try:
                losses.append(self._try_one_step())
            except (ActorDiedError, WorkerCrashedError,
                    ClusterUnavailableError, NodeDiedError, ObjectLostError):
                retries += 1
                if retries > self.max_retries:
                    raise
                # Elastic recovery: respawn the full worker set; params and
                # optimizer state live on the trainer, so nothing is lost.
                self._start_workers(self.num_workers)
        dt = time.perf_counter() - t0
        return {
            "loss": sum(losses) / max(len(losses), 1),
            "last_loss": losses[-1] if losses else float("nan"),
            "num_steps": num_steps,
            "step": self.step,
            "retries": retries,
            "steps_per_s": num_steps / dt if dt > 0 else float("inf"),
        }

    def validate(self, num_batches: int = 1) -> Dict[str, float]:
        self._sync_params()
        vals = _api.get([w.evaluate.remote(num_batches)
                         for w in self.workers])
        return {"val_loss": sum(vals) / len(vals)}

    # ----------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "step": self.step}

    def load_state_dict(self, state: Dict[str, Any]):
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.step = state["step"]
        self._sync_params()

    def save(self, path: str) -> str:
        import pickle

        with open(path, "wb") as f:
            pickle.dump(self.state_dict(), f)
        return path

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            self.load_state_dict(pickle.load(f))

    # -------------------------------------------------------------- lifecycle
    def resize(self, num_workers: int):
        """Elastic resize (reference torch_trainer.py:688)."""
        self.num_workers = num_workers
        self._start_workers(num_workers)

    def shutdown(self):
        for w in self.workers:
            try:
                _api.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []

    # ---------------------------------------------------------------- tune
    @classmethod
    def as_trainable(cls, init_fn, loss_fn, data_creator, **trainer_kwargs):
        """A tune Trainable wrapping this trainer (reference:
        torch_trainer.py:717 TorchTrainer.as_trainable). Tune config keys
        matching constructor kwargs (learning_rate, num_workers, seed, ...)
        override; the rest flow into the trainer's user config."""
        import os

        from ..tune.trainable import Trainable

        ctor_keys = {"optimizer", "learning_rate", "num_workers", "seed",
                     "max_retries", "num_cpus_per_worker"}

        class TPUTrainerTrainable(Trainable):
            def setup(self, config):
                kwargs = dict(trainer_kwargs)
                user_cfg = dict(kwargs.pop("config", {}) or {})
                for k, v in (config or {}).items():
                    if k.startswith("__"):
                        continue
                    if k in ctor_keys:
                        kwargs[k] = v
                    else:
                        user_cfg[k] = v
                self.trainer = cls(init_fn, loss_fn, data_creator,
                                   config=user_cfg, **kwargs)

            def step(self):
                return self.trainer.train()

            def save_checkpoint(self, checkpoint_dir):
                self.trainer.save(os.path.join(checkpoint_dir, "trainer.pkl"))
                return checkpoint_dir

            def load_checkpoint(self, checkpoint_path):
                if os.path.isdir(checkpoint_path):
                    checkpoint_path = os.path.join(
                        checkpoint_path, "trainer.pkl")
                self.trainer.restore(checkpoint_path)

            def cleanup(self):
                self.trainer.shutdown()

        TPUTrainerTrainable.__name__ = f"{cls.__name__}Trainable"
        return TPUTrainerTrainable
