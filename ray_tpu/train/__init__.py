"""Distributed training library (the RaySGD replacement).

Two complementary trainers:

- ``MeshTrainer`` — the TPU-native fast path: one controller, a global
  ``jax.sharding.Mesh``, a pjit'd train step with dp/tp/sp/pp shardings.
  XLA inserts the collectives; this is how training actually runs fast on
  TPU slices (replaces torch DDP + NCCL allreduce with GSPMD).
- ``TPUTrainer`` — actor-based data parallelism with elastic fault
  tolerance, mirroring the reference's TorchTrainer semantics
  (``python/ray/util/sgd/torch/torch_trainer.py:39``): N worker actors,
  gradient averaging, worker-failure recovery and resizing, checkpointing.
  Use it when workers must be separate processes/hosts outside one jax
  runtime (the RaySGD-shaped contract).
"""

from .mesh_trainer import MeshTrainer, TrainState  # noqa: F401
from .trainer import TPUTrainer  # noqa: F401
