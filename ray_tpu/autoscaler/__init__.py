"""Autoscaler (reference: python/ray/autoscaler/)."""

from .autoscaler import StandardAutoscaler  # noqa: F401
from .gce import GCETPUNodeProvider, make_provider  # noqa: F401
from .load_metrics import LoadMetrics  # noqa: F401
from .node_provider import MockProvider, NodeProvider, SubprocessProvider  # noqa: F401
from .resource_demand_scheduler import get_nodes_to_launch  # noqa: F401
