"""StandardAutoscaler (reference: python/ray/autoscaler/autoscaler.py:32).

Each ``update()``: prune dead nodes, terminate idle workers past the idle
timeout and any beyond max_workers, then launch workers for utilization
pressure and unplaceable pending demands (bin-packed). Same decision
structure as the reference, without the ssh/updater machinery (nodes here are
processes or cloud TPU VMs behind the provider).
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Dict, List, Optional

from .load_metrics import LoadMetrics
from .node_provider import (
    NodeProvider, STATUS_UP_TO_DATE, TAG_NODE_KIND, TAG_NODE_STATUS,
)
from .resource_demand_scheduler import get_nodes_to_launch

logger = logging.getLogger(__name__)

DEFAULT_CONFIG = {
    "min_workers": 0,
    "max_workers": 8,
    "target_utilization_fraction": 0.8,
    "idle_timeout_minutes": 5.0,
    "max_launch_batch": 4,
    "heartbeat_timeout_s": 30.0,
    "worker_resources": {"CPU": 2.0},
    "worker_node_config": {},
}


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, load_metrics: LoadMetrics,
                 config: Optional[Dict[str, Any]] = None,
                 drain_fn=None):
        self.provider = provider
        self.load_metrics = load_metrics
        self.config = {**DEFAULT_CONFIG, **(config or {})}
        self.last_idle_since: Dict[str, float] = {}
        self.num_launches = 0
        self.num_terminations = 0
        # Graceful scale-down hook: drain_fn(node_id) asks the control
        # plane to drain the node (no new placements, running tasks
        # finish, sole-copy objects re-home) and returns True once it has
        # fully retired. Termination is deferred across update() ticks
        # until then, so a planned scale-down never kills running tasks.
        self.drain_fn = drain_fn
        self.pending_drains: Dict[str, float] = {}

    def workers(self) -> List[str]:
        return self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: "worker"})

    def update(self) -> None:
        cfg = self.config
        self.load_metrics.prune_inactive(cfg["heartbeat_timeout_s"])
        workers = self.workers()

        # 1. enforce max_workers (newest first, matching the reference).
        while len(workers) > cfg["max_workers"]:
            victim = workers.pop()
            self._terminate(victim, "max_workers")

        # 2. terminate idle nodes past the timeout (but keep min_workers).
        idle_cutoff = cfg["idle_timeout_minutes"] * 60.0
        idle_ips = set(self.load_metrics.idle_ips(idle_cutoff))
        now = time.monotonic()
        for node_id in list(workers):
            if len(workers) <= cfg["min_workers"]:
                break
            ip = self.provider.internal_ip(node_id)
            if ip in idle_ips:
                since = self.last_idle_since.setdefault(node_id, now)
                if now - since > idle_cutoff:
                    workers.remove(node_id)
                    self._terminate(node_id, "idle")
            else:
                self.last_idle_since.pop(node_id, None)

        # 3. scale up: min_workers floor, utilization pressure, pending demands.
        target = cfg["min_workers"]
        util = self.load_metrics.utilization()
        if util > cfg["target_utilization_fraction"]:
            # grow proportionally to overshoot (reference's target-frac rule)
            cur = max(self.load_metrics.num_nodes(), 1)
            target = max(target, math.ceil(
                cur * util / cfg["target_utilization_fraction"]) - 1)
        demands = self.load_metrics.pending_demands
        pg_demands = self.load_metrics.pending_pg_demands
        if demands or pg_demands:
            free = list(self.load_metrics.dynamic_resources.values())
            extra = get_nodes_to_launch(
                demands, free, cfg["worker_resources"],
                max_new_nodes=cfg["max_workers"] - len(workers),
                pending_pg_demands=pg_demands)
            target = max(target, len(workers) + extra)

        target = min(target, cfg["max_workers"])
        if target > len(workers):
            count = min(target - len(workers), cfg["max_launch_batch"])
            self._launch(count)

    def _launch(self, count: int) -> None:
        logger.info("autoscaler: launching %d workers", count)
        self.provider.create_node(
            self.config["worker_node_config"],
            {TAG_NODE_KIND: "worker", TAG_NODE_STATUS: STATUS_UP_TO_DATE},
            count)
        self.num_launches += count

    def _terminate(self, node_id: str, reason: str) -> None:
        if self.drain_fn is not None:
            try:
                drained = bool(self.drain_fn(node_id))
            except Exception:  # noqa: BLE001 - no control plane: hard kill
                logger.exception("autoscaler: drain hook failed for %s",
                                 node_id)
                drained = True
            if not drained:
                # Still draining: leave the provider node up; the next
                # update() tick re-selects it and checks again.
                self.pending_drains.setdefault(node_id, time.monotonic())
                logger.info("autoscaler: draining %s (%s)", node_id, reason)
                return
            self.pending_drains.pop(node_id, None)
        logger.info("autoscaler: terminating %s (%s)", node_id, reason)
        self.provider.terminate_node(node_id)
        self.last_idle_since.pop(node_id, None)
        self.num_terminations += 1

    def summary(self) -> str:
        return (f"Autoscaler: {len(self.workers())} workers "
                f"(launched {self.num_launches}, "
                f"terminated {self.num_terminations}); "
                f"{self.load_metrics.summary()}")
