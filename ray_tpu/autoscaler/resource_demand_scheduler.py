"""Bin-packing preview: how many nodes to launch for pending demands
(reference: python/ray/autoscaler/resource_demand_scheduler.py)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _fits(demand: Dict[str, float], free: Dict[str, float]) -> bool:
    return all(free.get(k, 0.0) >= v for k, v in demand.items())


def _consume(demand: Dict[str, float], free: Dict[str, float]) -> None:
    for k, v in demand.items():
        free[k] = free.get(k, 0.0) - v


def _pack_gang(gang: Dict, free: List[Dict[str, float]],
               new_nodes: List[Dict[str, float]],
               node_type_resources: Dict[str, float],
               max_new_nodes: int) -> bool:
    """Place one placement-group gang ATOMICALLY: all bundles fit (over
    existing free capacity, already-planned new nodes, and — within the
    budget — fresh nodes), or NOTHING is consumed and no node is
    requested. A gang must never eat free capacity or launch nodes for
    one bundle's worth (the partial reservation could never be used)."""
    bundles = sorted((dict(b) for b in gang.get("bundles", [])),
                     key=lambda b: -sum(b.values()))
    if not bundles:
        return True
    strategy = gang.get("strategy", "PACK")
    trial_free = [dict(f) for f in free]
    trial_new = [dict(f) for f in new_nodes]
    added: List[Dict[str, float]] = []

    if strategy == "STRICT_PACK":
        total: Dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        for f in trial_free + trial_new:
            if _fits(total, f):
                _consume(total, f)
                free[:] = trial_free
                new_nodes[:] = trial_new
                return True
        if (len(new_nodes) < max_new_nodes
                and _fits(total, dict(node_type_resources))):
            fresh = dict(node_type_resources)
            _consume(total, fresh)
            new_nodes.append(fresh)
            return True
        return False

    distinct = strategy == "STRICT_SPREAD"
    used: set = set()
    for b in bundles:
        placed = False
        for pool in (trial_free, trial_new, added):
            for f in pool:
                if distinct and id(f) in used:
                    continue
                if _fits(b, f):
                    _consume(b, f)
                    used.add(id(f))
                    placed = True
                    break
            if placed:
                break
        if placed:
            continue
        if len(new_nodes) + len(added) >= max_new_nodes:
            return False
        if not _fits(b, dict(node_type_resources)):
            return False  # a bundle no node type can hold: infeasible
        fresh = dict(node_type_resources)
        _consume(b, fresh)
        used.add(id(fresh))
        added.append(fresh)
    free[:] = trial_free
    new_nodes[:] = trial_new
    new_nodes.extend(added)
    return True


def get_nodes_to_launch(
    pending_demands: List[Dict[str, float]],
    existing_free: List[Dict[str, float]],
    node_type_resources: Dict[str, float],
    max_new_nodes: int,
    pending_pg_demands: Optional[List[Dict]] = None,
) -> int:
    """First-fit-decreasing pack of pending demands onto existing free
    capacity, then onto hypothetical new nodes; returns new-node count.
    Pending placement groups are packed FIRST, each as one atomic unit
    (see _pack_gang) — gangs are the demands that need whole nodes."""
    free = [dict(f) for f in existing_free]
    new_nodes: List[Dict[str, float]] = []
    gangs = sorted(
        pending_pg_demands or [],
        key=lambda g: -sum(sum(b.values()) for b in g.get("bundles", [])))
    for gang in gangs:
        _pack_gang(gang, free, new_nodes, node_type_resources,
                   max_new_nodes)
    demands = sorted(pending_demands,
                     key=lambda d: -sum(d.values()))
    for demand in demands:
        placed = False
        for f in free:
            if _fits(demand, f):
                _consume(demand, f)
                placed = True
                break
        if placed:
            continue
        for f in new_nodes:
            if _fits(demand, f):
                _consume(demand, f)
                placed = True
                break
        if placed:
            continue
        if len(new_nodes) >= max_new_nodes:
            continue  # unservable within limits this round
        if not _fits(demand, dict(node_type_resources)):
            continue  # demand can never fit one node; skip (infeasible)
        fresh = dict(node_type_resources)
        _consume(demand, fresh)
        new_nodes.append(fresh)
    return len(new_nodes)
