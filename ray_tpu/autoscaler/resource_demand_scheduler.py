"""Bin-packing preview: how many nodes to launch for pending demands
(reference: python/ray/autoscaler/resource_demand_scheduler.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple


def _fits(demand: Dict[str, float], free: Dict[str, float]) -> bool:
    return all(free.get(k, 0.0) >= v for k, v in demand.items())


def _consume(demand: Dict[str, float], free: Dict[str, float]) -> None:
    for k, v in demand.items():
        free[k] = free.get(k, 0.0) - v


def get_nodes_to_launch(
    pending_demands: List[Dict[str, float]],
    existing_free: List[Dict[str, float]],
    node_type_resources: Dict[str, float],
    max_new_nodes: int,
) -> int:
    """First-fit-decreasing pack of pending demands onto existing free
    capacity, then onto hypothetical new nodes; returns new-node count."""
    free = [dict(f) for f in existing_free]
    demands = sorted(pending_demands,
                     key=lambda d: -sum(d.values()))
    new_nodes: List[Dict[str, float]] = []
    for demand in demands:
        placed = False
        for f in free:
            if _fits(demand, f):
                _consume(demand, f)
                placed = True
                break
        if placed:
            continue
        for f in new_nodes:
            if _fits(demand, f):
                _consume(demand, f)
                placed = True
                break
        if placed:
            continue
        if len(new_nodes) >= max_new_nodes:
            continue  # unservable within limits this round
        if not _fits(demand, dict(node_type_resources)):
            continue  # demand can never fit one node; skip (infeasible)
        fresh = dict(node_type_resources)
        _consume(demand, fresh)
        new_nodes.append(fresh)
    return len(new_nodes)
