"""LoadMetrics: cluster load snapshot from heartbeats
(reference: python/ray/autoscaler/load_metrics.py)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class LoadMetrics:
    def __init__(self):
        self.static_resources: Dict[str, Dict[str, float]] = {}   # ip -> total
        self.dynamic_resources: Dict[str, Dict[str, float]] = {}  # ip -> avail
        self.last_heartbeat: Dict[str, float] = {}
        self.pending_demands: List[Dict[str, float]] = []  # unplaceable tasks
        # Pending placement groups: each an ATOMIC demand unit — a gang
        # that cannot fit the fleet needs whole nodes for ALL its bundles
        # at once, never capacity for one bundle's worth. Shape:
        # {"strategy": str, "bundles": [resource dicts], "reason": str}.
        self.pending_pg_demands: List[Dict] = []

    def update(self, ip: str, static: Dict[str, float],
               dynamic: Dict[str, float]) -> None:
        self.static_resources[ip] = dict(static)
        self.dynamic_resources[ip] = dict(dynamic)
        self.last_heartbeat[ip] = time.monotonic()

    def mark_dead(self, ip: str) -> None:
        self.static_resources.pop(ip, None)
        self.dynamic_resources.pop(ip, None)
        self.last_heartbeat.pop(ip, None)

    def set_pending_demands(self, demands: List[Dict[str, float]]) -> None:
        self.pending_demands = list(demands)

    def set_pending_placement_groups(self, pg_demands: List[Dict]) -> None:
        self.pending_pg_demands = list(pg_demands)

    def prune_inactive(self, timeout_s: float) -> None:
        now = time.monotonic()
        for ip in [ip for ip, t in self.last_heartbeat.items()
                   if now - t > timeout_s]:
            self.mark_dead(ip)

    # ---- aggregates (reference load_metrics.py get_resource_usage) ----

    def num_nodes(self) -> int:
        return len(self.static_resources)

    def utilization(self) -> float:
        """Max over resource kinds of used/total (the reference's
        approach: scale on the most contended resource)."""
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for res in self.static_resources.values():
            for k, v in res.items():
                total[k] = total.get(k, 0.0) + v
        for res in self.dynamic_resources.values():
            for k, v in res.items():
                avail[k] = avail.get(k, 0.0) + v
        frac = 0.0
        for k, tot in total.items():
            if tot <= 0:
                continue
            used = tot - avail.get(k, 0.0)
            frac = max(frac, used / tot)
        return frac

    def idle_ips(self, idle_timeout_s: float,
                 busy_threshold: float = 1e-9) -> List[str]:
        """Nodes whose resources are fully available (nothing running)."""
        out = []
        for ip, total in self.static_resources.items():
            avail = self.dynamic_resources.get(ip, {})
            busy = any(
                total.get(k, 0.0) - avail.get(k, 0.0) > busy_threshold
                for k in total)
            if not busy:
                out.append(ip)
        return out

    def summary(self) -> str:
        return (f"LoadMetrics: {self.num_nodes()} nodes, "
                f"utilization={self.utilization():.2f}, "
                f"pending={len(self.pending_demands)}, "
                f"pending_pgs={len(self.pending_pg_demands)}")
