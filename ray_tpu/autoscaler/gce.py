"""GCE TPU-VM node provider (reference: python/ray/autoscaler/gcp/
node_provider.py + config.py — the TPU-native analogue provisions Cloud TPU
VMs instead of GCE instances).

Drives the Cloud TPU REST API (``tpu.googleapis.com/v2``) directly over
urllib with a token from the GCE metadata server — no SDK dependency, which
matters because the runtime image is frozen. All HTTP goes through one
injectable ``transport`` callable, so tests (and air-gapped dev boxes) swap
in a fake API that exercises the identical request surface
(tests/test_autoscaler.py::TestGCETPUProvider).

Worker bootstrap: each TPU VM gets a ``startup-script`` metadata entry that
joins the cluster (``python -m ray_tpu.cluster.launch node --gcs <addr>``),
mirroring the reference's autoscaler bootstrap-by-ssh with GCE's native
startup hook (no updater/ssh machinery needed for TPU VMs).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

TPU_API = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

# TPU node states considered live (cloud.google.com/tpu/docs/reference).
_RUNNING_STATES = {"CREATING", "READY", "RESTARTING", "STARTING", "REPAIRING"}


def _metadata_token() -> str:
    """OAuth token from the GCE metadata server (only works ON a GCE VM —
    exactly where a head node runs in production)."""
    req = urllib.request.Request(
        METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())["access_token"]


def default_transport(method: str, url: str,
                      body: Optional[Dict] = None) -> Dict:
    """urllib transport with metadata-server auth. Raises RuntimeError with
    the API's error message on non-2xx."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Authorization": f"Bearer {_metadata_token()}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        raise RuntimeError(
            f"TPU API {method} {url} -> {e.code}: {e.read()[:500]}") from e


def _sanitize_label(value: str) -> str:
    """GCP labels: lowercase letters, digits, dash/underscore, <=63 chars."""
    out = "".join(c if c.isalnum() or c in "-_" else "-"
                  for c in str(value).lower())
    return out[:63] or "x"


class GCETPUNodeProvider(NodeProvider):
    """Provision TPU-VM worker nodes for the autoscaler / ``cli up``.

    provider_config:
        project: GCP project id                           (required)
        zone: e.g. "us-central2-b"                        (required)
        accelerator_type: e.g. "v5litepod-8"              (required)
        runtime_version: e.g. "v2-alpha-tpuv5-lite"       (required)
        gcs_address: head node "host:port" workers join   (required)
        name_prefix: node name prefix     (default "ray-tpu-worker")
        worker_resources: resources each node advertises
        workers_per_node: worker processes per node (default 2)
        network / subnetwork: optional VPC config
        transport: injectable callable(method, url, body) -> dict
    """

    def __init__(self, provider_config: Dict[str, Any]):
        super().__init__(provider_config)
        for key in ("project", "zone", "accelerator_type",
                    "runtime_version", "gcs_address"):
            if key not in provider_config:
                raise ValueError(f"gce_tpu provider requires {key!r}")
        self.project = provider_config["project"]
        self.zone = provider_config["zone"]
        self.prefix = provider_config.get("name_prefix", "ray-tpu-worker")
        # Cluster-scoping label (reference: the autoscaler's cluster-name
        # tag): every node this provider creates carries it and every
        # list/terminate filters by it, so two clusters sharing a
        # project+zone — or unrelated TPU VMs — are never touched.
        self.cluster_name = _sanitize_label(
            provider_config.get("cluster_name", "ray-tpu"))
        self.transport: Callable = provider_config.get(
            "transport", default_transport)
        self._lock = threading.Lock()
        self._next = 0

    # ------------------------------------------------------------- REST bits
    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _url(self, suffix: str = "") -> str:
        return f"{TPU_API}/{self._parent}/nodes{suffix}"

    def _list(self) -> List[Dict]:
        out, page = [], ""
        while True:
            url = self._url() + (f"?pageToken={page}" if page else "")
            resp = self.transport("GET", url, None)
            out.extend(resp.get("nodes", []))
            page = resp.get("nextPageToken", "")
            if not page:
                return out

    def _get(self, node_id: str) -> Optional[Dict]:
        try:
            return self.transport("GET", self._url(f"/{node_id}"), None)
        except RuntimeError:
            return None

    def _startup_script(self) -> str:
        cfg = self.provider_config
        resources = json.dumps(cfg.get("worker_resources", {"TPU": 1.0}))
        return (
            "#!/bin/bash\n"
            "python3 -m ray_tpu.cluster.launch node "
            f"--gcs {cfg['gcs_address']} "
            f"--resources '{resources}' "
            f"--num-workers {cfg.get('workers_per_node', 2)} "
            "--label $(hostname)\n"
        )

    # ------------------------------------------------------- NodeProvider API
    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        wanted = {_sanitize_label(k): _sanitize_label(v)
                  for k, v in tag_filters.items()}
        wanted["ray-tpu-cluster"] = self.cluster_name
        out = []
        for node in self._list():
            if node.get("state") not in _RUNNING_STATES:
                continue
            labels = node.get("labels", {})
            if all(labels.get(k) == v for k, v in wanted.items()):
                out.append(node["name"].rsplit("/", 1)[-1])
        return out

    def is_running(self, node_id: str) -> bool:
        node = self._get(node_id)
        return bool(node) and node.get("state") in _RUNNING_STATES

    def is_terminated(self, node_id: str) -> bool:
        return not self.is_running(node_id)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        node = self._get(node_id)
        return dict(node.get("labels", {})) if node else {}

    def internal_ip(self, node_id: str) -> str:
        node = self._get(node_id)
        if node:
            for ep in node.get("networkEndpoints", []):
                if ep.get("ipAddress"):
                    return ep["ipAddress"]
        return node_id

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        cfg = self.provider_config
        labels = {_sanitize_label(k): _sanitize_label(v)
                  for k, v in tags.items()}
        labels["ray-tpu-cluster"] = self.cluster_name
        for _ in range(count):
            with self._lock:
                node_id = f"{self.prefix}-{self._next}-{int(time.time())}"
                self._next += 1
            body = {
                "acceleratorType": node_config.get(
                    "accelerator_type", cfg["accelerator_type"]),
                "runtimeVersion": node_config.get(
                    "runtime_version", cfg["runtime_version"]),
                "labels": labels,
                "metadata": {"startup-script": self._startup_script()},
            }
            if cfg.get("network") or cfg.get("subnetwork"):
                body["networkConfig"] = {
                    k: cfg[s] for k, s in
                    (("network", "network"), ("subnetwork", "subnetwork"))
                    if cfg.get(s)}
            self.transport("POST", self._url(f"?nodeId={node_id}"), body)

    def terminate_node(self, node_id: str) -> None:
        try:
            self.transport("DELETE", self._url(f"/{node_id}"), None)
        except RuntimeError:
            pass  # already gone


def _provider_types() -> Dict[str, type]:
    from .node_provider import MockProvider, SubprocessProvider

    return {"gce_tpu": GCETPUNodeProvider,
            "subprocess": SubprocessProvider,
            "mock": MockProvider}


def make_provider(provider_config: Dict[str, Any]) -> NodeProvider:
    """Provider factory for config files (``cli up`` / monitor):
    {"type": "gce_tpu" | "subprocess" | "mock", ...}."""
    types = _provider_types()
    ptype = provider_config.get("type", "subprocess")
    cls = types.get(ptype)
    if cls is None:
        raise ValueError(f"unknown provider type {ptype!r} "
                         f"(expected {' | '.join(sorted(types))})")
    return cls(provider_config)
