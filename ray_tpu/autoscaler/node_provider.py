"""NodeProvider abstraction (reference: python/ray/autoscaler/node_provider.py).

The reference ships aws/gcp/azure/k8s/local providers behind one interface;
here the interface plus two concrete ones: MockProvider (unit tests, exactly
like the reference's test MockProvider) and SubprocessProvider (real
controller processes on this host — the TPU-pod-slice analogue where "a node"
is a host process owning devices).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

TAG_NODE_KIND = "node-kind"  # head | worker
TAG_NODE_STATUS = "node-status"
STATUS_UP_TO_DATE = "up-to-date"
STATUS_UNINITIALIZED = "uninitialized"


class NodeProvider:
    """Minimal lifecycle interface (reference node_provider.py:70)."""

    def __init__(self, provider_config: Dict[str, Any]):
        self.provider_config = provider_config

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def is_terminated(self, node_id: str) -> bool:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any], tags: Dict[str, str],
                    count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> str:
        return node_id


class MockProvider(NodeProvider):
    """In-memory provider (reference: test_autoscaler.py MockProvider)."""

    def __init__(self, provider_config: Optional[Dict] = None):
        super().__init__(provider_config or {})
        self._lock = threading.Lock()
        self._next_id = 0
        self.nodes: Dict[str, Dict[str, Any]] = {}
        self.fail_creates = False

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for nid, n in self.nodes.items():
                if n["terminated"]:
                    continue
                if all(n["tags"].get(k) == v for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self.nodes and not self.nodes[node_id]["terminated"]

    def is_terminated(self, node_id: str) -> bool:
        return not self.is_running(node_id)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self.nodes[node_id]["tags"])

    def create_node(self, node_config, tags, count) -> None:
        if self.fail_creates:
            raise RuntimeError("injected create failure")
        with self._lock:
            for _ in range(count):
                nid = str(self._next_id)
                self._next_id += 1
                self.nodes[nid] = {
                    "tags": dict(tags), "config": dict(node_config),
                    "terminated": False, "created_at": time.monotonic(),
                }

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            if node_id in self.nodes:
                self.nodes[node_id]["terminated"] = True


class SubprocessProvider(NodeProvider):
    """Workers are `python -m ray_tpu.cluster.launch node` processes joined to
    a running GCS — scaling a one-host dev cluster up/down for real."""

    def __init__(self, provider_config: Dict[str, Any]):
        super().__init__(provider_config)
        self.gcs_address = provider_config["gcs_address"]
        self.resources = provider_config.get(
            "worker_resources", {"CPU": 2})
        self.num_workers = provider_config.get("workers_per_node", 2)
        self._lock = threading.Lock()
        self._procs: Dict[str, Any] = {}
        self._tags: Dict[str, Dict[str, str]] = {}
        self._next = 0

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            return [
                nid for nid, p in self._procs.items()
                if p.poll() is None and all(
                    self._tags[nid].get(k) == v
                    for k, v in tag_filters.items())
            ]

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            p = self._procs.get(node_id)
            return p is not None and p.poll() is None

    def is_terminated(self, node_id: str) -> bool:
        return not self.is_running(node_id)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._tags.get(node_id, {}))

    def create_node(self, node_config, tags, count) -> None:
        import json as _json
        import subprocess
        import sys

        resources = node_config.get("resources", self.resources)
        for _ in range(count):
            with self._lock:
                nid = f"worker-{self._next}"
                self._next += 1
            # The node registers with this provider id as its GCS label, so
            # LoadMetrics (keyed by label) and provider node ids line up and
            # idle termination can match (ADVICE round 1).
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.cluster.launch", "node",
                 "--gcs", self.gcs_address,
                 "--resources", _json.dumps(resources),
                 "--num-workers", str(self.num_workers),
                 "--label", nid],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            with self._lock:
                self._procs[nid] = proc
                self._tags[nid] = dict(tags)

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            proc = self._procs.get(node_id)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()
