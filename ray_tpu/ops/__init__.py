"""TPU compute ops: pallas kernels with XLA fallbacks."""

from .attention import attention_reference, flash_attention  # noqa: F401
