"""TPU compute ops: pallas kernels with XLA fallbacks."""

from .attention import attention_reference, flash_attention  # noqa: F401
from .fused import rms_norm, softmax_cross_entropy  # noqa: F401
