"""Paged-KV decode attention + page pool (net-new vs the reference — Ray
0.9 predates LLM serving; this is the vLLM-style building block the
contiguous-slot engine can graduate to).

Layout: one shared pool of fixed-size pages, ``k_pages/v_pages:
[num_pages, page_size, KH, D]``; each sequence owns a list of page ids
(``page_table: [B, max_pages]`` int32, -1 padded). Memory is allocated in
page granules on demand, so N concurrent sequences cost
sum(ceil(len_i/page_size)) pages instead of N * max_seq rows.

The pallas path REUSES the flash-decode kernel (`ops/attention.py
_decode_kernel`) unchanged: paging only changes WHERE a logical KV block
lives, which is exactly the index map's job — the scalar-prefetched page
table routes grid step (b, ki) to physical page ``page_table[b, ki]``, and
the same clamp that truncates the DMA sweep at each sequence's length
keeps dead pages (and -1 padding) from ever being fetched.

XLA reference path (CPU / non-tiling shapes): gather pages into the
contiguous layout and delegate to ``masked_gqa_attention`` — identical
math, one copy of the softmax semantics.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .attention import _decode_kernel, masked_gqa_attention, \
    unsharded_operands
from . import attention as _att


def paged_gather(k_pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """[num_pages, ps, KH, D] gathered to [B, max_pages*ps, KH, D] (XLA
    reference layout). -1 page ids are clamped to page 0; callers mask by
    length so the garbage rows are never attended."""
    safe = jnp.maximum(page_table, 0)                  # [B, P]
    gathered = k_pages[safe]                           # [B, P, ps, KH, D]
    B, P, ps, KH, D = gathered.shape
    return gathered.reshape(B, P * ps, KH, D)


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_table: jax.Array,
                           lengths: jax.Array) -> jax.Array:
    """Single-position cached attention over a paged KV pool.

    q [B, H, D]; k_pages/v_pages [num_pages, page_size, KH, D];
    page_table [B, max_pages] int32 (-1 padded); lengths [B] int32
    (inclusive attend bound, like ``decode_attention``) -> [B, H, D].
    """
    B, H, D = q.shape
    num_pages, ps, KH, _ = k_pages.shape
    P = page_table.shape[1]
    G = H // max(KH, 1)
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    tiles = (D % 128 == 0 and ps % 128 == 0 and H % KH == 0 and G % 8 == 0)
    # Sharded operands (kv heads on a tp mesh axis) take the XLA path: the
    # paged kernel's scalar-prefetched page routing is only verified on
    # single-device operands so far.
    if on_tpu and tiles and unsharded_operands(q, k_pages, v_pages):
        return _paged_flash_decode(q, k_pages, v_pages, page_table, lengths)
    buf_k = paged_gather(k_pages, page_table)
    buf_v = paged_gather(v_pages, page_table)
    S = P * ps
    mask = (jnp.arange(S)[None, :] <= lengths[:, None])[:, None, :]
    return masked_gqa_attention(q[:, None], buf_k, buf_v, mask)[:, 0]


def _paged_flash_decode(q, k_pages, v_pages, page_table, lengths):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    num_pages, ps, KH, _ = k_pages.shape
    P = page_table.shape[1]
    G = H // KH
    scale = D ** -0.5
    qf = q.reshape(B * KH, G, D)
    lens = lengths.astype(jnp.int32)
    pt = page_table.astype(jnp.int32)

    def kv_index(r, ki, lens_ref, pt_ref, kh=KH):
        b = r // kh
        # Clamp at the sequence's last live page: dead/-1 pages are never
        # fetched (revisited index => pallas skips the copy), mirroring
        # decode_attention's DMA truncation.
        last = lens_ref[b] // ps
        page = pt_ref[b, jnp.minimum(ki, last)]
        return (jnp.maximum(page, 0), 0, r % kh, 0)

    kernel = functools.partial(
        _paged_kernel, scale=scale, block_k=ps, kv_heads=KH)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * KH, P),
        in_specs=[
            pl.BlockSpec((1, G, D), lambda r, ki, lr, pr: (r, 0, 0)),
            pl.BlockSpec((1, ps, 1, D), kv_index),
            pl.BlockSpec((1, ps, 1, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda r, ki, lr, pr: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KH, G, D), q.dtype),
        interpret=_att._INTERPRET,
    )(lens, pt, qf, k_pages, v_pages)
    return out.reshape(B, H, D)


def _paged_kernel(len_ref, pt_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale, block_k, kv_heads):
    """The flash-decode kernel verbatim: logical position of grid step ki
    is still ki*page_size, so the online-softmax/masking math is identical
    — only the index maps (which consume pt_ref) differ."""
    _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr,
                   scale=scale, block_k=block_k, kv_heads=kv_heads)


class PagePool:
    """Host-side page allocator for a paged KV cache (the bookkeeping half
    of vLLM's block manager; device arrays live with the caller).

    Free pages are a LIFO; sequences append pages as they grow and return
    them on free. Raises when the pool is exhausted — admission control
    (e.g. an engine's slot queue) decides what to do about it.

    Pages are REFCOUNTED so immutable prompt blocks can be shared between
    sequences (prefix caching — the step beyond vLLM's block manager the
    reference never had): ``share`` joins an existing page to another
    sequence; the prefix CACHE maps a chained content hash of page-aligned
    prompt blocks to the resident page holding its K/V, pinning it (one
    cache ref) until pool pressure evicts it LRU via ``evict``.
    """

    def __init__(self, num_pages: int, page_size: int):
        self.page_size = page_size
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._owned: dict = {}  # seq id -> [page ids]
        self._refs: List[int] = [0] * num_pages
        # Chained-hash prefix cache: key -> page id (insertion-ordered =
        # LRU, refreshed on hit). Each entry holds one pinning ref.
        self._prefix_cache: dict = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def evictable_pages(self) -> int:
        """Cached pages pinned ONLY by the cache (refcount 1): reclaimable
        on demand, so admission may count them as free."""
        return sum(1 for p, _ in self._prefix_cache.values()
                   if self._refs[p] == 1)

    def pages_for(self, seq: int) -> List[int]:
        return list(self._owned.get(seq, ()))

    def alloc(self, seq: int, tokens: int) -> List[int]:
        """Ensure ``seq`` owns enough pages for ``tokens`` total tokens;
        returns newly allocated page ids (may be empty). Evicts unpinned
        prefix-cache pages LRU when the free list alone cannot satisfy."""
        owned = self._owned.setdefault(seq, [])
        need = -(-tokens // self.page_size) - len(owned)
        if need <= 0:
            return []
        if need > len(self._free):
            self.evict(need - len(self._free))
        if need > len(self._free):
            raise MemoryError(
                f"page pool exhausted: need {need}, free {len(self._free)}")
        new = [self._free.pop() for _ in range(need)]
        for p in new:
            self._refs[p] = 1
        owned.extend(new)
        return new

    def share(self, seq: int, page_ids: List[int]) -> None:
        """Join existing (immutable) pages to ``seq``'s owned list,
        bumping their refcounts — the capacity win of prefix reuse."""
        owned = self._owned.setdefault(seq, [])
        for p in page_ids:
            self._refs[p] += 1
            owned.append(p)

    def free(self, seq: int) -> int:
        """Drop all of ``seq``'s page refs; pages whose refcount reaches 0
        return to the free list (shared/cached pages survive). Returns how
        many pages were actually freed."""
        pages = self._owned.pop(seq, [])
        freed = 0
        for p in reversed(pages):
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    # ------------------------------------------------------- prefix cache
    @staticmethod
    def chain_hash(prev: int, block_tokens) -> int:
        """Key for one page-aligned prompt block: hashing the previous
        block's key into this one encodes the absolute position, so equal
        token blocks at different depths never collide (RoPE makes K/V
        position-dependent)."""
        return hash((prev, tuple(block_tokens)))

    def cache_get(self, key: int, tokens=None) -> Optional[int]:
        """Resident page for a block key, refreshing its LRU position.
        ``tokens``: the block's actual token ids — verified against the
        entry, because trusting the 64-bit hash alone would let a
        collision silently serve another prompt's K/V (the vLLM bug
        class); a mismatch is a miss."""
        ent = self._prefix_cache.get(key)
        if ent is None:
            return None
        page, blk = ent
        if tokens is not None and blk is not None and tuple(tokens) != blk:
            return None
        del self._prefix_cache[key]              # re-insert = most recent
        self._prefix_cache[key] = ent
        return page

    def cache_peek(self, key: int, tokens=None) -> Optional[int]:
        """cache_get without the LRU refresh: admission probes run every
        engine tick and must not promote blocks they aren't (yet) using."""
        ent = self._prefix_cache.get(key)
        if ent is None:
            return None
        page, blk = ent
        if tokens is not None and blk is not None and tuple(tokens) != blk:
            return None
        return page

    def cache_put(self, key: int, page_id: int, tokens=None) -> None:
        """Pin ``page_id`` under ``key``. First writer wins — a duplicate
        key keeps the already-cached page."""
        if key in self._prefix_cache:
            return
        self._refs[page_id] += 1
        self._prefix_cache[key] = (
            page_id, tuple(tokens) if tokens is not None else None)

    def evict(self, n: int) -> int:
        """Drop up to ``n`` LRU cache entries whose pages are pinned only
        by the cache; returns how many pages were reclaimed."""
        got = 0
        for key in list(self._prefix_cache):
            if got >= n:
                break
            page = self._prefix_cache[key][0]
            if self._refs[page] != 1:
                continue                     # a live sequence still reads it
            del self._prefix_cache[key]
            self._refs[page] = 0
            self._free.append(page)
            got += 1
        return got

    def table(self, seqs: List[int], max_pages: Optional[int] = None
              ) -> np.ndarray:
        """Dense [len(seqs), max_pages] int32 page table (-1 padded) for
        the given sequences, in order."""
        width = max_pages or max(
            (len(self._owned.get(s, ())) for s in seqs), default=1) or 1
        out = np.full((len(seqs), width), -1, np.int32)
        for i, s in enumerate(seqs):
            pages = self._owned.get(s, ())
            if len(pages) > width:
                raise ValueError(
                    f"seq {s} owns {len(pages)} pages but the table is "
                    f"only {width} wide — it outgrew the configured "
                    f"max_pages")
            out[i, :len(pages)] = pages
        return out


def write_paged(pages: jax.Array, pool_positions: jax.Array,
                values: jax.Array) -> jax.Array:
    """Scatter new KV rows into the paged pool.

    pages [num_pages, ps, KH, D]; pool_positions [N] int32 (global row =
    page_id * ps + offset, computed by the caller from its page table);
    values [N, KH, D]. Returns the updated pool. Donation-friendly: one
    scatter, no host sync.
    """
    num_pages, ps, KH, D = pages.shape
    flat = pages.reshape(num_pages * ps, KH, D)
    flat = flat.at[pool_positions].set(values.astype(flat.dtype))
    return flat.reshape(num_pages, ps, KH, D)
