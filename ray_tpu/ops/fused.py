"""Fused elementwise-reduction kernels: RMSNorm and softmax cross-entropy.

Reference analogue: none — the reference delegates compute to torch; these
are TPU-native hot ops for the model layer. Each op auto-dispatches: pallas
kernel on TPU with clean tiling (one VMEM pass, no intermediate HBM traffic),
XLA reference otherwise; both are differentiable via custom_vjp with analytic
backwards (see pallas_guide.md for the dispatch pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# --------------------------------------------------------------- RMSNorm


def _rms_norm_ref(x, weight, eps):
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    return (x.astype(jnp.float32) * inv).astype(x.dtype) * weight


def _rms_norm_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


# Interpret-mode escape hatch, same pattern as attention._INTERPRET: lets
# CPU CI and scripts/onchip_smoke.py execute the pallas kernels themselves
# (the public dispatchers below route CPU callers to the XLA reference, so
# without this the kernels would only ever run on real TPU).
_INTERPRET = False


def _rms_norm_pallas(x2d, weight, eps, block_rows):
    import jax.experimental.pallas as pl

    R, E = x2d.shape
    kernel = functools.partial(_rms_norm_kernel, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=(R // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, E), lambda r: (r, 0)),
            pl.BlockSpec((E,), lambda r: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, E), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((R, E), x2d.dtype),
        interpret=_INTERPRET,
    )(x2d, weight)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps: float = 1e-5):
    """y = x * rsqrt(mean(x^2) + eps) * weight, fused over the last axis."""
    E = x.shape[-1]
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    block = 256
    if on_tpu and E % 128 == 0 and rows % block == 0:
        out = _rms_norm_pallas(x.reshape(rows, E), weight, eps, block)
        return out.reshape(x.shape)
    return _rms_norm_ref(x, weight, eps)


def _rms_norm_fwd(x, weight, eps):
    return rms_norm(x, weight, eps), (x, weight)


def _rms_norm_bwd(eps, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    E = x.shape[-1]
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    gw = gf * wf
    # d/dx [x_i * inv]: inv * g_i - x_i * inv^3 * mean(gw * x)
    dx = inv * gw - xf * (inv ** 3) * jnp.mean(gw * xf, axis=-1,
                                               keepdims=True)
    dw = jnp.sum((xf * inv).reshape(-1, E) * gf.reshape(-1, E), axis=0)
    return dx.astype(x.dtype), dw.astype(weight.dtype)


rms_norm.defvjp(_rms_norm_fwd, _rms_norm_bwd)


# ------------------------------------------- softmax cross-entropy


def _xent_ref(logits, labels):
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    picked = jnp.take_along_axis(lf, labels[:, None], axis=-1)[:, 0]
    return lse - picked


def _xent_kernel(logits_ref, labels_ref, o_ref):
    lf = logits_ref[...].astype(jnp.float32)  # [block_b, V]
    m = jnp.max(lf, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1, keepdims=True)) + m
    labels = labels_ref[...]  # [block_b, 1]
    onehot_pick = jnp.sum(
        jnp.where(jax.lax.broadcasted_iota(jnp.int32, lf.shape, 1)
                  == labels, lf, 0.0), axis=-1, keepdims=True)
    o_ref[...] = lse - onehot_pick


def _xent_pallas(logits, labels, block_b):
    import jax.experimental.pallas as pl

    B, V = logits.shape
    # labels/losses ride as [B, 1] columns: rank-1 blocks on TPU must tile
    # by 128, rank-2 (block_b, 1) blocks are unrestricted.
    out = pl.pallas_call(
        _xent_kernel,
        grid=(B // block_b,),
        in_specs=[
            pl.BlockSpec((block_b, V), lambda b: (b, 0)),
            pl.BlockSpec((block_b, 1), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, 1), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1), jnp.float32),
        interpret=_INTERPRET,
    )(logits, labels.astype(jnp.int32)[:, None])
    return out[:, 0]


@jax.custom_vjp
def softmax_cross_entropy(logits, labels):
    """Per-row -log softmax(logits)[label], [B, V] x [B] -> [B], fused
    (never materializes the [B, V] softmax in the forward)."""
    B, V = logits.shape
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    block = 8
    if on_tpu and V % 128 == 0 and B % block == 0:
        return _xent_pallas(logits, labels, block)
    return _xent_ref(logits, labels)


def _xent_fwd(logits, labels):
    return softmax_cross_entropy(logits, labels), (logits, labels)


def _xent_bwd(res, g):
    logits, labels = res
    lf = logits.astype(jnp.float32)
    probs = jax.nn.softmax(lf, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    dlogits = (probs - onehot) * g[:, None]
    return dlogits.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_xent_fwd, _xent_bwd)
