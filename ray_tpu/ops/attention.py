"""Attention ops: pallas flash-attention kernel + XLA reference.

The pallas kernel implements the standard online-softmax flash attention
(single pass over KV blocks, f32 running max/sum in VMEM scratch, bf16-friendly
matmuls on the MXU). It is used on TPU for shapes that tile cleanly; everything
else (CPU tests, ragged shapes) uses the XLA reference, which XLA fuses well.

Backward: pallas kernels too (Dao 2022 two-pass form) — dq in one kernel
sweeping KV blocks, dk/dv in a second sweeping Q blocks, both recomputing P
from the forward's saved logsumexp instead of materializing [T, S] scores.
Validated against the XLA reference gradient in pallas interpret mode
(tests/test_fused_ops.py), so correctness holds without TPU hardware.

Supports GQA: q has H heads, k/v have KH heads with H % KH == 0 (backward
group-sums per-Q-head dk/dv into the shared kv heads).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def unsharded_operands(*arrays) -> bool:
    """True when every operand is addressable on a single device (or its
    placement can't be inspected — tracers inside jit keep today's
    behavior). The decode pallas kernels are verified on single-device
    operands only; a committed multi-device sharding must take the XLA
    path, which partitions correctly under SPMD, until the kernels are
    validated under a real sharded mesh."""
    for a in arrays:
        try:
            sharding = a.sharding  # raises/absent on tracers & non-arrays
        except Exception:  # noqa: BLE001 - tracer or non-jax input
            continue
        try:
            if len(sharding.device_set) > 1:
                return False
        except Exception:  # noqa: BLE001 - exotic sharding: assume fine
            continue
    return True


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """[B, S, KH, D] -> [B, S, H, D] by repeating each kv head."""
    kh = k.shape[2]
    if kh == num_heads:
        return k
    return jnp.repeat(k, num_heads // kh, axis=2)


def attention_reference(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, KH, D]
    v: jax.Array,  # [B, S, KH, D]
    *,
    causal: bool = True,
    q_offset: int = 0,
    k_offset: int = 0,
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention; f32 softmax accumulation regardless of input dtype.

    ``q_offset``/``k_offset`` are global position offsets, used by ring
    attention where each shard holds a slice of the full sequence.
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    k = _repeat_kv(k, H)
    v = _repeat_kv(v, H)
    scores = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = q_offset + jnp.arange(T)[:, None]
        k_pos = k_offset + jnp.arange(S)[None, :]
        scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------

# Flipped to True by tests: runs every pallas kernel in interpret mode on
# CPU so the backward kernels are validated without TPU hardware.
_INTERPRET = False


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, causal: bool, scale: float, block_q: int, block_k: int):
    """One (batch*head, q_block, k_block) grid step with accumulation.

    Inputs are reshaped to [B*H, T, D] so blocks tile the TPU-native
    (sublane, lane) = (T, D) layout. Grid order puts the KV axis last, so for
    a fixed q block we sweep KV blocks sequentially, maintaining the
    online-softmax state in VMEM scratch (m: running max, l: running sum,
    acc: unnormalized output).
    """
    import jax.experimental.pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, :, :]                     # [block_q, D]
        k = k_ref[0, :, :]                     # [block_k, D]
        v = v_ref[0, :, :]                     # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                               # [block_q, block_k]

        if causal:
            # Mask only where the block straddles the diagonal.
            def masked():
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                return jnp.where(k_pos <= q_pos, s, NEG_INF)

            straddles = (ki + 1) * block_k - 1 > qi * block_q
            s2 = jax.lax.cond(straddles, masked, lambda: s)
        else:
            s2 = s

        m_prev = m_scr[:]                       # [block_q, 1]
        m_cur = jnp.max(s2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s2 - m_new)                 # [block_q, block_k]
        corr = jnp.exp(m_prev - m_new)          # [block_q, 1]
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    if causal:
        # Skip blocks entirely above the diagonal (k_start > q_end).
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0, :, :] = (
            acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
        ).astype(o_ref.dtype)
        # Row logsumexp, the only softmax residual the backward needs
        # (flash attention v2 trick: m + log l folds max and sum).
        lse_ref[0, :] = (
            m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
        )


def _kv_row_fn(H: int, KH: int):
    group = H // KH

    def kv_row(bh, ki, g=group, h_per_b=H, kh_per_b=KH):
        b, h = bh // h_per_b, bh % h_per_b
        return (b * kh_per_b + h // g, ki, 0)

    return kv_row


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int):
    """Returns (out [B,T,H,D], lse [B*H, T] f32)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    scale = D ** -0.5

    # [B, T, H, D] -> [B*H, T, D]: tiles land on the native (T, D) layout.
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    grid = (B * H, T // block_q, S // block_k)
    kv_row = _kv_row_fn(H, KH)

    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q), lambda bh, qi, ki: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3), lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, dq_ref,
                   acc_scr, *, causal: bool, scale: float,
                   block_q: int, block_k: int):
    """dq for one (bh, q block): sweep KV blocks, accumulate in VMEM.

    With the forward's logsumexp residual, P recomputes in one pass
    (P = exp(S - lse)), no second softmax reduction needed:
      ds = P * (dO @ V^T - rowsum(dO*O)) * scale;  dq += ds @ K
    (Dao 2022, backward pass).
    """
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, :, :]
        k = k_ref[0, :, :]
        v = v_ref[0, :, :]
        do = do_ref[0, :, :]
        lse = lse_ref[0, :]                     # [block_q]
        dsum = dsum_ref[0, :]                   # [block_q]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])           # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum[:, None]) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0, :, :] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                    scale: float, block_q: int, block_k: int):
    """dk/dv for one (bh, kv block): sweep Q blocks, accumulate in VMEM.

      dv += P^T @ dO;   dk += ds^T @ Q
    """
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        q = q_ref[0, :, :]
        k = k_ref[0, :, :]
        v = v_ref[0, :, :]
        do = do_ref[0, :, :]
        lse = lse_ref[0, :]
        dsum = dsum_ref[0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])           # [block_q, block_k]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum[:, None]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Q blocks strictly above the diagonal contribute nothing.
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool,
                    block_q: int, block_k: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    group = H // KH
    scale = D ** -0.5

    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    dof = g.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    # D_i = rowsum(dO_i * O_i): cheap elementwise reduce, left to XLA.
    dsum = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(B * H, T)
    kv_row = _kv_row_fn(H, KH)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        grid=(B * H, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q), lambda bh, qi, ki: (bh, qi)),
            pl.BlockSpec((1, block_q), lambda bh, qi, ki: (bh, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_INTERPRET,
    )(qf, kf, vf, dof, lse, dsum)

    # dk/dv are computed per Q head ([B*H, S, D]) and group-summed to the
    # KH kv heads afterwards (GQA): the kernel stays dense and the group
    # reduction is one XLA reshape-sum.
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        grid=(B * H, S // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: kv_row(bh, ki)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: kv_row(bh, ki)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q), lambda bh, ki, qi: (bh, qi)),
            pl.BlockSpec((1, block_q), lambda bh, ki, qi: (bh, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qf, kf, vf, dof, lse, dsum)

    dq = dq.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    dk = dk_h.reshape(B, KH, group, S, D).sum(axis=2)
    dv = dv_h.reshape(B, KH, group, S, D).sum(axis=2)
    dk = dk.transpose(0, 2, 1, 3)
    dv = dv.transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool = True, block_q: int = 512, block_k: int = 512,
) -> jax.Array:
    """Flash attention with automatic pallas/XLA dispatch.

    Uses the pallas kernel when running on TPU and the shapes tile cleanly;
    otherwise the XLA reference (identical math).
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    tiles = (T % block_q == 0 and S % block_k == 0 and D % 128 == 0
             and block_q % 8 == 0 and block_k % 128 == 0
             and H % k.shape[2] == 0)
    if on_tpu and tiles:
        return _flash(q, k, v, causal, block_q, block_k)
    return attention_reference(q, k, v, causal=causal)


# ---------------------------------------------------------------------------
# Decode attention (single query position per sequence, KV cache + lengths)
# ---------------------------------------------------------------------------


def masked_gqa_attention(q, buf_k, buf_v, mask):
    """q [B, T, H, Dh] against cache buffers [B, S, KH, Dh]; mask [T, S]
    (shared) or [B, T, S] (per-sequence), True where attendable. The
    canonical XLA decode/cached-attention math — generate/engine delegate
    here so there is exactly one copy."""
    B, T, H, Dh = q.shape
    KH = buf_k.shape[2]
    G = H // KH
    if mask.ndim == 2:
        mask = mask[None]
    qg = q.reshape(B, T, KH, G, Dh)
    scores = jnp.einsum("btkgd,bskd->btkgs", qg, buf_k) / jnp.sqrt(Dh)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", probs.astype(q.dtype), buf_v)
    return out.reshape(B, T, H, Dh)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                   *, scale: float, block_k: int, kv_heads: int):
    """One (batch*kv_head, k_block) grid step. The G query heads sharing one
    KV head ride the sublane axis (rows), so the per-block matmul is
    [G, D] @ [D, block_k] — MXU work even though T == 1. KV axis is the last
    grid dim: sequential sweep with online-softmax state in VMEM scratch.

    ``len_ref`` is the scalar-prefetched lengths array (SMEM): the KV
    index maps clamp out-of-range block indices to the sequence's last
    live block, and pallas skips the copy when a block ref revisits the
    same index — so short sequences stop paying the full-pool HBM sweep
    (round-3 verdict item 5). Compute for those blocks is skipped here."""
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[pl.program_id(0) // kv_heads]  # inclusive attend bound

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_k <= length)
    def _compute():
        q = q_ref[0]                            # [G, D]
        k = k_ref[0, :, 0, :]                   # [block_k, D]
        v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [G, block_k]
        G = s.shape[0]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (G, block_k), 1)
        s = jnp.where(k_pos <= length, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype)


def _flash_decode(q, k, v, lengths, block_k: int,
                  truncate_dma: bool = True):
    """q [B, H, D], k/v [B, S, KH, D], lengths [B] -> out [B, H, D].

    ``truncate_dma``: clamp the KV block index maps at each sequence's last
    live block, so the pipeline re-references (and therefore does not
    re-copy) a block instead of streaming the dead remainder of the pool.
    False keeps the full-pool sweep — kept for A/B measurement
    (scripts/model_bench.py decode section).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    G = H // KH
    scale = D ** -0.5
    # Pack group heads as rows: head h = kh * G + g (matches _repeat_kv).
    # K/V keep their native [B, S, KH, D] layout — blocks are sliced per
    # (batch, kv-head) by the index map, so the cache pool is never
    # transposed/copied (it is the large buffer here).
    qf = q.reshape(B * KH, G, D)
    lens = lengths.astype(jnp.int32)
    grid = (B * KH, S // block_k)

    if truncate_dma:
        def kv_index(r, ki, lens_ref, kh=KH):
            last = lens_ref[r // kh] // block_k
            return (r // kh, jnp.minimum(ki, last), r % kh, 0)
    else:
        def kv_index(r, ki, lens_ref, kh=KH):
            return (r // kh, ki, r % kh, 0)

    kernel = functools.partial(_decode_kernel, scale=scale, block_k=block_k,
                               kv_heads=KH)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, G, D), lambda r, ki, lens_ref: (r, 0, 0)),
            pl.BlockSpec((1, block_k, 1, D), kv_index),
            pl.BlockSpec((1, block_k, 1, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda r, ki, lens_ref: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KH, G, D), q.dtype),
        interpret=_INTERPRET,
    )(lens, qf, k, v)
    return out.reshape(B, H, D)


def decode_attention(q, k, v, lengths, *, block_k: int = 512,
                     truncate_dma: bool = True):
    """Single-position cached attention with per-sequence lengths
    (attends to cache rows 0..lengths[b] inclusive).

    q [B, H, D]; k/v [B, S, KH, D]; lengths [B] int32 -> [B, H, D].
    Pallas flash-decode kernel on TPU when shapes tile (group heads ride
    the MXU sublanes; both compute AND the HBM block sweep stop at each
    sequence's length via a scalar-prefetch grid — ``truncate_dma=False``
    restores the full-pool sweep for A/B); XLA reference otherwise —
    identical math.
    """
    B, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    bk = min(block_k, S)
    G = H // max(KH, 1)
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    # G rides the sublane axis: require full 8-row tiles (same rule as
    # flash_attention's block_q % 8) — small-group GQA/MHA configs take
    # the XLA path rather than risk an untileable (1, G, D) block.
    tiles = (S % bk == 0 and D % 128 == 0 and bk % 128 == 0
             and H % KH == 0 and G % 8 == 0)
    if on_tpu and tiles and unsharded_operands(q, k, v):
        return _flash_decode(q, k, v, lengths, bk,
                             truncate_dma=truncate_dma)
    mask = (jnp.arange(S)[None, :] <= lengths[:, None])[:, None, :]
    return masked_gqa_attention(q[:, None], k, v, mask)[:, 0]
