"""Attention ops: pallas flash-attention kernel + XLA reference.

The pallas kernel implements the standard online-softmax flash attention
(single pass over KV blocks, f32 running max/sum in VMEM scratch, bf16-friendly
matmuls on the MXU). It is used on TPU for shapes that tile cleanly; everything
else (CPU tests, ragged shapes) uses the XLA reference, which XLA fuses well.

Backward: custom_vjp with rematerialized XLA math — correct and memory-lean
(no score tensor saved); a pallas backward kernel is a later optimization.

Supports GQA: q has H heads, k/v have KH heads with H % KH == 0.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """[B, S, KH, D] -> [B, S, H, D] by repeating each kv head."""
    kh = k.shape[2]
    if kh == num_heads:
        return k
    return jnp.repeat(k, num_heads // kh, axis=2)


def attention_reference(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, KH, D]
    v: jax.Array,  # [B, S, KH, D]
    *,
    causal: bool = True,
    q_offset: int = 0,
    k_offset: int = 0,
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention; f32 softmax accumulation regardless of input dtype.

    ``q_offset``/``k_offset`` are global position offsets, used by ring
    attention where each shard holds a slice of the full sequence.
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    k = _repeat_kv(k, H)
    v = _repeat_kv(v, H)
    scores = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = q_offset + jnp.arange(T)[:, None]
        k_pos = k_offset + jnp.arange(S)[None, :]
        scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, causal: bool, scale: float, block_q: int, block_k: int):
    """One (batch*head, q_block, k_block) grid step with accumulation.

    Inputs are reshaped to [B*H, T, D] so blocks tile the TPU-native
    (sublane, lane) = (T, D) layout. Grid order puts the KV axis last, so for
    a fixed q block we sweep KV blocks sequentially, maintaining the
    online-softmax state in VMEM scratch (m: running max, l: running sum,
    acc: unnormalized output).
    """
    import jax.experimental.pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, :, :]                     # [block_q, D]
        k = k_ref[0, :, :]                     # [block_k, D]
        v = v_ref[0, :, :]                     # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                               # [block_q, block_k]

        if causal:
            # Mask only where the block straddles the diagonal.
            def masked():
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                return jnp.where(k_pos <= q_pos, s, NEG_INF)

            straddles = (ki + 1) * block_k - 1 > qi * block_q
            s2 = jax.lax.cond(straddles, masked, lambda: s)
        else:
            s2 = s

        m_prev = m_scr[:]                       # [block_q, 1]
        m_cur = jnp.max(s2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s2 - m_new)                 # [block_q, block_k]
        corr = jnp.exp(m_prev - m_new)          # [block_q, 1]
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    if causal:
        # Skip blocks entirely above the diagonal (k_start > q_end).
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0, :, :] = (
            acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
        ).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int) -> jax.Array:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    group = H // KH
    scale = D ** -0.5

    # [B, T, H, D] -> [B*H, T, D]: tiles land on the native (T, D) layout.
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * KH, S, D)
    grid = (B * H, T // block_q, S // block_k)

    def kv_row(bh, ki, g=group, h_per_b=H, kh_per_b=KH):
        b, h = bh // h_per_b, bh % h_per_b
        return (b * kh_per_b + h // g, ki, 0)

    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: kv_row(bh, ki)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
    )(qf, kf, vf)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return _flash_forward(q, k, v, causal, block_q, block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    return _flash_forward(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    q, k, v = residuals
    # Rematerialize through the XLA reference; XLA differentiates it.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool = True, block_q: int = 512, block_k: int = 512,
) -> jax.Array:
    """Flash attention with automatic pallas/XLA dispatch.

    Uses the pallas kernel when running on TPU and the shapes tile cleanly;
    otherwise the XLA reference (identical math).
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    tiles = (T % block_q == 0 and S % block_k == 0 and D % 128 == 0
             and block_q % 8 == 0 and block_k % 128 == 0
             and H % k.shape[2] == 0)
    if on_tpu and tiles:
        return _flash(q, k, v, causal, block_q, block_k)
    return attention_reference(q, k, v, causal=causal)
