"""Global state API (reference: python/ray/state.py GlobalState).

Snapshot queries over the running system: nodes, actors, objects, resources,
and the memory summary that backs the ``ray memory`` CLI view.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ._private.worker import global_worker


def _core():
    worker = global_worker()
    worker.check_connected()
    return worker.core


def nodes() -> List[Dict[str, Any]]:
    return _core().nodes()


def actors() -> Dict[str, Dict[str, Any]]:
    """actor_id hex -> {ActorID, State, Name} (reference state.py actors)."""
    return _core().actors()


def objects() -> Dict[str, Dict[str, Any]]:
    """object_id hex -> {size, has_error} for every stored object.

    Local mode reads the in-process store; cluster mode reads the GCS
    object directory (reference: GlobalState.objects over the GCS object
    table)."""
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        gcs = getattr(core, "gcs", None)
        if gcs is None:
            return {}
        resp = gcs.call({"type": "list_objects", "limit": 1_000_000})
        return {
            hex_id: {"size_bytes": info.get("size", 0), "has_error": False,
                     "locations": info.get("locations", []),
                     "spilled": info.get("spilled", [])}
            for hex_id, info in resp.get("objects", {}).items()
        }
    out = {}
    with store._lock:
        for oid, obj in store._objects.items():
            out[oid.hex()] = {
                "size_bytes": obj.nbytes,
                "has_error": obj.error is not None,
            }
    return out


_local_sampler = None


def node_stats() -> Dict[str, Dict[str, Any]]:
    """node_id -> physical stats from each node's reporter (reference:
    dashboard reporter datapath). Local mode samples this process's host."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is not None:
        return gcs.call({"type": "get_node_stats"})["stats"]
    global _local_sampler
    from ._private.node_stats import NodeStatsSampler

    if _local_sampler is None:
        _local_sampler = NodeStatsSampler()
    import os as _os

    return {"local": _local_sampler.sample([_os.getpid()])}


def cluster_resources() -> Dict[str, float]:
    return _core().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _core().available_resources()


def object_store_stats() -> Dict[str, int]:
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        return {}
    return store.stats()


def memory_summary() -> str:
    """Human-readable object-store summary (reference: `ray memory`,
    scripts.py:1084 + memory.py)."""
    objs = objects()
    stats = object_store_stats()
    lines = [
        "=== Object store summary ===",
        f"objects: {len(objs)}",
        f"used_bytes: {stats.get('used_bytes', 0)}",
        f"max_bytes: {stats.get('max_bytes', 0) or 'unlimited'}",
        "",
        f"{'OBJECT_ID':<44} {'SIZE':>12}  ERROR",
    ]
    for oid, info in sorted(objs.items(),
                            key=lambda kv: -kv[1]["size_bytes"])[:50]:
        lines.append(
            f"{oid:<44} {info['size_bytes']:>12}  {info['has_error']}")
    return "\n".join(lines)


def jobs() -> List[Dict[str, Any]]:
    core = _core()
    return [{"job_id": core.job_id.hex(), "is_dead": False}]
