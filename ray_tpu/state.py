"""Global state API (reference: python/ray/state.py GlobalState).

Snapshot queries over the running system: nodes, actors, objects, resources,
the memory summary that backs the ``ray memory`` CLI view, and — state API
v2 — the bounded/filterable/paginated task table (``tasks()`` /
``summarize_tasks()``) with per-task pending-reason attribution.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ._private.worker import global_worker


def _core():
    worker = global_worker()
    worker.check_connected()
    return worker.core


def tasks(state: Optional[str] = None, kind: Optional[str] = None,
          node_id: Optional[str] = None, reason: Optional[str] = None,
          name_contains: Optional[str] = None,
          limit: int = 1000, offset: int = 0) -> List[Dict[str, Any]]:
    """Query the cluster task table (reference: Ray's state API
    ``list_tasks``). Each row carries the lifecycle (state + wall-clock
    stamps ``ts_submit``/``ts_dispatch``/``ts_finish``) and, for PENDING
    tasks, the scheduler's pending-reason attribution (waiting-for-deps /
    waiting-for-capacity / infeasible / waiting-for-pg / quota-throttled).

    Filterable by ``state``/``kind``/``node_id``/``reason``/
    ``name_contains``; paginated by ``limit``/``offset`` (server-capped at
    10k rows per page). Local mode serves the same row shape from the
    in-process runtime's task records (same lifecycle + exec stamps, so
    durations don't read 0 in local runs)."""
    core = _core()
    if getattr(core, "gcs", None) is None:
        rows = [r for r in _local_task_rows(core)
                if (not state or r["state"] == state)
                and (not kind or r["kind"] == kind)
                and (not node_id or r["node_id"] == node_id)
                and (not reason or r.get("pending_reason") == reason)
                and (not name_contains or name_contains in r["name"])]
        return rows[offset:offset + limit]
    return core.list_tasks(state=state, kind=kind, node_id=node_id,
                           reason=reason, name_contains=name_contains,
                           limit=limit, offset=offset)["tasks"]


def _local_task_rows(core) -> List[Dict[str, Any]]:
    rows = getattr(core, "task_rows", None)
    return rows() if callable(rows) else []


def summarize_tasks() -> Dict[str, Any]:
    """Per-state counts over the cluster task table, with the PENDING set
    broken down by pending reason:
    ``{total, states, kinds, pending_reasons, ...}``."""
    core = _core()
    if getattr(core, "gcs", None) is None:
        return {"total": 0, "states": {}, "kinds": {},
                "pending_reasons": {}}
    out = core.task_summary()
    out.pop("ok", None)
    return out


def nodes() -> List[Dict[str, Any]]:
    return _core().nodes()


def actors() -> Dict[str, Dict[str, Any]]:
    """actor_id hex -> {ActorID, State, Name} (reference state.py actors)."""
    return _core().actors()


def objects() -> Dict[str, Dict[str, Any]]:
    """object_id hex -> {size, has_error} for every stored object.

    Local mode reads the in-process store; cluster mode reads the GCS
    object directory (reference: GlobalState.objects over the GCS object
    table)."""
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        gcs = getattr(core, "gcs", None)
        if gcs is None:
            return {}
        resp = gcs.call({"type": "list_objects", "limit": 1_000_000})
        return {
            hex_id: {"size_bytes": info.get("size", 0),
                     # Served by the GCS (error blobs live in its error
                     # table, not the directory) — was hardcoded False,
                     # which made `cli memory` lie about errored objects.
                     "has_error": bool(info.get("has_error")),
                     "locations": info.get("locations", []),
                     "spilled": info.get("spilled", [])}
            for hex_id, info in resp.get("objects", {}).items()
        }
    out = {}
    with store._lock:
        for oid, obj in store._objects.items():
            out[oid.hex()] = {
                "size_bytes": obj.nbytes,
                "has_error": obj.error is not None,
            }
    return out


_local_sampler = None


def node_stats() -> Dict[str, Dict[str, Any]]:
    """node_id -> physical stats from each node's reporter (reference:
    dashboard reporter datapath). Local mode samples this process's host."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is not None:
        return gcs.call({"type": "get_node_stats"})["stats"]
    global _local_sampler
    from ._private.node_stats import NodeStatsSampler

    if _local_sampler is None:
        _local_sampler = NodeStatsSampler()
    import os as _os

    return {"local": _local_sampler.sample([_os.getpid()])}


def cluster_resources() -> Dict[str, float]:
    return _core().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _core().available_resources()


def object_store_stats() -> Dict[str, int]:
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        return {}
    return store.stats()


def memory_summary() -> str:
    """Human-readable object-store summary (reference: `ray memory`,
    scripts.py:1084 + memory.py)."""
    objs = objects()
    stats = object_store_stats()
    lines = [
        "=== Object store summary ===",
        f"objects: {len(objs)}",
        f"used_bytes: {stats.get('used_bytes', 0)}",
        f"max_bytes: {stats.get('max_bytes', 0) or 'unlimited'}",
        "",
        f"{'OBJECT_ID':<44} {'SIZE':>12}  ERROR",
    ]
    for oid, info in sorted(objs.items(),
                            key=lambda kv: -kv[1]["size_bytes"])[:50]:
        lines.append(
            f"{oid:<44} {info['size_bytes']:>12}  {info['has_error']}")
    return "\n".join(lines)


def jobs() -> List[Dict[str, Any]]:
    """Per-job rollup rows (`cli jobs`): task/state counts, submit /
    finish bounds, and — once the GCS profiler tick has analyzed a
    completed job — its efficiency figures. Local mode rolls up the
    in-process task records; with no records yet it degrades to the
    single driver-job row."""
    core = _core()
    if getattr(core, "gcs", None) is not None:
        rows = core.list_jobs().get("jobs", [])
        if rows:
            for row in rows:
                row["is_dead"] = not row.get("active", False)
            return rows
        return [{"job_id": core.job_id.hex(), "is_dead": False}]
    by_job: Dict[str, Dict[str, Any]] = {}
    for r in _local_task_rows(core):
        job = r["task_id"][24:32]  # tail 4 bytes of the 16-byte TaskID
        row = by_job.setdefault(job, {
            "job_id": job, "tasks": 0, "states": {},
            "ts_first_submit": 0.0, "ts_last_finish": 0.0})
        row["tasks"] += 1
        row["states"][r["state"]] = row["states"].get(r["state"], 0) + 1
        ts = r.get("ts_submit") or 0.0
        if ts and (not row["ts_first_submit"] or ts < row["ts_first_submit"]):
            row["ts_first_submit"] = ts
        row["ts_last_finish"] = max(row["ts_last_finish"],
                                    r.get("ts_finish") or 0.0)
    if not by_job:
        return [{"job_id": core.job_id.hex(), "is_dead": False}]
    for row in by_job.values():
        row["active"] = any(st not in ("FINISHED", "FAILED")
                            for st in row["states"])
        row["is_dead"] = not row["active"]
    return sorted(by_job.values(), key=lambda j: j["ts_first_submit"])


def job_profile(job_id: Optional[str] = None) -> Dict[str, Any]:
    """Critical-path profile of one job (hex prefix accepted; omitted =
    the only job): makespan, the duration-weighted longest path with
    per-hop blocked-time buckets, per-node skew, and the
    scheduler-efficiency ratio (critical-path exec lower bound / actual
    makespan). Cluster mode asks the GCS; local mode profiles the
    in-process records directly."""
    core = _core()
    if getattr(core, "gcs", None) is not None:
        resp = core.job_profile(job_id=job_id)
        if not resp.get("ok"):
            raise ValueError(resp.get("error", "job_profile failed"))
        return resp["profile"]
    rows, job = _local_job_rows(core, job_id)
    from .scheduler import critical_path as _cp
    import time as _time

    return _cp.profile_rows(rows, job_id=job, now=_time.time())


def job_timeline(job_id: Optional[str] = None,
                 path: Optional[str] = None):
    """Chrome-trace / Perfetto export of a job's DAG timeline: one lane
    per node, one slice per task exec window, flow arrows per dep edge.
    With ``path``, writes the JSON file and returns the path; without,
    returns the trace dict (``json.dump``-able)."""
    core = _core()
    if getattr(core, "gcs", None) is not None:
        resp = core.job_profile(job_id=job_id, include_rows=True)
        if not resp.get("ok"):
            raise ValueError(resp.get("error", "job_profile failed"))
        rows = resp.get("rows", [])
        job = resp["profile"].get("job_id", "")
    else:
        rows, job = _local_job_rows(core, job_id)
    from .scheduler import critical_path as _cp

    trace = _cp.chrome_trace(rows, job_id=job)
    if path:
        import json

        with open(path, "w") as f:
            json.dump(trace, f)
        return path
    return trace


def _local_job_rows(core, job_id: Optional[str]):
    """(rows, job_hex) for one job out of the local records, with the
    same prefix-match/ambiguity contract as the GCS handler."""
    rows = _local_task_rows(core)
    all_jobs = sorted({r["task_id"][24:32] for r in rows})
    want = (job_id or "").lower()
    matches = [j for j in all_jobs if j.startswith(want)] \
        if want else all_jobs
    if not matches:
        raise ValueError(f"no job matching {want!r}")
    if len(matches) > 1:
        raise ValueError(f"{len(matches)} jobs match {want!r}: {matches}")
    job = matches[0]
    return [r for r in rows if r["task_id"][24:32] == job], job
