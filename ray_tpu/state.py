"""Global state API (reference: python/ray/state.py GlobalState).

Snapshot queries over the running system: nodes, actors, objects, resources,
the memory summary that backs the ``ray memory`` CLI view, and — state API
v2 — the bounded/filterable/paginated task table (``tasks()`` /
``summarize_tasks()``) with per-task pending-reason attribution.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ._private.worker import global_worker


def _core():
    worker = global_worker()
    worker.check_connected()
    return worker.core


def tasks(state: Optional[str] = None, kind: Optional[str] = None,
          node_id: Optional[str] = None, reason: Optional[str] = None,
          name_contains: Optional[str] = None,
          limit: int = 1000, offset: int = 0) -> List[Dict[str, Any]]:
    """Query the cluster task table (reference: Ray's state API
    ``list_tasks``). Each row carries the lifecycle (state + wall-clock
    stamps ``ts_submit``/``ts_dispatch``/``ts_finish``) and, for PENDING
    tasks, the scheduler's pending-reason attribution (waiting-for-deps /
    waiting-for-capacity / infeasible / waiting-for-pg / quota-throttled).

    Filterable by ``state``/``kind``/``node_id``/``reason``/
    ``name_contains``; paginated by ``limit``/``offset`` (server-capped at
    10k rows per page). Local mode has no cluster task table and returns
    []."""
    core = _core()
    if getattr(core, "gcs", None) is None:
        return []
    return core.list_tasks(state=state, kind=kind, node_id=node_id,
                           reason=reason, name_contains=name_contains,
                           limit=limit, offset=offset)["tasks"]


def summarize_tasks() -> Dict[str, Any]:
    """Per-state counts over the cluster task table, with the PENDING set
    broken down by pending reason:
    ``{total, states, kinds, pending_reasons, ...}``."""
    core = _core()
    if getattr(core, "gcs", None) is None:
        return {"total": 0, "states": {}, "kinds": {},
                "pending_reasons": {}}
    out = core.task_summary()
    out.pop("ok", None)
    return out


def nodes() -> List[Dict[str, Any]]:
    return _core().nodes()


def actors() -> Dict[str, Dict[str, Any]]:
    """actor_id hex -> {ActorID, State, Name} (reference state.py actors)."""
    return _core().actors()


def objects() -> Dict[str, Dict[str, Any]]:
    """object_id hex -> {size, has_error} for every stored object.

    Local mode reads the in-process store; cluster mode reads the GCS
    object directory (reference: GlobalState.objects over the GCS object
    table)."""
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        gcs = getattr(core, "gcs", None)
        if gcs is None:
            return {}
        resp = gcs.call({"type": "list_objects", "limit": 1_000_000})
        return {
            hex_id: {"size_bytes": info.get("size", 0),
                     # Served by the GCS (error blobs live in its error
                     # table, not the directory) — was hardcoded False,
                     # which made `cli memory` lie about errored objects.
                     "has_error": bool(info.get("has_error")),
                     "locations": info.get("locations", []),
                     "spilled": info.get("spilled", [])}
            for hex_id, info in resp.get("objects", {}).items()
        }
    out = {}
    with store._lock:
        for oid, obj in store._objects.items():
            out[oid.hex()] = {
                "size_bytes": obj.nbytes,
                "has_error": obj.error is not None,
            }
    return out


_local_sampler = None


def node_stats() -> Dict[str, Dict[str, Any]]:
    """node_id -> physical stats from each node's reporter (reference:
    dashboard reporter datapath). Local mode samples this process's host."""
    core = _core()
    gcs = getattr(core, "gcs", None)
    if gcs is not None:
        return gcs.call({"type": "get_node_stats"})["stats"]
    global _local_sampler
    from ._private.node_stats import NodeStatsSampler

    if _local_sampler is None:
        _local_sampler = NodeStatsSampler()
    import os as _os

    return {"local": _local_sampler.sample([_os.getpid()])}


def cluster_resources() -> Dict[str, float]:
    return _core().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _core().available_resources()


def object_store_stats() -> Dict[str, int]:
    core = _core()
    store = getattr(core, "store", None)
    if store is None:
        return {}
    return store.stats()


def memory_summary() -> str:
    """Human-readable object-store summary (reference: `ray memory`,
    scripts.py:1084 + memory.py)."""
    objs = objects()
    stats = object_store_stats()
    lines = [
        "=== Object store summary ===",
        f"objects: {len(objs)}",
        f"used_bytes: {stats.get('used_bytes', 0)}",
        f"max_bytes: {stats.get('max_bytes', 0) or 'unlimited'}",
        "",
        f"{'OBJECT_ID':<44} {'SIZE':>12}  ERROR",
    ]
    for oid, info in sorted(objs.items(),
                            key=lambda kv: -kv[1]["size_bytes"])[:50]:
        lines.append(
            f"{oid:<44} {info['size_bytes']:>12}  {info['has_error']}")
    return "\n".join(lines)


def jobs() -> List[Dict[str, Any]]:
    core = _core()
    return [{"job_id": core.job_id.hex(), "is_dead": False}]
