"""Resource accounting: fixed-point vectors + named custom resources.

Modeled on the reference's *new* vectorized scheduler data model (reference:
``src/ray/common/scheduling/cluster_resource_scheduler.h:28-217`` — predefined
slots with TPU already first-class, fixed-point arithmetic so fractional
resources compare exactly) rather than the legacy string-keyed ``ResourceSet``.

All quantities are stored as int64 "kilo-units" (1.0 == 1000), which makes
demand<=available comparisons exact for fractional requests like 0.5 CPU, and
makes the whole cluster state embeddable as an int32/int64 device tensor for the
batch placement kernel (see ray_tpu/scheduler/kernel.py).
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Tuple

import numpy as np

KILO = 1000  # fixed-point scale: 1.0 resource unit == 1000

# Predefined dense slots. Order matters: it is the kernel's resource axis.
CPU, MEM, TPU, TPU_MEM = 0, 1, 2, 3
PREDEFINED = ("CPU", "memory", "TPU", "tpu_memory")
NUM_PREDEFINED = len(PREDEFINED)
_PREDEFINED_INDEX = {name: i for i, name in enumerate(PREDEFINED)}
# Aliases accepted in user-facing resource dicts.
_ALIASES = {"GPU": "TPU", "num_cpus": "CPU", "num_tpus": "TPU", "object_store_memory": "memory"}


def to_fixed(value: float) -> int:
    return int(round(value * KILO))


def from_fixed(value: int) -> float:
    return value / KILO


class ResourceSet:
    """An immutable demand/capacity vector: dense predefined slots + custom map.

    Equivalent of the reference's ``TaskRequest``/``NodeResources`` pair
    (``cluster_resource_scheduler.h:137,185``) collapsed into one type.
    """

    __slots__ = ("predefined", "custom", "_key")

    def __init__(
        self,
        predefined: Optional[np.ndarray] = None,
        custom: Optional[Mapping[str, int]] = None,
    ):
        if predefined is None:
            predefined = np.zeros(NUM_PREDEFINED, dtype=np.int64)
        self.predefined = np.asarray(predefined, dtype=np.int64)
        assert self.predefined.shape == (NUM_PREDEFINED,)
        self.custom: Dict[str, int] = {k: v for k, v in (custom or {}).items() if v != 0}
        self._key: Optional[Tuple] = None

    @classmethod
    def from_dict(cls, resources: Optional[Mapping[str, float]]) -> "ResourceSet":
        predefined = np.zeros(NUM_PREDEFINED, dtype=np.int64)
        custom: Dict[str, int] = {}
        for name, qty in (resources or {}).items():
            name = _ALIASES.get(name, name)
            fixed = to_fixed(qty)
            idx = _PREDEFINED_INDEX.get(name)
            if idx is not None:
                predefined[idx] += fixed
            else:
                custom[name] = custom.get(name, 0) + fixed
        return cls(predefined, custom)

    def to_dict(self) -> Dict[str, float]:
        out = {
            PREDEFINED[i]: from_fixed(int(v))
            for i, v in enumerate(self.predefined)
            if v != 0
        }
        out.update({k: from_fixed(v) for k, v in self.custom.items()})
        return out

    def is_empty(self) -> bool:
        return not self.custom and not self.predefined.any()

    def is_subset_of(self, other: "ResourceSet") -> bool:
        """Feasibility test: self (demand) fits in other (available).

        Exactly the reference's ``ResourceSet::IsSubset`` used in the placement
        loop (``scheduling_policy.cc:75``), in fixed-point. Pure-python tuple
        compare: this sits in the dispatch hot loop where a 4-wide numpy
        ufunc launch costs more than the comparison itself.
        """
        a, b = self.key()[0], other.key()[0]
        if (a[0] > b[0] or a[1] > b[1] or a[2] > b[2] or a[3] > b[3]):
            return False
        return all(other.custom.get(k, 0) >= v for k, v in self.custom.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        custom = dict(self.custom)
        for k, v in other.custom.items():
            custom[k] = custom.get(k, 0) + v
        return ResourceSet(self.predefined + other.predefined, custom)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        custom = dict(self.custom)
        for k, v in other.custom.items():
            custom[k] = custom.get(k, 0) - v
        return ResourceSet(self.predefined - other.predefined, custom)

    def key(self) -> Tuple:
        """Hashable interning key (basis of SchedulingClass, ref task_spec.h:190)."""
        if self._key is None:
            self._key = (tuple(self.predefined.tolist()), tuple(sorted(self.custom.items())))
        return self._key

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


class NodeResources:
    """Mutable per-node accounting: total and available ResourceSets.

    Mirrors the reference's ``SchedulingResources`` (total/available/load,
    ``common/task/scheduling_resources.h``); load is tracked by the scheduler.
    """

    __slots__ = ("total", "available")

    def __init__(self, total: ResourceSet):
        self.total = total
        self.available = total

    def acquire(self, demand: ResourceSet) -> bool:
        if not demand.is_subset_of(self.available):
            return False
        self.available = self.available.subtract(demand)
        return True

    def release(self, demand: ResourceSet) -> None:
        released = self.available.add(demand)
        # Clamp: a release should never exceed total (defensive vs. double
        # release). Custom keys no longer in total (a removed placement
        # group's bundle resources, a deleted dynamic resource) are
        # dropped — a late release must not resurrect them as phantom
        # availability. New object, not in-place: ResourceSet caches key().
        custom = {k: min(v, self.total.custom[k])
                  for k, v in released.custom.items()
                  if k in self.total.custom}
        self.available = ResourceSet(
            np.minimum(released.predefined, self.total.predefined),
            custom)

    def __repr__(self):
        return f"NodeResources(total={self.total}, available={self.available})"


# --------------------------------------------------------------------------
# Placement-group resource naming (ray_tpu/placement_group.py).
#
# A created group's bundles materialize as CUSTOM resources on their nodes
# (reference: the formatted ``CPU_group_0_<id>`` resources placement groups
# create on raylets). Tasks targeting a bundle demand those names instead of
# the base resources, so the ENTIRE existing machinery — kernel placement,
# greedy placer, GCS accounting, controller local admission — schedules
# them with zero special cases: only the bundle's node owns the name.
# --------------------------------------------------------------------------

PG_BUNDLE_MARKER = "bundle"        # synthetic per-bundle membership resource
PG_BUNDLE_CAPACITY = 1000.0        # marker capacity per bundle (ref: 1000)
PG_MARKER_DEMAND = 0.001           # marker slice a member task consumes
_PG_SEP = "_group_"


def pg_resource_name(base: str, pg_hex: str,
                     bundle_index: Optional[int] = None) -> str:
    """``CPU_group_3_<hex>`` (one bundle) or ``CPU_group_<hex>`` (wildcard:
    any bundle of the group)."""
    if bundle_index is None or bundle_index < 0:
        return f"{base}{_PG_SEP}{pg_hex}"
    return f"{base}{_PG_SEP}{bundle_index}_{pg_hex}"


def parse_pg_resource(name: str) -> Optional[Tuple[str, Optional[int], str]]:
    """(base, bundle_index|None, pg_hex) for a placement-group resource
    name; None for ordinary resources."""
    idx = name.rfind(_PG_SEP)
    if idx <= 0:
        return None
    base, tail = name[:idx], name[idx + len(_PG_SEP):]
    head, _, rest = tail.partition("_")
    if rest and head.isdigit():
        return base, int(head), rest
    return (base, None, tail) if tail else None


def translate_pg_demand(resources: Dict[str, float], pg_hex: str,
                        bundle_index: int = -1) -> Dict[str, float]:
    """Rewrite a task/actor demand to its in-group form: every base
    resource becomes the group-scoped name (bundle-specific or wildcard),
    plus a sliver of the bundle marker so even zero-resource tasks are
    pinned to the group's nodes."""
    idx = bundle_index if bundle_index >= 0 else None
    out = {pg_resource_name(k, pg_hex, idx): v
           for k, v in resources.items() if v > 0}
    out[pg_resource_name(PG_BUNDLE_MARKER, pg_hex, idx)] = PG_MARKER_DEMAND
    return out


def pg_bundle_grants(bundles, pg_hex: str):
    """Per-bundle custom-resource grant maps a reservation creates on its
    node: bundle-specific names, wildcard names (any-bundle demand), and
    the membership markers. Returns one dict per bundle; a node hosting
    several bundles sums its dicts."""
    grants = []
    for i, bundle in enumerate(bundles):
        add: Dict[str, float] = {}
        for k, v in bundle.items():
            if v <= 0:
                continue
            add[pg_resource_name(k, pg_hex, i)] = v
            add[pg_resource_name(k, pg_hex)] = \
                add.get(pg_resource_name(k, pg_hex), 0.0) + v
        add[pg_resource_name(PG_BUNDLE_MARKER, pg_hex, i)] = \
            PG_BUNDLE_CAPACITY
        add[pg_resource_name(PG_BUNDLE_MARKER, pg_hex)] = PG_BUNDLE_CAPACITY
        grants.append(add)
    return grants


def dense_matrix(sets: Iterable[ResourceSet], custom_names: Tuple[str, ...] = ()) -> np.ndarray:
    """Pack ResourceSets into an [N, R] int64 matrix for the placement kernel.

    Columns are the predefined slots followed by ``custom_names`` in order.
    """
    sets = list(sets)
    ncols = NUM_PREDEFINED + len(custom_names)
    out = np.zeros((len(sets), ncols), dtype=np.int64)
    for i, rs in enumerate(sets):
        out[i, :NUM_PREDEFINED] = rs.predefined
        for j, name in enumerate(custom_names):
            out[i, NUM_PREDEFINED + j] = rs.custom.get(name, 0)
    return out
