"""Event-loop observatory: per-loop lag/dwell/callback attribution plus
per-thread off-CPU truth from ``/proc/self/task/*`` (reference: the role
``aiodebug``/``aiomonitor`` play for asyncio loops and the off-CPU
discipline of BPF wall-clock profilers, shrunk to stdlib + procfs — the
measure-then-act lineage of arXiv:1712.05889 applied to our own control
plane).

Why: PR 17 proved the GCS *handlers* cost 2–8 µs/task while the phase
table still charges ~150 µs/task to ``submit_rpc`` — the wall cost lives
in select dwell, callback scheduling, loop lag and GIL/ctx-switch waits
that no handler timer can see. Two instruments close that gap:

* :class:`LoopMonitor` — installed on a *running* asyncio loop. A
  high-frequency heartbeat (``RAY_TPU_LOOPMON_HB_MS``, default 50 ms)
  measures **loop lag** (scheduled-vs-actual wakeup delta, the queueing
  delay every callback on that loop inherits); the selector's ``select``
  is wrapped to split wall time into **poll dwell** (waiting for IO/
  timers) vs **callback run** — the run side is the exact gap between
  one poll's exit and the next poll's entry, so the aggregate split
  costs nothing per callback. Individual callbacks are wrapped at a
  1-in-N sample (``RAY_TPU_LOOPMON_SAMPLE``, default 32; asyncio emits a
  ``call_soon`` per task step, so wrapping every one is the difference
  between <1% and ~3% warm-throughput cost) purely to *name* entries in
  the top-N slow-callback ledger (threshold ``RAY_TPU_LOOPMON_SLOW_MS``,
  default 20 ms); timers stay always-wrapped (rare, often interesting).
* :class:`ThreadCpuSampler` — per-thread utime+stime and voluntary/
  involuntary context-switch deltas from ``/proc/self/task/*``, the
  off-CPU ground truth the flight recorder's on-CPU stack tagging and
  the ``cli top`` on/off-CPU split rows are built on.

Both drain on the existing 2 s stats cadence (no timers of their own);
``RAY_TPU_LOOPMON=0`` is the kill switch — ``install()`` becomes a no-op
and the loops run exactly the untouched stock code paths.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_HB_MS = 50.0        # heartbeat cadence (loop-lag probe)
DEFAULT_SLOW_MS = 20.0      # slow-callback ledger threshold
DEFAULT_SAMPLE = 32         # time 1-in-N callbacks (naming only)
MAX_SLOW_NAMES = 64         # slow-callback ledger entries
OVERFLOW_KEY = "<overflow>"

# Histogram boundaries for loop-lag samples (ms). str keys match the
# timeseries hist-cell convention (quantile_from_hist float()s them).
LAG_BOUNDS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0)

_lock = threading.Lock()
_monitors: Dict[str, "LoopMonitor"] = {}
_cpu_sampler: Optional["ThreadCpuSampler"] = None


def enabled() -> bool:
    """Process-wide kill switch (``RAY_TPU_LOOPMON=0``)."""
    return os.environ.get("RAY_TPU_LOOPMON", "1") not in ("", "0")


def _env_ms(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def thread_cpu_ns(tid: int) -> Optional[int]:  # raylint: hotpath
    """Nanoseconds this native thread has spent on-CPU (schedstat field
    0 — updated at context-switch granularity, so even sub-tick runs
    register, unlike the 10 ms utime/stime ticks). None off-Linux or for
    an exited thread."""
    try:
        with open(f"/proc/self/task/{tid}/schedstat", "rb") as f:
            return int(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return None


# Name cache keyed by the underlying code object (Task steps and bound
# methods recur with fresh wrappers but one stable code identity — same
# per-code-object caching discipline as the flight recorder's folder).
_name_cache: Dict[Any, str] = {}
_NAME_CACHE_MAX = 4096


def _cb_name(cb: Any) -> str:
    """Stable attribution key for a loop callback: partials unwrap to
    their target, ``Task.__step`` resolves to the coroutine's code name
    (the thing a human can grep for), everything else its qualname."""
    while isinstance(cb, functools.partial):
        cb = cb.func
    owner = getattr(cb, "__self__", None)
    if owner is not None and hasattr(owner, "get_coro"):
        try:
            coro = owner.get_coro()
            code = getattr(coro, "cr_code", None) or \
                getattr(coro, "gi_code", None)
            if code is not None:
                name = _name_cache.get(code)
                if name is None:
                    if len(_name_cache) >= _NAME_CACHE_MAX:
                        _name_cache.clear()
                    name = _name_cache[code] = f"task:{code.co_name}"
                return name
        except Exception:  # noqa: BLE001 - naming must never raise
            pass
    key = getattr(cb, "__func__", cb)
    try:
        name = _name_cache.get(key)
    except TypeError:
        key = None
        name = None
    if name is None:
        name = (getattr(cb, "__qualname__", "")
                or getattr(cb, "__name__", "") or type(cb).__name__)
        if key is not None:
            if len(_name_cache) >= _NAME_CACHE_MAX:
                _name_cache.clear()
            _name_cache[key] = name
    return name


class LoopMonitor:
    """Instrumented asyncio loop: lag heartbeat + exact dwell/run split
    (from poll boundaries) + a sampled slow-callback ledger.

    All counters are written from the loop's own thread (the wrappers run
    there) and drained from a coroutine on the same loop, so the hot
    increments need no lock; a racy external ``snapshot()`` at worst
    reads a torn window, never corrupts one.
    """

    def __init__(self, component: str, loop,
                 hb_ms: Optional[float] = None,
                 slow_ms: Optional[float] = None,
                 sample: Optional[int] = None):
        self.component = component
        self.loop = loop
        self.hb_s = (hb_ms if hb_ms is not None
                     else _env_ms("RAY_TPU_LOOPMON_HB_MS",
                                  DEFAULT_HB_MS)) / 1000.0
        self.slow_s = (slow_ms if slow_ms is not None
                       else _env_ms("RAY_TPU_LOOPMON_SLOW_MS",
                                    DEFAULT_SLOW_MS)) / 1000.0
        self.sample = max(1, int(sample if sample is not None
                                 else _env_ms("RAY_TPU_LOOPMON_SAMPLE",
                                              DEFAULT_SAMPLE)))
        self.installed = False
        self._orig: Dict[str, Any] = {}
        self._hb_handle = None
        self._hb_expected = 0.0
        self._t_window0 = time.perf_counter()
        # Sampling tick, shared by every wrap site. call_soon_threadsafe
        # mutates it off-loop: a torn increment only skews WHICH callback
        # gets sampled, never the exact aggregates.
        self._tick = 0
        self._sel_exit = 0.0        # perf_counter at last select() exit
        # -- window accumulators (reset by drain) --
        self._dwell_s = 0.0
        self._polls = 0
        self._run_s = 0.0           # exact: inter-poll (non-dwell) wall
        self._cb_count = 0          # estimate: sample-weighted
        self._slow: Dict[str, List[float]] = {}       # name -> [n, sec, max]
        self._lag_buckets: Dict[str, int] = {}
        self._lag_sum_ms = 0.0
        self._lag_count = 0
        self._lag_max_ms = 0.0
        self._queue_max = 0

    # ------------------------------------------------------------ install
    def install(self) -> bool:
        """Wrap the loop's scheduling surface and start the heartbeat.
        Must run on (or before) the loop's own thread; idempotent."""
        if self.installed:
            return False
        loop = self.loop
        self._wrap_selector(loop)
        for meth in ("call_soon", "call_soon_threadsafe",
                     "call_later", "call_at"):
            self._wrap_sched(loop, meth, cb_pos=1)
        for meth in ("_add_reader", "_add_writer"):
            self._wrap_sched(loop, meth, cb_pos=1)
        self._hb_expected = loop.time() + self.hb_s
        self._hb_handle = loop.call_at(self._hb_expected, self._beat)
        self.installed = True
        return True

    def uninstall(self) -> None:
        """Restore every wrapped attribute; the loop reverts to stock
        scheduling (kill-switch semantics, pinned by tests)."""
        if self._hb_handle is not None:
            try:
                self._hb_handle.cancel()
            except Exception:  # noqa: BLE001
                pass
            self._hb_handle = None
        loop = self.loop
        sel = getattr(loop, "_selector", None)
        if sel is not None and "select" in self._orig:
            try:
                sel.select = self._orig.pop("select")
            except (AttributeError, TypeError):
                self._orig.pop("select", None)
        for meth, orig in list(self._orig.items()):
            try:
                delattr(loop, meth)
            except AttributeError:
                pass
        self._orig.clear()
        self.installed = False

    def _wrap_selector(self, loop) -> None:
        sel = getattr(loop, "_selector", None)
        if sel is None:
            return
        orig_select = sel.select

        def timed_select(timeout=None):  # raylint: hotpath
            t0 = time.perf_counter()
            # The stretch since the previous poll's exit is exactly the
            # wall the loop spent OUT of select: callbacks + loop
            # bookkeeping. This is the aggregate run/dwell split, at
            # zero per-callback cost.
            prev = self._sel_exit
            if prev:
                self._run_s += t0 - prev
            try:
                return orig_select(timeout)
            finally:
                t1 = time.perf_counter()
                self._sel_exit = t1
                self._dwell_s += t1 - t0
                self._polls += 1

        try:
            sel.select = timed_select
            self._orig["select"] = orig_select
        except (AttributeError, TypeError):
            pass  # exotic selector: dwell stays unmeasured, rest works

    def _wrap_sched(self, loop, meth: str, cb_pos: int) -> None:
        orig = getattr(loop, meth, None)
        if orig is None:
            return

        if meth in ("call_soon", "call_soon_threadsafe"):
            # One-shot callbacks at task-step frequency: the wrapper
            # closure allocation IS the cost, so only every Nth
            # scheduled callback gets one (weighted back up on drain).
            def sched(callback, *args, _orig=orig, **kw):  # raylint: hotpath
                t = self._tick + 1
                if t >= self.sample:
                    self._tick = 0
                    callback = self._timed(callback, self.sample)
                else:
                    self._tick = t
                return _orig(callback, *args, **kw)
        elif meth in ("call_later", "call_at"):
            # Timers are rare and often interesting (stats loops, GC
            # nudges, retry backoffs): always timed, weight 1.
            def sched(when, callback, *args, _orig=orig, **kw):
                return _orig(when, self._timed(callback), *args, **kw)
        else:
            # _add_reader/_add_writer: ONE registration serves every IO
            # event on the fd for the connection's lifetime, so the
            # (single) persistent wrapper samples per *invocation*.
            def sched(fd, callback, *args, _orig=orig, **kw):
                return _orig(fd, self._timed_events(callback),
                             *args, **kw)

        setattr(loop, meth, sched)
        self._orig[meth] = orig

    def _record(self, name: str, dt: float, weight: int) -> None:
        # raylint: hotpath — runs only for sampled/slow callbacks.
        self._cb_count += weight
        if dt >= self.slow_s:
            slow = self._slow
            srow = slow.get(name)
            if srow is None:
                if len(slow) >= MAX_SLOW_NAMES:
                    srow = slow.setdefault(OVERFLOW_KEY, [0, 0.0, 0.0])
                else:
                    srow = slow[name] = [0, 0.0, 0.0]
            srow[0] += 1
            srow[1] += dt
            srow[2] = max(srow[2], dt)

    def _timed(self, cb, weight: int = 1):
        """Wrap one callback with run-time + slow-ledger attribution;
        ``weight`` is how many unwrapped callbacks this sample stands
        for in the ``cb_count`` estimate."""
        if getattr(cb, "_loopmon", False):
            return cb
        name = _cb_name(cb)

        def run(*args):  # raylint: hotpath
            t0 = time.perf_counter()
            try:
                return cb(*args)
            finally:
                self._record(name, time.perf_counter() - t0, weight)

        run._loopmon = True
        return run

    def _timed_events(self, cb):
        """Persistent wrapper for reader/writer callbacks: fast path is
        one counter check per IO event; every Nth event is timed."""
        if getattr(cb, "_loopmon", False):
            return cb
        name = _cb_name(cb)

        def run(*args):  # raylint: hotpath
            t = self._tick + 1
            if t < self.sample:
                self._tick = t
                return cb(*args)
            self._tick = 0
            t0 = time.perf_counter()
            try:
                return cb(*args)
            finally:
                self._record(name, time.perf_counter() - t0, self.sample)

        run._loopmon = True
        return run

    # ---------------------------------------------------------- heartbeat
    def _beat(self) -> None:  # raylint: hotpath
        """Loop-lag probe: the delta between when this timer was due and
        when the loop actually ran it IS the queueing delay every other
        callback suffered; also samples the ready-queue depth."""
        now = self.loop.time()
        lag_ms = max(0.0, (now - self._hb_expected) * 1000.0)
        self._lag_sum_ms += lag_ms
        self._lag_count += 1
        if lag_ms > self._lag_max_ms:
            self._lag_max_ms = lag_ms
        for bound in LAG_BOUNDS_MS:
            if lag_ms <= bound:
                key = str(bound)
                break
        else:
            key = "+inf"
        self._lag_buckets[key] = self._lag_buckets.get(key, 0) + 1
        depth = len(getattr(self.loop, "_ready", ()))
        if depth > self._queue_max:
            self._queue_max = depth
        # Re-anchor from *now*: after a stall we measure fresh lag, not
        # an ever-growing backlog of missed beats.
        self._hb_expected = now + self.hb_s
        self._hb_handle = self.loop.call_at(self._hb_expected, self._beat)

    # -------------------------------------------------------------- sinks
    def drain(self) -> Dict[str, Any]:
        """Swap the window out (runs on the loop's thread via the 2 s
        stats coroutine). Returns the observatory window payload the GCS
        rolls into the time-series store."""
        now = time.perf_counter()
        out = {
            "component": self.component,
            "wall_s": max(now - self._t_window0, 1e-9),
            "dwell_s": self._dwell_s, "polls": self._polls,
            "cb_s": self._run_s, "cb_count": self._cb_count,
            "lag": {"buckets": self._lag_buckets,
                    "sum_ms": self._lag_sum_ms,
                    "count": self._lag_count,
                    "max_ms": self._lag_max_ms},
            "queue_max": self._queue_max,
            "slow": sorted(
                ([n, int(r[0]), r[1], r[2]]
                 for n, r in self._slow.items()),
                key=lambda r: -r[2])[:16],
        }
        self._t_window0 = now
        self._dwell_s = 0.0
        self._polls = 0
        self._run_s = 0.0
        self._cb_count = 0
        self._slow = {}
        self._lag_buckets = {}
        self._lag_sum_ms = 0.0
        self._lag_count = 0
        self._lag_max_ms = 0.0
        self._queue_max = 0
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Non-destructive copy of the live window (tests, `cli loops`
        against an in-process monitor)."""
        return {
            "component": self.component,
            "wall_s": max(time.perf_counter() - self._t_window0, 1e-9),
            "dwell_s": self._dwell_s, "polls": self._polls,
            "cb_s": self._run_s, "cb_count": self._cb_count,
            "lag": {"buckets": dict(self._lag_buckets),
                    "sum_ms": self._lag_sum_ms,
                    "count": self._lag_count,
                    "max_ms": self._lag_max_ms},
            "queue_max": self._queue_max,
            "slow": sorted(
                ([n, int(r[0]), r[1], r[2]]
                 for n, r in self._slow.items()),
                key=lambda r: -r[2])[:16],
        }


# --------------------------------------------------------------------------
# off-CPU truth: per-thread CPU + context-switch deltas from procfs
# --------------------------------------------------------------------------

class ThreadCpuSampler:
    """Per-window /proc/self/task/* deltas: utime+stime (CLOCK ticks) and
    voluntary/involuntary context switches per thread. One instance per
    process (``cpu_sampler()``); drained on the 2 s stats cadence, so the
    procfs walk costs ~a dozen file reads every 2 s."""

    _CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100

    def __init__(self, component: str = ""):
        self.component = component
        self.available = os.path.isdir("/proc/self/task")
        self._prev: Dict[int, tuple] = {}   # tid -> (cpu_s, vol, invol)
        self._t0 = time.perf_counter()

    @classmethod
    def _read_task(cls, tid: int) -> Optional[tuple]:
        """(comm, cpu_s, vol, invol) for one native thread."""
        try:
            with open(f"/proc/self/task/{tid}/stat") as f:
                raw = f.read()
            comm = raw[raw.index("(") + 1:raw.rindex(")")]
            fields = raw.rsplit(")", 1)[1].split()
            cpu_s = (int(fields[11]) + int(fields[12])) / cls._CLK
            vol = invol = 0
            with open(f"/proc/self/task/{tid}/status") as f:
                for line in f:
                    if line.startswith("voluntary_ctxt"):
                        vol = int(line.split()[1])
                    elif line.startswith("nonvoluntary_ctxt"):
                        invol = int(line.split()[1])
            return comm, cpu_s, vol, invol
        except (OSError, ValueError, IndexError):
            return None

    def drain(self) -> Optional[Dict[str, Any]]:
        """One delta window over every live thread; None off-Linux."""
        if not self.available:
            return None
        now = time.perf_counter()
        wall_s = max(now - self._t0, 1e-9)
        self._t0 = now
        try:
            tids = [int(d) for d in os.listdir("/proc/self/task")]
        except (OSError, ValueError):
            return None
        total_cpu = 0.0
        total_vol = 0
        total_invol = 0
        threads: Dict[str, Dict[str, float]] = {}
        seen = set()
        for tid in tids:
            row = self._read_task(tid)
            if row is None:
                continue
            comm, cpu_s, vol, invol = row
            seen.add(tid)
            prev = self._prev.get(tid)
            self._prev[tid] = (cpu_s, vol, invol)
            if prev is None:
                # First sight: whole-life totals would mislabel the
                # window; contribute nothing until the next drain.
                continue
            d_cpu = max(0.0, cpu_s - prev[0])
            d_vol = max(0, vol - prev[1])
            d_invol = max(0, invol - prev[2])
            total_cpu += d_cpu
            total_vol += d_vol
            total_invol += d_invol
            t = threads.setdefault(
                comm, {"cpu_s": 0.0, "vol": 0, "invol": 0})
            t["cpu_s"] += d_cpu
            t["vol"] += d_vol
            t["invol"] += d_invol
        for tid in list(self._prev):
            if tid not in seen:
                del self._prev[tid]
        top = dict(sorted(threads.items(),
                          key=lambda kv: -kv[1]["cpu_s"])[:12])
        return {"wall_s": wall_s, "cpu_s": total_cpu,
                "vol": total_vol, "invol": total_invol,
                "nthreads": len(seen), "threads": top}


# --------------------------------------------------------------------------
# per-process registry (mirrors flight_recorder's singleton discipline:
# the head process hosts the GCS loop AND a colocated controller loop —
# one monitor per loop, one cpu sampler per process)
# --------------------------------------------------------------------------

def install(component: str, loop=None) -> Optional[LoopMonitor]:
    """Install (or return) the monitor for ``component``'s running loop.
    None when the kill switch is set or no loop is running."""
    if not enabled():
        return None
    if loop is None:
        import asyncio
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return None
    with _lock:
        mon = _monitors.get(component)
        if mon is not None and mon.loop is loop and mon.installed:
            return mon
        mon = LoopMonitor(component, loop)
        _monitors[component] = mon
    mon.install()
    return mon


def get(component: str) -> Optional[LoopMonitor]:
    return _monitors.get(component)


def uninstall(component: str) -> None:
    with _lock:
        mon = _monitors.pop(component, None)
    if mon is not None:
        mon.uninstall()


def cpu_sampler(component: str = "") -> Optional[ThreadCpuSampler]:
    """This process's one ThreadCpuSampler (first caller's component
    labels it — same discipline as the flight-recorder singleton). None
    when the observatory is disabled."""
    global _cpu_sampler
    if not enabled():
        return None
    with _lock:
        if _cpu_sampler is None:
            _cpu_sampler = ThreadCpuSampler(component)
        return _cpu_sampler
