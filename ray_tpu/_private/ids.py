"""Binary IDs for the ray_tpu runtime.

Design follows the reference's ID scheme (reference: ``src/ray/common/id.h`` and
``src/ray/design_docs/id_specification.md``) in *semantics* — IDs are fixed-width
binary strings, task IDs embed their parent lineage hash, and object IDs are
derived from the task that creates them plus a return/put index — but the layout
is simplified: we do not need the legacy transport-type flag bits, and all
derivation is plain BLAKE2b instead of murmur hashes.

Layout:
    JobID     4 bytes   (counter on the driver)
    ActorID   12 bytes  = hash(job, parent_task, parent_counter)[:8] + job(4)
    TaskID    16 bytes  = hash(lineage)[:12] + actor_or_job(4)
    ObjectID  24 bytes  = TaskID(16) + index(4, signed: >0 returns, <0 puts) + pad(4)
    NodeID / WorkerID / PlacementGroupID  16 random bytes
"""

from __future__ import annotations

import hashlib
import os
import threading

_NIL = b"\xff"


def _hash(*parts: bytes, size: int) -> bytes:
    h = hashlib.blake2b(digest_size=size)
    for p in parts:
        h.update(p)
    return h.digest()


class BaseID:
    """A fixed-size immutable binary identifier."""

    SIZE = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def is_nil(self) -> bool:
        return self._binary == _NIL * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class UniqueID(BaseID):
    SIZE = 16


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(4, "little"))

    def int(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", parent_counter: int) -> "ActorID":
        body = _hash(
            job_id.binary(),
            parent_task_id.binary(),
            parent_counter.to_bytes(8, "little"),
            size=8,
        )
        return cls(body + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[8:12])


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def for_driver_task(cls, job_id: JobID) -> "TaskID":
        return cls(_hash(b"driver", job_id.binary(), size=12) + job_id.binary())

    @classmethod
    def for_normal_task(
        cls, job_id: JobID, parent_task_id: "TaskID", parent_counter: int
    ) -> "TaskID":
        body = _hash(
            b"task",
            job_id.binary(),
            parent_task_id.binary(),
            parent_counter.to_bytes(8, "little"),
            size=12,
        )
        return cls(body + job_id.binary())

    @classmethod
    def for_actor_creation_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_hash(b"actor_creation", actor_id.binary(), size=12) + actor_id.binary()[8:12])

    @classmethod
    def for_actor_task(
        cls, job_id: JobID, parent_task_id: "TaskID", parent_counter: int, actor_id: ActorID
    ) -> "TaskID":
        body = _hash(
            b"actor_task",
            actor_id.binary(),
            parent_task_id.binary(),
            parent_counter.to_bytes(8, "little"),
            size=12,
        )
        return cls(body + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[12:16])


class ObjectID(BaseID):
    """ObjectID = producing TaskID + signed index.

    index > 0: the index-th return value of the task.
    index < 0: the (-index)-th ``put`` performed by the task.
    """

    SIZE = 24
    MAX_INDEX = 2**31 - 1

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        assert 0 < index <= cls.MAX_INDEX
        return cls(task_id.binary() + index.to_bytes(4, "little", signed=True) + b"\x00" * 4)

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        assert 0 < put_index <= cls.MAX_INDEX
        return cls(
            task_id.binary() + (-put_index).to_bytes(4, "little", signed=True) + b"\x00" * 4
        )

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:16])

    def index(self) -> int:
        return int.from_bytes(self._binary[16:20], "little", signed=True)

    def is_return(self) -> bool:
        return self.index() > 0

    def is_put(self) -> bool:
        return self.index() < 0


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


__all__ = [
    "BaseID",
    "UniqueID",
    "NodeID",
    "WorkerID",
    "PlacementGroupID",
    "JobID",
    "ActorID",
    "TaskID",
    "ObjectID",
]
