"""SIGUSR1 stack dumps for cluster processes.

Reference: ``ray stack`` (python/ray/scripts/scripts.py:1000) shells out to
py-spy; py-spy isn't in this image, so every cluster process registers a
faulthandler that appends all-thread tracebacks to a per-pid file under
``/tmp/ray_tpu_stacks/`` on SIGUSR1. ``cli stack`` signals the session's
process tree and prints the files.
"""

from __future__ import annotations

import faulthandler
import os
import signal

STACK_DIR = "/tmp/ray_tpu_stacks"

_registered_file = None


def register_stack_dump() -> str:
    """Idempotently register the SIGUSR1 all-threads dump for this process."""
    global _registered_file
    path = os.path.join(STACK_DIR, f"{os.getpid()}.txt")
    if _registered_file is not None:
        return path
    try:
        os.makedirs(STACK_DIR, exist_ok=True)
        _registered_file = open(path, "a")
        faulthandler.register(
            signal.SIGUSR1, file=_registered_file, all_threads=True
        )
    except (OSError, ValueError, AttributeError):
        # Non-main interpreter / restricted platform: stacks are a debug
        # aid, never a startup failure.
        _registered_file = None
    return path
