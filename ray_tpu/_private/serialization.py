"""Serialization: cloudpickle control plane + zero-copy buffer data plane.

Equivalent role to the reference's ``SerializationContext`` (reference:
``python/ray/serialization.py:88`` — cloudpickle with pickle5 out-of-band
buffers for zero-copy numpy, plus custom serializers for handles/refs), but
TPU-native on the data plane: jax.Arrays are exported via ``__array__`` /
dlpack to host buffers on serialize and restored with ``jax.device_put`` on
deserialize, so large tensors move as raw out-of-band buffers, never through
pickle's byte stream.

Wire format of a serialized object:
    header  = pickle.dumps(obj, protocol=5, buffer_callback=...)
    buffers = list of raw PickleBuffer payloads (zero-copy views when possible)
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

import cloudpickle
import numpy as np


@dataclass
class SerializedObject:
    header: bytes
    buffers: List[pickle.PickleBuffer]
    # ObjectIDs (binary) of ObjectRefs pickled inside this object. Not part
    # of the wire format: the serializing process reports them to its ref
    # counter so contained refs keep their targets alive (reference:
    # reference_count.h nested/contained refs, AddNestedObjectIds).
    contained_refs: List[bytes] = field(default_factory=list)

    def total_bytes(self) -> int:
        return len(self.header) + sum(b.raw().nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one byte string (for cross-process transport)."""
        parts = [len(self.header).to_bytes(8, "little"), self.header,
                 len(self.buffers).to_bytes(4, "little")]
        for b in self.buffers:
            raw = b.raw()
            parts.append(raw.nbytes.to_bytes(8, "little"))
            parts.append(raw.tobytes() if raw.ndim else bytes(raw))
        return b"".join(parts)

    def framed_size(self) -> int:
        """Size of the to_bytes() framing without materializing it."""
        return (8 + len(self.header) + 4
                + sum(8 + b.raw().nbytes for b in self.buffers))

    def write_into(self, view: memoryview) -> int:
        """Write the to_bytes() layout directly into ``view`` (e.g. a shm
        arena slot) — one copy from source buffers instead of two."""
        off = 0

        def w(b: bytes):
            nonlocal off
            view[off:off + len(b)] = b
            off += len(b)

        w(len(self.header).to_bytes(8, "little"))
        w(self.header)
        w(len(self.buffers).to_bytes(4, "little"))
        for b in self.buffers:
            raw = b.raw()
            w(raw.nbytes.to_bytes(8, "little"))
            try:
                flat = raw.cast("B")
            except TypeError:
                flat = memoryview(raw.tobytes())
            view[off:off + raw.nbytes] = flat
            off += raw.nbytes
        return off

    @classmethod
    def from_bytes(cls, data: bytes) -> "SerializedObject":
        view = memoryview(data)
        hlen = int.from_bytes(view[:8], "little")
        header = bytes(view[8 : 8 + hlen])
        off = 8 + hlen
        nbuf = int.from_bytes(view[off : off + 4], "little")
        off += 4
        buffers = []
        for _ in range(nbuf):
            blen = int.from_bytes(view[off : off + 8], "little")
            off += 8
            buffers.append(pickle.PickleBuffer(view[off : off + blen]))
            off += blen
        return cls(header, buffers)


class _JaxArrayPlaceholder:
    """Pickled stand-in for a jax.Array; data travels out-of-band."""

    __slots__ = ("dtype", "shape", "buffer_index", "sharding_repr")

    def __init__(self, dtype, shape, buffer_index, sharding_repr=None):
        self.dtype = dtype
        self.shape = shape
        self.buffer_index = buffer_index
        self.sharding_repr = sharding_repr


class SerializationContext:
    """Process-wide serializer with custom-type hooks."""

    def __init__(self):
        self._custom: Dict[Type, Tuple[Callable, Callable]] = {}

    def register_custom_serializer(
        self, cls: Type, serializer: Callable, deserializer: Callable
    ) -> None:
        self._custom[cls] = (serializer, deserializer)

    # -- serialize ------------------------------------------------------------
    def serialize(self, value: Any) -> SerializedObject:
        from ..object_ref import ObjectRef

        buffers: List[pickle.PickleBuffer] = []
        oob_arrays: List[Any] = []  # device arrays exported out-of-band
        contained: List[bytes] = []

        def reducer_override(obj):
            custom = self._custom.get(type(obj))
            if custom is not None:
                ser, de = custom
                payload = ser(obj)
                return (_apply_deserializer, (de, payload))
            if isinstance(obj, ObjectRef):
                # Record and fall through to ObjectRef.__reduce__.
                contained.append(obj.id.binary())
                return NotImplemented
            if _is_jax_array(obj):
                idx = len(oob_arrays)
                oob_arrays.append(obj)
                return (
                    _JaxArrayPlaceholder,
                    (np.dtype(obj.dtype).str, tuple(obj.shape), idx, None),
                )
            return NotImplemented

        pickler = _Pickler(
            buffers.append, reducer_override, protocol=5
        )
        header = pickler.dumps(value)
        # Device arrays: append host views after the in-band buffers so
        # buffer_index in the placeholder is len(inband)+idx — we instead
        # record absolute indices by appending now and patching placeholders
        # at unpickle time via the recorded order (placeholders store their
        # position in oob_arrays; absolute index = n_inband + position).
        n_inband = len(buffers)
        for arr in oob_arrays:
            host = np.asarray(arr)  # device->host copy (single transfer)
            buffers.append(pickle.PickleBuffer(host))
        return SerializedObject(
            header=_prefix_oob_base(header, n_inband), buffers=buffers,
            contained_refs=contained,
        )

    # -- deserialize ----------------------------------------------------------
    def deserialize(self, serialized: SerializedObject, device_put: bool = False) -> Any:
        oob_base, header = _strip_oob_base(serialized.header)
        value = pickle.loads(header, buffers=serialized.buffers)
        return _restore_jax_arrays(value, serialized.buffers, oob_base, device_put)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, buffer_callback, reducer_override_fn, protocol=5):
        import io

        self._file = io.BytesIO()
        super().__init__(self._file, protocol=protocol, buffer_callback=buffer_callback)
        self._reducer_override_fn = reducer_override_fn

    def reducer_override(self, obj):
        reduced = self._reducer_override_fn(obj)
        if reduced is not NotImplemented:
            return reduced
        # Fall back to cloudpickle's own reducers (functions, classes, ...).
        return super().reducer_override(obj)

    def dumps(self, value) -> bytes:
        self.dump(value)
        return self._file.getvalue()


def _is_jax_array(obj) -> bool:
    try:
        import jax
        return isinstance(obj, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def _apply_deserializer(de, payload):
    return de(payload)


_OOB_MAGIC = b"RTOB"


def _prefix_oob_base(header: bytes, n_inband: int) -> bytes:
    return _OOB_MAGIC + n_inband.to_bytes(4, "little") + header


def _strip_oob_base(header: bytes) -> Tuple[int, bytes]:
    assert header[:4] == _OOB_MAGIC
    return int.from_bytes(header[4:8], "little"), header[8:]


def _restore_jax_arrays(value, buffers, oob_base, device_put):
    """Walk the object graph replacing _JaxArrayPlaceholder with real arrays."""
    placeholder_found = _contains_placeholder(value)
    if not placeholder_found:
        return value

    def restore(obj, seen):
        if isinstance(obj, _JaxArrayPlaceholder):
            buf = buffers[oob_base + obj.buffer_index]
            host = np.frombuffer(buf, dtype=np.dtype(obj.dtype)).reshape(obj.shape)
            if device_put:
                import jax
                return jax.device_put(host)
            import jax
            return jax.device_put(host)  # always rebuild as jax.Array
        oid = id(obj)
        if oid in seen:
            return obj
        seen.add(oid)
        if isinstance(obj, list):
            for i, v in enumerate(obj):
                obj[i] = restore(v, seen)
            return obj
        if isinstance(obj, dict):
            for k in list(obj):
                obj[k] = restore(obj[k], seen)
            return obj
        if isinstance(obj, tuple):
            return tuple(restore(v, seen) for v in obj)
        if hasattr(obj, "__dict__"):
            for k, v in vars(obj).items():
                setattr(obj, k, restore(v, seen))
            return obj
        return obj

    return restore(value, set())


def _contains_placeholder(value, depth=0) -> bool:
    if isinstance(value, _JaxArrayPlaceholder):
        return True
    if depth > 6:
        return True  # deep graph: be conservative, walk it
    if isinstance(value, (list, tuple)):
        return any(_contains_placeholder(v, depth + 1) for v in value)
    if isinstance(value, dict):
        return any(_contains_placeholder(v, depth + 1) for v in value.values())
    if hasattr(value, "__dict__"):
        return any(_contains_placeholder(v, depth + 1) for v in vars(value).values())
    return False


_global_context: Optional[SerializationContext] = None


def get_context() -> SerializationContext:
    global _global_context
    if _global_context is None:
        _global_context = SerializationContext()
    return _global_context
