"""Fault-injection harness for cluster chaos testing.

Reference counterpart: Ray's ``ray._private.test_utils`` failure helpers +
the chaos-testing ``NodeKillerActor`` — collapsed into one env-driven
module so any process in the cluster can be told to misbehave without code
changes. The head-failover soak scenario and the chaos-matrix tests drive
these knobs; ``docs/devtools.md`` documents them.

Env knobs (all off by default; read once at :func:`install_from_env`):

``RAY_TPU_CHAOS_DROP_FRAME_P``
    Probability in [0, 1] that an inbound RPC frame is dropped on the
    floor by the server (the sender sees a timeout, not an error — the
    lost-oneway / lost-request case).
``RAY_TPU_CHAOS_DELAY_FRAME_P`` / ``RAY_TPU_CHAOS_DELAY_FRAME_MS``
    Probability that an inbound frame is delayed, and the maximum delay in
    milliseconds (uniform in [0, max]).
``RAY_TPU_CHAOS_PARTITION_NODE``
    Node-id prefix to partition: every frame arriving on a connection that
    registered that node is dropped (a one-way network partition as seen
    from this server).
``RAY_TPU_CHAOS_KILL_HEAD_AFTER_S``
    In a head process: SIGKILL the whole process after N seconds (the hard
    leader-death drill).
``RAY_TPU_CHAOS_PAUSE_HEAD_AFTER_S`` / ``RAY_TPU_CHAOS_PAUSE_HEAD_S``
    In a head process: SIGSTOP after N seconds, SIGCONT after a further M
    seconds (default 10) — the deposed-leader/split-brain drill: the head
    wakes up believing it still leads and must find its lease stolen.
``RAY_TPU_CHAOS_KILL_WORKER_EVERY_S``
    In a controller process: SIGKILL one random live worker process every
    N seconds (armed controller-side in ``Controller.start``) — the
    blast-radius drill: blame attribution, collateral re-drive, and the
    poison-quarantine counters all run under it.
``RAY_TPU_CHAOS_KILL_REPLICA_EVERY_S``
    In a serve driver: kill one random replica of a backend every N
    seconds (armed by :func:`arm_replica_killer`, driven by
    ``scripts/serve_soak.py``) — the self-healing fleet drill: failover
    routing, stream fast-fail, and replica auto-replacement all run
    under it.
``RAY_TPU_CHAOS_SEED``
    Deterministic RNG seed for the drop/delay draws.

Hostile-task helpers (:func:`hostile_hang`, :func:`hostile_segfault`,
:func:`hostile_oom`) are plain functions meant to be submitted as remote
tasks by chaos workloads (``scripts/soak.py hostile_workload``, the
containment test suite): a hanger for the deadline killer, a
crash-looper for quarantine, an allocator bomb for the OOM guard.
"""

from __future__ import annotations

import os
import random
import signal
import threading
from typing import Optional


def _env_f(name: str, default: float = 0.0) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class Chaos:
    """One process's installed fault plan. Decision methods are cheap and
    called from the server's event loop; timers run on daemon threads."""

    def __init__(self, drop_p: float = 0.0, delay_p: float = 0.0,
                 delay_max_ms: float = 0.0, partition_node: str = "",
                 seed: Optional[int] = None):
        self.drop_p = max(0.0, min(1.0, drop_p))
        self.delay_p = max(0.0, min(1.0, delay_p))
        self.delay_max_s = max(0.0, delay_max_ms) / 1000.0
        self.partition_node = partition_node
        self._rng = random.Random(seed)
        # Counters for tests/postmortems (single-threaded loop updates).
        self.dropped = 0
        self.delayed = 0

    def should_drop_frame(self, conn_meta: Optional[dict] = None) -> bool:
        """Drop decision for one inbound frame (server side)."""
        if self.partition_node and conn_meta is not None:
            nid = str(conn_meta.get("node_id") or "")
            if nid and nid.startswith(self.partition_node):
                self.dropped += 1
                return True
        if self.drop_p > 0.0 and self._rng.random() < self.drop_p:
            self.dropped += 1
            return True
        return False

    def frame_delay_s(self) -> float:
        """Extra latency to inject before handling one frame (0 = none)."""
        if self.delay_p > 0.0 and self._rng.random() < self.delay_p:
            self.delayed += 1
            return self._rng.uniform(0.0, self.delay_max_s)
        return 0.0

    @property
    def active(self) -> bool:
        return bool(self.drop_p or self.delay_p or self.partition_node)


# The process-wide plan. Written once by install_from_env() before the
# server starts serving, read by the protocol layer per frame.
_active: Optional[Chaos] = None


def get() -> Optional[Chaos]:
    return _active


def install_from_env() -> Optional[Chaos]:
    """Read the env knobs; install and return a plan when any is set."""
    global _active
    plan = Chaos(
        drop_p=_env_f("RAY_TPU_CHAOS_DROP_FRAME_P"),
        delay_p=_env_f("RAY_TPU_CHAOS_DELAY_FRAME_P"),
        delay_max_ms=_env_f("RAY_TPU_CHAOS_DELAY_FRAME_MS"),
        partition_node=os.environ.get("RAY_TPU_CHAOS_PARTITION_NODE", ""),
        seed=int(_env_f("RAY_TPU_CHAOS_SEED")) or None,
    )
    if plan.active:
        _active = plan
        return plan
    return None


def uninstall() -> None:
    global _active
    _active = None


# ------------------------------------------------------------ hostile tasks
# Helpers submitted AS tasks by chaos workloads. Top-level functions so
# they pickle by reference; each models one blast-radius failure mode.

def hostile_hang(seconds: float = 3600.0) -> str:
    """Run (far) past any sane deadline — the deadline killer's prey.
    Returns only if nothing killed it (a containment failure)."""
    import time as _time

    _time.sleep(seconds)
    return "hung task survived"


def hostile_segfault() -> None:
    """Die with SIGSEGV, taking the worker process with it — the
    poison-quarantine counter's prey (3 strikes by default)."""
    os.kill(os.getpid(), signal.SIGSEGV)


def hostile_exit(code: int = 13) -> None:
    """Hard-exit the worker without a signal (os._exit skips every
    finally/atexit) — the exit-code blame-classification case."""
    os._exit(code)


def hostile_oom(target_bytes: int = 1 << 30,
                step_bytes: int = 32 << 20,
                hold_s: float = 60.0) -> str:
    """Allocate RSS in steps up to ``target_bytes`` and sit on it — the
    OOM guard's prey: declare a small ``memory`` resource and grow well
    past it. Real pages (bytearrays are touched), so the RSS sampler
    sees the growth."""
    import time as _time

    hoard = []
    held = 0
    while held < target_bytes:
        block = bytearray(min(step_bytes, target_bytes - held))
        for i in range(0, len(block), 4096):
            block[i] = 1  # touch every page: reserved != resident
        hoard.append(block)
        held += len(block)
        _time.sleep(0.01)
    _time.sleep(hold_s)
    return f"oom bomb survived holding {held} bytes"


# ---------------------------------------------------------------- process
# helpers: kill / pause / resume by pid (the head-failover drill and
# `cli kill_random_node --head` use these; SIGSTOP/SIGCONT model a hung —
# not dead — leader, the split-brain case fencing must win).

def kill_process(pid: int) -> bool:
    try:
        os.kill(pid, signal.SIGKILL)
        return True
    except (OSError, ProcessLookupError):
        return False


def pause_process(pid: int) -> bool:
    try:
        os.kill(pid, signal.SIGSTOP)
        return True
    except (OSError, ProcessLookupError):
        return False


def resume_process(pid: int) -> bool:
    try:
        os.kill(pid, signal.SIGCONT)
        return True
    except (OSError, ProcessLookupError):
        return False


def arm_replica_killer(master: object, backend_tag: str,
                       every_s: float = 0.0,
                       stop: Optional[threading.Event] = None,
                       on_kill=None) -> threading.Event:
    """In a serve driver: kill one RANDOM live replica of ``backend_tag``
    every ``every_s`` seconds (env ``RAY_TPU_CHAOS_KILL_REPLICA_EVERY_S``
    when 0) until the returned Event is set. The self-healing drill:
    each kill must produce a router failover + a master replacement, not
    a client-visible failure. ``on_kill(handle)`` is called after each
    kill (soaks count kills survived)."""
    import ray_tpu

    stop = stop or threading.Event()
    every_s = every_s or _env_f("RAY_TPU_CHAOS_KILL_REPLICA_EVERY_S")
    if every_s <= 0:
        stop.set()
        return stop
    rng = random.Random(int(_env_f("RAY_TPU_CHAOS_SEED")) or None)

    def _loop():
        while not stop.wait(every_s):
            try:
                replicas = ray_tpu.get(
                    master.get_replicas.remote(backend_tag))
                if not replicas:
                    continue
                victim = rng.choice(replicas)
                ray_tpu.kill(victim)
                if on_kill is not None:
                    on_kill(victim)
            except Exception:  # noqa: BLE001 - chaos must not crash the soak
                if not ray_tpu.is_initialized():
                    return

    t = threading.Thread(target=_loop, name="chaos-replica-killer",
                         daemon=True)
    t.start()
    return stop


def arm_head_timers() -> None:
    """In a head process: arm the self-kill / self-pause timers from the
    env knobs. Daemon threads so they never block shutdown."""
    kill_after = _env_f("RAY_TPU_CHAOS_KILL_HEAD_AFTER_S")
    if kill_after > 0:
        t = threading.Timer(kill_after, kill_process, args=(os.getpid(),))
        t.daemon = True
        t.start()
    pause_after = _env_f("RAY_TPU_CHAOS_PAUSE_HEAD_AFTER_S")
    if pause_after > 0:
        pause_s = _env_f("RAY_TPU_CHAOS_PAUSE_HEAD_S", 10.0)

        def _pause_then_resume():
            pid = os.getpid()
            resume = threading.Timer(pause_s, resume_process, args=(pid,))
            resume.daemon = True
            resume.start()  # armed BEFORE the stop: we can't run while stopped
            pause_process(pid)

        t = threading.Timer(pause_after, _pause_then_resume)
        t.daemon = True
        t.start()
