"""Per-node physical stats sampler (reference: python/ray/dashboard/
reporter.py, which shells out to psutil; psutil isn't in this image, so the
sampler reads /proc directly — Linux is the only deploy target).

Stateful: CPU percentages are deltas between consecutive ``sample()`` calls
(first call returns 0% like psutil's interval=None convention).
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Dict, Iterable, Optional

_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def _read_cpu_total() -> Optional[tuple]:
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
        vals = [int(v) for v in parts[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
        return sum(vals), idle
    except (OSError, ValueError, IndexError):
        return None


def _read_meminfo() -> Dict[str, int]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                out[key] = int(rest.split()[0]) * 1024  # kB -> bytes
    except (OSError, ValueError, IndexError):
        pass
    return out


def _read_proc_cpu(pid: int) -> Optional[float]:
    """Cumulative CPU seconds of one process."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
        # utime, stime are fields 14,15 (1-indexed); after the comm split
        # they land at offsets 11,12.
        return (int(fields[11]) + int(fields[12])) / _CLK
    except (OSError, ValueError, IndexError):
        return None


def _read_proc_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class NodeStatsSampler:
    def __init__(self):
        self._last_total: Optional[tuple] = None
        self._last_proc: Dict[int, tuple] = {}  # pid -> (wall, cpu_seconds)

    def sample(self, worker_pids: Iterable[int] = ()) -> Dict:
        now = time.monotonic()
        stats: Dict = {"ts": time.time(), "num_cpus": os.cpu_count() or 1}

        cur = _read_cpu_total()
        if cur is not None and self._last_total is not None:
            d_total = cur[0] - self._last_total[0]
            d_idle = cur[1] - self._last_total[1]
            stats["cpu_percent"] = round(
                100.0 * (d_total - d_idle) / max(d_total, 1), 1)
        else:
            stats["cpu_percent"] = 0.0
        if cur is not None:
            self._last_total = cur

        mem = _read_meminfo()
        if mem:
            total = mem.get("MemTotal", 0)
            avail = mem.get("MemAvailable", 0)
            stats["mem_total_bytes"] = total
            stats["mem_available_bytes"] = avail
            stats["mem_percent"] = round(
                100.0 * (total - avail) / max(total, 1), 1)
        try:
            stats["load_avg"] = list(os.getloadavg())
        except OSError:
            stats["load_avg"] = [0.0, 0.0, 0.0]
        try:
            du = shutil.disk_usage("/tmp")
            stats["disk_percent"] = round(100.0 * du.used / max(du.total, 1), 1)
        except OSError:
            pass

        workers = []
        seen = set()
        for pid in list(worker_pids):
            seen.add(pid)
            cpu_s = _read_proc_cpu(pid)
            if cpu_s is None:
                continue
            pct = 0.0
            last = self._last_proc.get(pid)
            if last is not None and now > last[0]:
                pct = round(100.0 * (cpu_s - last[1]) / (now - last[0]), 1)
            self._last_proc[pid] = (now, cpu_s)
            workers.append({"pid": pid, "cpu_percent": max(pct, 0.0),
                            "rss_bytes": _read_proc_rss(pid)})
        for pid in list(self._last_proc):
            if pid not in seen:
                del self._last_proc[pid]
        stats["workers"] = workers
        return stats
