"""Typed runtime configuration registry.

Equivalent role to the reference's ``RAY_CONFIG(type, name, default)`` macro
registry (reference: ``src/ray/common/ray_config_def.h``): a single process-wide
table of typed knobs, overridable at ``init()`` time via a ``_system_config``
dict or via ``RAY_TPU_<NAME>`` environment variables.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional


def _env(name: str, default, cast):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes")
    return cast(raw)


@dataclass
class Config:
    # --- heartbeats / failure detection (reference: ray_config_def.h:38,46) ---
    heartbeat_interval_ms: int = 100
    num_heartbeats_timeout: int = 30
    # --- scheduling ---
    scheduler_backend: str = "jax"  # "jax" | "scalar"
    scheduler_tick_ms: int = 2
    scheduler_spread_threshold: float = 0.5
    max_tasks_per_tick: int = 65536
    # --- objects ---
    max_direct_call_object_size: int = 100 * 1024  # inline threshold, ref ray_config_def.h:117
    object_store_memory: int = 2 * 1024**3
    object_transfer_chunk_bytes: int = 1024 * 1024  # ref ray_config_def.h:242
    free_objects_batch_size: int = 100
    # Spill-to-disk under memory pressure (reference: plasma
    # external_store.h + quota_aware_policy.cc). Arena use above the high
    # watermark spills cold unpinned sealed objects down to the low one;
    # producers over the high watermark back off (bounded) before putting.
    object_spill_enabled: bool = True
    object_spill_dir: str = ""  # "" => <tmpdir>/ray_tpu_spill/<store name>
    object_spill_high_watermark: float = 0.85
    object_spill_low_watermark: float = 0.60
    # Per-owner arena byte quota, LRU-within-owner enforced (0 = off).
    object_store_owner_quota: int = 0
    # Owner-side put backpressure: bounded wait (exponential backoff) while
    # the node is over its spill high watermark. 0 disables the wait.
    put_backpressure_max_wait_s: float = 2.0
    # Owner-side refcount GC (reference: core_worker/reference_count.h:33)
    ref_counting_enabled: bool = True
    # --- tasks / actors ---
    max_retries_default: int = 4  # ref doc/source/fault-tolerance.rst:12
    actor_max_restarts_default: int = 0
    max_pending_lease_requests: int = 10
    worker_lease_timeout_ms: int = 500
    # Owner worker leases + direct push (reference: direct task transport,
    # direct_task_transport.h:49): dependency-free tasks skip the GCS queue
    # and go straight to a leased worker while few results are outstanding.
    direct_call_enabled: bool = True
    direct_call_max_outstanding: int = 32
    direct_lease_idle_s: float = 5.0
    # --- workers ---
    num_workers_soft_limit: int = 0  # 0 => num_cpus
    worker_register_timeout_s: int = 30
    maximum_startup_concurrency: int = 8
    # --- lineage / reconstruction ---
    max_lineage_size: int = 100  # ref ray_config_def.h:157
    task_lease_timeout_ms: int = 1000
    # --- logging / debug ---
    debug_dump_period_ms: int = 10000
    event_log_enabled: bool = True
    # Cluster event-log ring size (GCS cluster_events deque; overflow is
    # counted in events_dropped and surfaced by `cli events`).
    event_log_size: int = 20_000
    # --- observability: flight recorder + time-series rollups ---
    # Continuous stack sampler (env kill switch RAY_TPU_FLIGHT_RECORDER=0;
    # rate via RAY_TPU_FLIGHT_RECORDER_HZ, default 20).
    flight_recorder: bool = True
    # GCS time-series store: fixed bucket width and per-series retention
    # ring (360 x 10 s = one hour of rollups), rolled every tick.
    timeseries_bucket_s: int = 10
    timeseries_retention_buckets: int = 360
    timeseries_tick_s: float = 2.0
    # Consistency auditor: seconds between periodic GCS reconciliation
    # passes (directory vs controller arenas/spill dirs/rings/task table).
    # <= 0 disables the loop; `cli doctor` still audits on demand.
    audit_interval_s: float = 30.0
    # --- head HA: GCS reconnect / leadership / replication ---
    # ResilientClient re-dial budget per call (was a hardcoded 30 s) and
    # the jittered-exponential-backoff shape of the re-dials
    # (sleep = min(cap, base * 2^attempt) * uniform[0.5, 1.5)).
    gcs_retry_window_s: float = 30.0
    gcs_retry_backoff_base_s: float = 0.05
    gcs_retry_backoff_cap_s: float = 2.0
    # Extra GCS addresses clients rotate through on reconnect
    # ("host:port,host:port" — typically the warm standby).
    gcs_addrs: str = ""
    # Leadership lease: the leader renews every ttl/3; a standby may steal
    # only after expiry (epoch bump). Must comfortably exceed one renewal
    # round-trip to the persistent store.
    gcs_lease_ttl_s: float = 3.0
    # Replication log: buffered on-loop, flushed to the snapshot backend
    # off-loop at this cadence (the acked-but-unflushed window a hard head
    # kill can lose; the warm standby's wire tail usually covers it).
    gcs_repl_flush_interval_s: float = 0.05
    # Warm standby: leader-tail poll cadence and the in-memory ring of
    # recent records the leader serves tails from (a standby farther
    # behind than the ring gets a full-snapshot resync).
    gcs_standby_poll_interval_s: float = 0.1
    gcs_repl_ring_size: int = 65536
    # --- raw overrides applied last ---
    _overrides: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        for f in fields(self):
            if f.name.startswith("_"):
                continue
            cast = type(getattr(self, f.name))
            setattr(self, f.name, _env(f.name, getattr(self, f.name), cast))

    def update(self, overrides: Optional[Dict[str, Any]] = None) -> "Config":
        for key, value in (overrides or {}).items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown config key: {key}")
            setattr(self, key, value)
            self._overrides[key] = value
        return self


_global_config: Optional[Config] = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def reset_config(overrides: Optional[Dict[str, Any]] = None) -> Config:
    global _global_config
    _global_config = Config().update(overrides)
    return _global_config
