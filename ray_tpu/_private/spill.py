"""Object spill-to-disk: graceful degradation under memory pressure.

Reference counterpart: plasma's external-store spill interface
(``plasma/external_store.h``) + quota-aware eviction
(``plasma/quota_aware_policy.cc``). The reference evicts cold objects to an
external store when the shared-memory arena runs out; here the external
store is a directory of checksummed files, and the policy layer lives in
Python so the native arena stays a dumb allocator.

Three pieces:

``SpillManager``
    An on-disk object directory. Writes are atomic (tmp file + fsync +
    rename) and checksummed (crc32 in a fixed header), so a crash mid-spill
    can never serve torn bytes: the restart scan drops stray ``.tmp`` files
    and truncated entries, and a checksum mismatch at read time deletes the
    file and reports a miss instead of returning garbage.

``SpillingStore``
    Wraps a node's arena (``ShmObjectStore`` or ``PyObjectStore``) with the
    spill policy: puts that would push the arena over its high watermark
    first spill cold **unpinned sealed** objects (LRU by last wrapper
    access) down to the low watermark; objects that cannot fit even then go
    straight to disk. ``get()`` is arena-first, disk-second — a disk hit is
    transparently restored into the arena (making room the same way) so hot
    objects migrate back. Per-owner byte quotas evict LRU-within-owner.

    The wrapper spills BEFORE the native allocator's own evictor would kick
    in: native eviction *drops* bytes (recoverable only through lineage),
    spilling preserves them. The native evictor remains the backstop for
    writers that bypass the wrapper (same-host workers writing straight
    into the arena) — the controller keeps headroom for them by calling
    ``maybe_spill()`` on its heartbeat.

``put_backpressure``
    Owner-side bounded wait: a producer whose node is over the spill high
    watermark backs off (exponential, capped total wait) instead of racing
    the spiller — a runaway producer slows down rather than OOM-killing
    the node.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

from .._native.shm_store import PinnedBuffer, StoreFullError, _pad_id

# File layout: header (magic, crc32 of payload, payload size) + payload.
_MAGIC = b"RTPSPL1\n"
_HEADER = struct.Struct("<8sIQ")


class _SpillMetrics:
    """Lazily-registered spill counters (shared across stores in-process)."""

    _instance: Optional["_SpillMetrics"] = None
    _lock = threading.Lock()

    def __init__(self):
        from ..metrics import Count, Histogram, get_or_create

        self.spilled_bytes = get_or_create(
            Count, "object_store_spilled_bytes",
            description="bytes moved from the arena to the spill directory")
        self.restored_bytes = get_or_create(
            Count, "object_store_restored_bytes",
            description="bytes restored from the spill directory")
        self.spill_latency_ms = get_or_create(
            Histogram, "object_store_spill_latency_ms",
            description="per-object spill write latency",
            boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500])
        self.restore_latency_ms = get_or_create(
            Histogram, "object_store_restore_latency_ms",
            description="per-object restore read latency",
            boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500])
        self.quota_evictions = get_or_create(
            Count, "object_store_quota_evictions",
            description="objects spilled by per-owner quota enforcement")
        self.backpressure_wait_ms = get_or_create(
            Histogram, "object_put_backpressure_wait_ms",
            description="producer-side bounded wait under memory pressure",
            boundaries=[1, 5, 10, 50, 100, 500, 1000, 5000])

    @classmethod
    def get(cls) -> "_SpillMetrics":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance


class SpillManager:
    """Crash-safe on-disk object directory (the external store).

    One file per object (``<oid hex>.obj``), written atomically and
    checksummed. Safe for concurrent use from multiple threads of one
    process; multi-process coordination is the caller's job (each node
    store owns its own directory).
    """

    def __init__(self, spill_dir: str):
        self.dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._index: Dict[bytes, int] = {}  # oid -> payload size
        self._scan()

    # ------------------------------------------------------------------ paths
    def _path(self, oid: bytes) -> str:
        return os.path.join(self.dir, oid.hex() + ".obj")

    def _scan(self) -> None:
        """Restart scan: index valid entries, drop torn/stray files. Run at
        construction so a crashed node's spilled objects survive a restart
        of its controller (the directory outlives the arena)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            path = os.path.join(self.dir, name)
            if name.endswith(".tmp"):
                # A writer died mid-spill; the object was still in the
                # arena when this was being written, so the file is pure
                # garbage — never a lost copy.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if not name.endswith(".obj"):
                continue
            try:
                with open(path, "rb") as f:
                    hdr = f.read(_HEADER.size)
                magic, _crc, size = _HEADER.unpack(hdr)
                if magic != _MAGIC:
                    raise ValueError("bad magic")
                if os.path.getsize(path) != _HEADER.size + size:
                    raise ValueError("truncated")
                self._index[bytes.fromhex(name[:-4])] = size
            except (OSError, ValueError, struct.error):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # ------------------------------------------------------------------- ops
    def write(self, oid: bytes, data) -> int:
        """Atomically persist one object; returns payload bytes written.
        Idempotent: an existing entry is kept (objects are immutable)."""
        if not isinstance(data, (bytes, bytearray)):
            data = bytes(data)
        with self._lock:
            if oid in self._index:
                return self._index[oid]
        t0 = time.monotonic()
        path = self._path(oid)
        tmp = f"{path}.{os.getpid()}.tmp"
        header = _HEADER.pack(_MAGIC, zlib.crc32(data) & 0xFFFFFFFF,
                              len(data))
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self._index[oid] = len(data)
        m = _SpillMetrics.get()
        m.spilled_bytes.record(len(data))
        m.spill_latency_ms.record((time.monotonic() - t0) * 1000.0)
        return len(data)

    def read(self, oid: bytes) -> Optional[bytes]:
        """Read + verify one object; a checksum mismatch deletes the entry
        and reports a miss (torn copies must never be served)."""
        t0 = time.monotonic()
        try:
            with open(self._path(oid), "rb") as f:
                hdr = f.read(_HEADER.size)
                magic, crc, size = _HEADER.unpack(hdr)
                if magic != _MAGIC:
                    raise ValueError("bad magic")
                data = f.read(size)
        except (OSError, ValueError, struct.error):
            return None
        if len(data) != size or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            self.delete(oid)
            return None
        m = _SpillMetrics.get()
        m.restored_bytes.record(len(data))
        m.restore_latency_ms.record((time.monotonic() - t0) * 1000.0)
        return data

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._index

    def delete(self, oid: bytes) -> None:
        with self._lock:
            self._index.pop(oid, None)
        try:
            os.unlink(self._path(oid))
        except OSError:
            pass

    def ids(self) -> List[bytes]:
        with self._lock:
            return list(self._index)

    @property
    def spilled_bytes(self) -> int:
        with self._lock:
            return sum(self._index.values())

    @property
    def num_objects(self) -> int:
        with self._lock:
            return len(self._index)

    def size_of(self, oid: bytes) -> Optional[int]:
        with self._lock:
            return self._index.get(oid)

    def close(self, remove: bool = True) -> None:
        """Normal shutdown removes the directory; crash paths skip this so
        the restart scan can recover the entries."""
        if not remove:
            return
        with self._lock:
            ids, self._index = list(self._index), {}
        for oid in ids:
            try:
                os.unlink(self._path(oid))
            except OSError:
                pass
        try:
            os.rmdir(self.dir)
        except OSError:
            pass  # non-empty (foreign files) or already gone


class _DiskBufferReleaser:
    """Release target for buffers served straight from the spill disk:
    drops only the wrapper's pin, never the arena's — forwarding to the
    arena could steal a pin the arena took for a DIFFERENT reader if the
    object was restored between the disk read and this release."""

    __slots__ = ("wrapper",)

    def __init__(self, wrapper: "SpillingStore"):
        self.wrapper = wrapper

    def _release(self, object_id: bytes) -> None:
        self.wrapper._drop_pin(object_id)


class SpillingStore:
    """Arena + spill policy with the ShmObjectStore interface (put/create/
    seal/get/..., plus owner tags and watermark maintenance)."""

    def __init__(self, base, spill: SpillManager,
                 high_watermark: float = 0.85, low_watermark: float = 0.60,
                 owner_quota: int = 0):
        self.base = base
        self.spill = spill
        self.name = getattr(base, "name", "")
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.owner_quota = owner_quota
        self._lock = threading.RLock()
        # Policy state for objects that went THROUGH this wrapper. Foreign
        # arena objects (same-host workers write zero-copy) are visible via
        # base.list_ids() and get spilled as coldest-unknown candidates.
        self._meta: Dict[bytes, Dict] = {}  # oid -> {owner,size,used,sealed}
        self._pins: Dict[bytes, int] = {}
        self._owner_bytes: Dict[str, int] = {}
        self._staging: Dict[bytes, bytearray] = {}
        self._clock = 0
        self._num_spills = 0
        self._num_restores = 0
        self._quota_evictions = 0
        self._disk_releaser = _DiskBufferReleaser(self)
        self.on_spill: Optional[Callable[[bytes, int], None]] = None
        self.on_restore: Optional[Callable[[bytes, int], None]] = None

    def set_spill_callbacks(self, on_spill=None, on_restore=None) -> None:
        self.on_spill = on_spill
        self.on_restore = on_restore

    # ------------------------------------------------------------- accounting
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _track(self, oid: bytes, size: int, owner: Optional[str],
               sealed: bool) -> None:
        with self._lock:
            self._meta[oid] = {"owner": owner, "size": size,
                               "used": self._tick(), "sealed": sealed}
            if owner:
                self._owner_bytes[owner] = (
                    self._owner_bytes.get(owner, 0) + size)

    def _untrack(self, oid: bytes) -> None:
        with self._lock:
            meta = self._meta.pop(oid, None)
            if meta and meta.get("owner"):
                owner = meta["owner"]
                left = self._owner_bytes.get(owner, 0) - meta["size"]
                if left > 0:
                    self._owner_bytes[owner] = left
                else:
                    self._owner_bytes.pop(owner, None)

    def _touch(self, oid: bytes) -> None:
        with self._lock:
            meta = self._meta.get(oid)
            if meta is not None:
                meta["used"] = self._tick()

    # ------------------------------------------------------------ spill policy
    def _capacity(self) -> int:
        st = self.base.stats()
        return st.get("capacity") or st.get("arena_bytes") or 0

    def _used(self) -> int:
        return self.base.stats().get("used_bytes", 0)

    def _victims(self, exclude=()) -> List[bytes]:
        """Spill candidates, coldest first: foreign arena objects (unknown
        recency — treated coldest), then wrapper-tracked sealed unpinned
        objects by LRU. Wrapper-pinned objects are NEVER candidates."""
        with self._lock:
            known = []
            for oid, meta in self._meta.items():
                if oid in exclude or not meta["sealed"]:
                    continue
                if self._pins.get(oid, 0) > 0:
                    continue
                known.append((meta["used"], oid))
            known.sort()
            tracked = set(self._meta)
        foreign = [oid for oid in self.base.list_ids()
                   if oid not in tracked and oid not in exclude]
        return foreign + [oid for _, oid in known]

    def _spill_one(self, oid: bytes, quota: bool = False) -> int:
        """Copy one arena object to disk and drop its arena bytes. Returns
        bytes reclaimed (0 = skipped: unsealed, vanished, or natively
        pinned so the delete deferred)."""
        blob = self.base.get_bytes(oid)
        if blob is None:
            return 0
        self.spill.write(oid, blob)
        self.base.delete(oid)
        if self.base.contains(oid):
            # A reader in another process holds a native pin: the delete
            # deferred, so no bytes came back yet. The disk copy is still
            # correct (objects are immutable) and will serve gets once the
            # arena copy goes.
            reclaimed = 0
        else:
            reclaimed = len(blob)
        self._untrack(oid)
        with self._lock:
            self._num_spills += 1
            if quota:
                self._quota_evictions += 1
        if quota:
            _SpillMetrics.get().quota_evictions.record(1)
        if self.on_spill is not None:
            try:
                self.on_spill(oid, len(blob))
            except Exception:  # noqa: BLE001 - telemetry must not fail puts
                pass
        return reclaimed

    def _make_room(self, need: int, exclude=()) -> None:
        """Spill cold objects until ``need`` more bytes fit under the high
        watermark (aiming for the low watermark so puts don't re-trigger
        immediately). Best-effort: stops when out of candidates."""
        cap = self._capacity()
        if cap <= 0:
            return
        # Aim low, but never demand more room than the arena has.
        target = min(int(cap * self.low_watermark),
                     max(0, cap - need - (need // 16) - 4096))
        if self._used() + need <= cap * self.high_watermark:
            return
        for oid in self._victims(exclude=exclude):
            self._spill_one(oid)
            if self._used() <= target:
                break

    def maybe_spill(self) -> int:
        """Watermark maintenance: spill down to the low watermark when the
        arena is above the high one. Called periodically by the controller
        so direct (wrapper-bypassing) writers keep finding headroom instead
        of triggering the native evictor. Returns objects spilled."""
        cap = self._capacity()
        if cap <= 0 or self._used() <= cap * self.high_watermark:
            return 0
        before = self._num_spills
        target = int(cap * self.low_watermark)
        for oid in self._victims():
            self._spill_one(oid)
            if self._used() <= target:
                break
        return self._num_spills - before

    def _enforce_quota(self, owner: Optional[str], exclude=()) -> None:
        if not owner or not self.owner_quota:
            return
        while self._owner_bytes.get(owner, 0) > self.owner_quota:
            with self._lock:
                candidates = sorted(
                    (meta["used"], oid)
                    for oid, meta in self._meta.items()
                    if meta.get("owner") == owner and meta["sealed"]
                    and oid not in exclude
                    and self._pins.get(oid, 0) == 0)
            for _, oid in candidates:
                if self._spill_one(oid, quota=True):
                    break
            else:
                return  # everything left is pinned/unsealed: give up

    # ---------------------------------------------------------------- write
    def put(self, object_id: bytes, data, owner: Optional[str] = None) -> bool:
        oid = _pad_id(object_id)
        if self.spill.contains(oid) or self.base.contains(oid):
            return False  # immutable double-put is a no-op
        if not isinstance(data, (bytes, bytearray)):
            data = bytes(memoryview(data).cast("B"))
        size = len(data)
        # Proactively make room so base.put never reaches the native
        # evictor (which DROPS bytes instead of spilling them).
        self._make_room(size, exclude=(oid,))
        try:
            created = self.base.put(oid, data)
        except StoreFullError:
            # Cannot fit even after spilling (oversized, or all pinned):
            # the object itself goes to disk — degradation, not failure.
            self.spill.write(oid, data)
            with self._lock:
                self._num_spills += 1
            if self.on_spill is not None:
                try:
                    self.on_spill(oid, size)
                except Exception:  # noqa: BLE001
                    pass
            return True
        if created:
            self._track(oid, size, owner, sealed=True)
            self._enforce_quota(owner, exclude=(oid,))
        return created

    def create(self, object_id: bytes, size: int,
               owner: Optional[str] = None) -> Optional[memoryview]:
        oid = _pad_id(object_id)
        if self.spill.contains(oid):
            return None
        self._make_room(size, exclude=(oid,))
        try:
            view = self.base.create(oid, size)
        except StoreFullError:
            # Stage off-arena; seal() spills it.
            buf = bytearray(size)
            with self._lock:
                self._staging[oid] = buf
            return memoryview(buf)
        if view is not None:
            self._track(oid, size, owner, sealed=False)
        return view

    def seal(self, object_id: bytes) -> None:
        oid = _pad_id(object_id)
        with self._lock:
            staged = self._staging.pop(oid, None)
        if staged is not None:
            self.spill.write(oid, bytes(staged))
            with self._lock:
                self._num_spills += 1
            if self.on_spill is not None:
                try:
                    self.on_spill(oid, len(staged))
                except Exception:  # noqa: BLE001
                    pass
            return
        try:
            self.base.seal(oid)
        except StoreFullError:
            # PyObjectStore defers its arena charge to seal time; make room
            # and retry once, then fall back to its staged bytes.
            self._make_room(0, exclude=(oid,))
            try:
                self.base.seal(oid)
            except StoreFullError:
                staged = getattr(self.base, "_staging", None)
                if staged and staged[0] == oid:
                    self.spill.write(oid, bytes(staged[1]))
                    self.base.abort(oid)
                return
        with self._lock:
            meta = self._meta.get(oid)
            owner = meta.get("owner") if meta else None
            if meta is not None:
                meta["sealed"] = True
        self._enforce_quota(owner, exclude=(oid,))

    def abort(self, object_id: bytes) -> None:
        oid = _pad_id(object_id)
        with self._lock:
            if self._staging.pop(oid, None) is not None:
                return
        self._untrack(oid)
        self.base.abort(oid)

    # ----------------------------------------------------------------- read
    def get(self, object_id: bytes) -> Optional[PinnedBuffer]:
        oid = _pad_id(object_id)
        buf = self.base.get(oid)
        if buf is not None:
            self._touch(oid)
            with self._lock:
                self._pins[oid] = self._pins.get(oid, 0) + 1
            # Reroute release through this wrapper so pin accounting (the
            # never-spill-pinned invariant) sees it.
            buf.store = self
            return buf
        data = self._restore(oid)
        if data is None:
            return None
        buf = self.base.get(oid)
        if buf is not None:  # restored into the arena
            self._touch(oid)
            with self._lock:
                self._pins[oid] = self._pins.get(oid, 0) + 1
            buf.store = self
            return buf
        # Arena had no room (all pinned): serve the disk bytes directly.
        with self._lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1
        return PinnedBuffer(self._disk_releaser, oid, memoryview(data))

    def _restore(self, oid: bytes) -> Optional[bytes]:
        """Disk-second half of get(): read + verify, then migrate back into
        the arena when it fits (making room by spilling colder objects)."""
        data = self.spill.read(oid)
        if data is None:
            return None
        self._make_room(len(data), exclude=(oid,))
        try:
            if self.base.put(oid, data):
                self._track(oid, len(data), None, sealed=True)
                self.spill.delete(oid)
                with self._lock:
                    self._num_restores += 1
                if self.on_restore is not None:
                    try:
                        self.on_restore(oid, len(data))
                    except Exception:  # noqa: BLE001
                        pass
        except StoreFullError:
            pass  # serve from the disk copy; it stays authoritative
        return data

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        buf = self.get(object_id)
        if buf is None:
            return None
        try:
            return buf.tobytes()
        finally:
            buf.release()

    def contains(self, object_id: bytes) -> bool:
        oid = _pad_id(object_id)
        return self.base.contains(oid) or self.spill.contains(oid)

    def in_arena(self, object_id: bytes) -> bool:
        return self.base.contains(_pad_id(object_id))

    def is_spilled(self, object_id: bytes) -> bool:
        return self.spill.contains(_pad_id(object_id))

    def _drop_pin(self, object_id: bytes) -> None:
        oid = _pad_id(object_id)
        with self._lock:
            n = self._pins.get(oid, 0)
            if n > 1:
                self._pins[oid] = n - 1
            else:
                self._pins.pop(oid, None)

    def _release(self, object_id: bytes) -> None:
        """Release of an arena-backed buffer handed out by get()."""
        oid = _pad_id(object_id)
        self._drop_pin(oid)
        self.base._release(oid)

    # --------------------------------------------------------------- manage
    def delete(self, object_id: bytes) -> None:
        oid = _pad_id(object_id)
        self._untrack(oid)
        self.base.delete(oid)
        self.spill.delete(oid)

    def list_ids(self, max_ids: int = 1 << 16) -> List[bytes]:
        ids = self.base.list_ids(max_ids)
        seen = set(ids)
        for oid in self.spill.ids():
            if oid not in seen and len(ids) < max_ids:
                ids.append(oid)
        return ids

    def stats(self) -> Dict[str, int]:
        st = self.base.stats()
        with self._lock:
            st.update({
                "spilled_bytes": self.spill.spilled_bytes,
                "spilled_objects": self.spill.num_objects,
                "num_spills": self._num_spills,
                "num_restores": self._num_restores,
                "quota_evictions": self._quota_evictions,
            })
        return st

    def close(self) -> None:
        self.base.close()
        self.spill.close(remove=True)


def resolve_spill_dir(config, store_name: str) -> Optional[str]:
    """The per-store spill directory for this config, or None when spill is
    disabled. Layout: <object_spill_dir or $TMPDIR/ray_tpu_spill>/<store>."""
    import tempfile

    if not getattr(config, "object_spill_enabled", False):
        return None
    base = getattr(config, "object_spill_dir", "") or os.path.join(
        tempfile.gettempdir(), "ray_tpu_spill")
    return os.path.join(base, store_name)


def put_backpressure(stats_fn: Callable[[], Dict[str, int]], nbytes: int,
                     high_watermark: float = 0.85,
                     max_wait_s: float = 2.0) -> float:
    """Owner-side bounded wait: while the arena is over its high watermark,
    back off (2 ms doubling to 250 ms) up to ``max_wait_s`` total, giving
    the node's spiller time to make room. Returns seconds waited. Never
    blocks forever — after the bound the put proceeds and the store-side
    spill path absorbs it."""
    waited = 0.0
    delay = 0.002
    while True:
        try:
            st = stats_fn()
        except Exception:  # noqa: BLE001 - stats must never fail a put
            break
        cap = st.get("capacity") or st.get("arena_bytes") or 0
        if cap <= 0 or st.get("used_bytes", 0) + nbytes <= cap * high_watermark:
            break
        if waited >= max_wait_s:
            break
        step = min(delay, max_wait_s - waited)
        time.sleep(step)
        waited += step
        delay = min(delay * 2, 0.25)
    if waited > 0:
        _SpillMetrics.get().backpressure_wait_ms.record(waited * 1000.0)
    return waited
