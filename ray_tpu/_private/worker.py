"""Process-global worker state (reference: python/ray/worker.py Worker :83)."""

from __future__ import annotations

import threading
from typing import Optional


class Worker:
    def __init__(self):
        self.core = None          # LocalRuntime or cluster CoreWorker
        self.mode: Optional[str] = None  # "local" | "driver" | "worker"
        self.connected = False

    def check_connected(self):
        if not self.connected:
            raise RuntimeError(
                "ray_tpu.init() must be called before using the API"
            )


_worker = Worker()
_lock = threading.Lock()


def global_worker() -> Worker:
    return _worker
