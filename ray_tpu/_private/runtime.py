"""Local (single-process) runtime: core worker + node scheduler in one.

This is the stage-2 runtime from the build plan: the semantics of the
reference's core_worker (task submission, dependency resolution, object
put/get/wait — reference: ``src/ray/core_worker/core_worker.h:262``) fused with
a single node's scheduler (resource admission + dispatch — reference:
``src/ray/raylet/node_manager.cc:993`` DispatchTasks) into one in-process
engine. The cluster backend (ray_tpu/cluster) reuses the same submission/actor
machinery but routes placement through the batch placement kernel and objects
through the shared-memory arena.

Execution model:
  - normal tasks run on a growable thread pool; admission is controlled by the
    node's ResourceSet accounting, not pool size (jax/XLA work releases the GIL
    so threads give real parallelism for the TPU path);
  - a task that blocks in ``get()`` releases its resources and re-acquires
    (oversubscribing if needed) on unblock — the reference's
    HandleDirectCallTaskBlocked/Unblocked protocol (node_manager.h:385-392),
    without which nested task graphs deadlock;
  - actors are dispatch threads with ordered inbound queues (the reference's
    direct actor transport, direct_actor_transport.h:298), optional
    max_concurrency via an inner pool, optional asyncio event loop.
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import time
import traceback
from collections import deque
import logging
from concurrent.futures import Future, ThreadPoolExecutor

logger = logging.getLogger(__name__)
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ReplicaUnavailableError,
    TaskCancelledError,
    TaskError,
)
from ..object_ref import ObjectRef
from .config import Config
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID
from .memory_store import MemoryStore, StoredObject
from .resources import KILO, NodeResources, ResourceSet
from .serialization import get_context as get_serialization_context
from .task_spec import TaskSpec, TaskType

_LOCAL = threading.local()
_ADMITTED = object()  # PendingTask.future sentinel: handed to the pool


class WorkerContext:
    """Per-thread execution context (reference: core_worker/context.h)."""

    def __init__(self, job_id: JobID, task_id: TaskID):
        self.job_id = job_id
        self.current_task_id = task_id
        self.task_counter = itertools.count(1)
        self.put_counter = itertools.count(1)
        self.acquired: Optional[ResourceSet] = None  # held by the running task


def current_context() -> Optional[WorkerContext]:
    return getattr(_LOCAL, "ctx", None)


def ensure_context(runtime) -> WorkerContext:
    """Context for this thread, creating a driver-scoped one if absent.

    User-spawned threads (e.g. a ThreadPoolExecutor in driver code) have no
    inherited context; they submit as children of the driver task.

    A context auto-created here is tagged with its runtime and replaced
    when that runtime changes: after shutdown()+init() in one process the
    thread-local would otherwise keep deriving task/object IDs from the
    DEAD job (their embedded job bytes then name a completion ring that
    no longer exists, and cross-driver result serving breaks the same
    way). Contexts set by task execution are never replaced — they carry
    the SUBMITTING driver's job on purpose.
    """
    ctx = getattr(_LOCAL, "ctx", None)
    if ctx is None or getattr(ctx, "scoped_runtime", None) \
            not in (None, runtime):
        # Scope the thread under a unique pseudo-task so two threads never
        # derive colliding task/object IDs (counters alone are per-context).
        scope = TaskID.for_normal_task(
            runtime.job_id, runtime.driver_task_id, next(runtime._thread_scope_counter)
        )
        ctx = WorkerContext(runtime.job_id, scope)
        ctx.scoped_runtime = runtime
        _LOCAL.ctx = ctx
    return ctx


class _EventLog:
    """Cheap append-only profile log; feeds timeline() chrome-trace export."""

    def __init__(self, enabled: bool):
        self.enabled = enabled
        self.events: deque = deque(maxlen=1_000_000)

    def record(self, kind: str, name: str, start: float, end: float, **extra):
        if self.enabled:
            self.events.append((kind, name, start, end, extra))


class PendingTask:
    __slots__ = ("spec", "fn", "remaining_deps", "retries_left", "cancelled", "future")

    def __init__(self, spec: TaskSpec, fn: Callable, retries_left: int):
        self.spec = spec
        self.fn = fn
        self.remaining_deps = 0
        self.retries_left = retries_left
        self.cancelled = False
        self.future: Optional[Future] = None


class LocalActor:
    """One live actor: instance + ordered dispatch thread.

    Reference semantics: per-caller sequence ordering and bounded concurrency
    (``direct_actor_transport.h:264,298``); asyncio actors run methods on an
    event loop instead of blocking the dispatch thread (core_worker/fiber.h).
    """

    def __init__(self, actor_id: ActorID, name: Optional[str], runtime: "LocalRuntime",
                 max_concurrency: int, is_asyncio: bool,
                 lifetime_resources: ResourceSet):
        self.actor_id = actor_id
        self.name = name
        self.runtime = runtime
        self.instance: Any = None
        self.dead = False
        self.resources_released = False
        self.class_info: Optional[Tuple[str, str, tuple]] = None  # name, module, methods
        self.creation_error: Optional[BaseException] = None
        self.created = threading.Event()
        self.lifetime_resources = lifetime_resources
        self.max_concurrency = max_concurrency
        self.is_asyncio = is_asyncio
        self.queue: "deque[Tuple[int, TaskSpec]]" = deque()
        self.next_seq = 0
        self.restarts_left = 0  # set from creation spec in start()
        self.checkpoints: deque = deque(maxlen=20)  # Checkpointable blobs
        self._exit_requested = False
        self.pending_out_of_order: Dict[int, TaskSpec] = {}
        self.cv = threading.Condition()
        self.num_executing = 0
        self.inner_pool: Optional[ThreadPoolExecutor] = None
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.thread = threading.Thread(
            target=self._run, name=f"actor-{actor_id.hex()[:8]}", daemon=True
        )

    def _died_error(self) -> "ActorDiedError":
        """Death error that names the CAUSE when the constructor failed —
        a bare "died unexpectedly" sent callers hunting through logs."""
        if self.creation_error is not None:
            return ActorDiedError(
                self.actor_id,
                f"actor constructor failed: "
                f"{type(self.creation_error).__name__}: "
                f"{self.creation_error}")
        return ActorDiedError(self.actor_id)

    def start(self, creation_spec: TaskSpec, cls: type, args, kwargs):
        self._creation = (creation_spec, cls, args, kwargs)
        self.restarts_left = creation_spec.max_restarts
        self.thread.start()

    def submit(self, seq_no: int, spec: TaskSpec):
        with self.cv:
            if self.dead:
                self._fail_spec(spec, self._died_error())
                return
            if seq_no == self.next_seq:
                self.queue.append((seq_no, spec))
                self.next_seq += 1
                # drain any buffered out-of-order successors
                while self.next_seq in self.pending_out_of_order:
                    self.queue.append(
                        (self.next_seq, self.pending_out_of_order.pop(self.next_seq))
                    )
                    self.next_seq += 1
            else:
                self.pending_out_of_order[seq_no] = spec
            self.cv.notify_all()
        self._wake_loop()

    def kill(self, no_restart: bool = True) -> bool:
        """Kill the actor; returns True if it is restarting instead of dying.

        Restart semantics follow the reference (max_restarts,
        core_worker.cc:1156 + gcs_actor_manager): queued calls fail during the
        restart, later calls hit the fresh instance; -1 = infinite restarts.
        """
        with self.cv:
            already_dead = self.dead
            self.dead = True
            pending = [spec for _, spec in self.queue]
            pending.extend(self.pending_out_of_order.values())
            self.queue.clear()
            self.pending_out_of_order.clear()
            self.cv.notify_all()
        for spec in pending:
            self._fail_spec(spec, self._died_error())
        self._wake_loop()
        if (no_restart or already_dead or self.creation_error is not None
                or self.restarts_left == 0):
            return False
        if self.restarts_left > 0:
            self.restarts_left -= 1
        self._restart()
        return True

    def _restart(self) -> None:
        old_thread = self.thread
        old_loop = self.loop
        same_thread = old_thread is threading.current_thread()
        if old_thread.is_alive() and not same_thread:
            old_thread.join(timeout=5.0)
        if (old_loop is not None and not old_loop.is_closed()
                and not old_loop.is_running()):
            old_loop.close()
        with self.cv:
            self.instance = None
            self.loop = None
            self.inner_pool = None
            self.created.clear()
            self._exit_requested = False
            self.dead = False
        self.thread = threading.Thread(
            target=self._run, name=f"actor-{self.actor_id.hex()[:8]}",
            daemon=True)
        with self.cv:
            self.cv.notify_all()  # wake a same-thread-restart's old loop
        self.thread.start()

    def _fail_spec(self, spec: TaskSpec, error: BaseException):
        self.runtime._stamp_terminal(spec, "FAILED")
        for oid in spec.return_ids():
            self.runtime.store.put(oid, StoredObject(error=error))
        self.runtime._unpin_args(spec.dependencies())

    # -- dispatch loop --------------------------------------------------------
    def _run(self):
        creation_spec, cls, args, kwargs = self._creation
        _LOCAL.ctx = WorkerContext(creation_spec.job_id, creation_spec.task_id)
        t0 = time.monotonic()
        w0 = time.time()
        self.runtime._stamp_dispatch(creation_spec)
        try:
            resolved_args, resolved_kwargs = self.runtime._resolve_args(args, kwargs)
            self.instance = cls(*resolved_args, **resolved_kwargs)
            if self.checkpoints and hasattr(self.instance, "load_checkpoint"):
                # Restart of a Checkpointable actor: resume from the newest
                # checkpoint (reference actor.py:972 + node_manager.h:525).
                self.instance.load_checkpoint(self.checkpoints[-1])
            self.runtime.store.put(
                creation_spec.return_ids()[0], StoredObject(value=self.actor_id)
            )
        except BaseException as e:  # noqa: BLE001 - creation failure is data
            self.creation_error = e
            self.runtime._stamp_terminal(
                creation_spec, "FAILED", (w0, time.time()),
                time.monotonic() - t0)
            err = TaskError(f"{cls.__name__}.__init__", e)
            self.runtime.store.put(creation_spec.return_ids()[0], StoredObject(error=err))
            with self.cv:
                self.dead = True
                # Calls submitted between thread start and this failure sit
                # in the queue; abandoning them would hang their callers
                # forever (observed: serve master blocked on ready()).
                pending = [spec for _, spec in self.queue]
                pending.extend(self.pending_out_of_order.values())
                self.queue.clear()
                self.pending_out_of_order.clear()
            for spec in pending:
                self._fail_spec(spec, self._died_error())
            self.created.set()
            # Release lifetime resources reserved in create_actor, else a
            # failed constructor permanently leaks them.
            self.runtime._release_actor_resources(self)
            return
        finally:
            self.runtime.events.record(
                "actor_creation", cls.__name__, t0, time.monotonic(),
                actor_id=self.actor_id.hex(),
            )
        self.runtime._stamp_terminal(
            creation_spec, "FINISHED", (w0, time.time()),
            time.monotonic() - t0)
        self.created.set()

        if self.is_asyncio:
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            self.loop.create_task(self._async_dispatch())
            self.loop.run_forever()
            return
        if self.max_concurrency > 1:
            self.inner_pool = ThreadPoolExecutor(
                max_workers=self.max_concurrency,
                thread_name_prefix=f"actor-{self.actor_id.hex()[:8]}-c",
            )
        me = threading.current_thread()
        while True:
            with self.cv:
                # `self.thread is not me` => a restart replaced this loop
                # (possible when the restart was triggered from this very
                # thread, e.g. a method calling kill on its own actor):
                # retire so two dispatchers never run concurrently.
                while not self.queue and not self.dead and self.thread is me:
                    self.cv.wait()
                if self.thread is not me:
                    break
                if self.dead and not self.queue:
                    break
                _, spec = self.queue.popleft()
            if self.inner_pool is not None:
                self.inner_pool.submit(self._execute_method, spec)
            else:
                self._execute_method(spec)
        if self.inner_pool is not None:
            self.inner_pool.shutdown(wait=False)

    async def _async_dispatch(self):
        # Woken by submit()/kill() via call_soon_threadsafe on this event —
        # no idle polling.
        self._wake = asyncio.Event()
        me = threading.current_thread()
        while True:
            spec = None
            with self.cv:
                if self.thread is not me:
                    break  # a restart replaced this loop; retire
                if self.queue:
                    _, spec = self.queue.popleft()
                elif self.dead:
                    break
            if spec is None:
                self._wake.clear()
                await self._wake.wait()
                continue
            asyncio.get_event_loop().create_task(self._execute_method_async(spec))
        # Cancel stragglers (e.g. long-lived background loops the actor
        # spawned) so loop teardown doesn't warn about pending tasks; yield
        # once so the cancellations actually propagate before stop().
        stragglers = [t for t in asyncio.all_tasks(self.loop)
                      if t is not asyncio.current_task()]
        for task in stragglers:
            task.cancel()
        if stragglers:
            await asyncio.gather(*stragglers, return_exceptions=True)
        self.loop.stop()

    def _wake_loop(self):
        if self.loop is not None and hasattr(self, "_wake"):
            self.loop.call_soon_threadsafe(self._wake.set)

    def _execute_method(self, spec: TaskSpec):
        from ..exceptions import ActorExitError

        _LOCAL.ctx = WorkerContext(spec.job_id, spec.task_id)

        def call(a, k):
            try:
                return getattr(self.instance, spec.function.qualname)(*a, **k)
            except ActorExitError:
                self._exit_requested = True
                return None

        self.runtime._execute_callable(spec, call)
        self._post_method_hooks()

    def _post_method_hooks(self):
        if self._exit_requested:
            self.runtime.kill_actor(self.actor_id, no_restart=True)
            return
        inst = self.instance
        if (inst is not None and hasattr(inst, "should_checkpoint")
                and hasattr(inst, "save_checkpoint")):
            try:
                if inst.should_checkpoint(None):
                    self.checkpoints.append(inst.save_checkpoint())
            except Exception:  # noqa: BLE001 - checkpointing is best-effort
                pass

    async def _execute_method_async(self, spec: TaskSpec):
        from ..exceptions import ActorExitError

        method = getattr(self.instance, spec.function.qualname)
        t0 = time.monotonic()
        w0 = time.time()
        self.runtime._stamp_dispatch(spec)
        try:
            args, kwargs = self.runtime._resolve_args_from_spec(spec)
            result = method(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
            self.runtime._store_returns(spec, result)
            self._post_method_hooks()
        except ActorExitError:
            self.runtime._store_returns(spec, None)
            self._exit_requested = True
            self._post_method_hooks()
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, (TaskError, ActorDiedError,
                              ReplicaUnavailableError)):
                err = e  # propagate the original failure through chains
            else:
                err = TaskError(spec.function.repr_name, e)
            self.runtime._store_error(spec, err)
        finally:
            self.runtime._stamp_terminal(
                spec, "FINISHED", (w0, time.time()), time.monotonic() - t0)
            self.runtime._unpin_args(spec.dependencies())
            self.runtime.events.record(
                "actor_task", spec.function.repr_name, t0, time.monotonic(),
                actor_id=self.actor_id.hex(),
            )


class _TaskPool:
    """Growable thread pool with exact idle accounting.

    stdlib ThreadPoolExecutor spawns a new thread on nearly every submit
    (its idle check races with completions), which at 10k+ task rates melts
    into thread-creation overhead. This pool spawns only when no worker is
    actually idle — the same grow-on-demand policy as the reference's
    WorkerPool (worker_pool.h:45) — and retires workers after an idle
    timeout. max_threads stays high only as a deadlock backstop for tasks
    that block on ray.get of sub-task results.
    """

    def __init__(self, max_threads: int = 4096, idle_timeout_s: float = 30.0,
                 name: str = "task"):
        self._max = max_threads
        self._idle_timeout = idle_timeout_s
        self._name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._idle = 0
        self._threads = 0
        self._spawned_total = 0
        self._shutdown = False

    def submit(self, fn: Callable, *args) -> None:
        with self._cv:
            if self._shutdown:
                return
            self._q.append((fn, args))
            # Spawn when idle workers can't cover the backlog. `_idle` still
            # counts workers that were notified but haven't woken, so compare
            # against queue depth rather than testing idle > 0 — otherwise
            # two quick submits can both be assigned to one worker and the
            # second item waits behind the first (deadlock if item 1 blocks
            # on item 2's result).
            if self._idle < len(self._q) and self._threads < self._max:
                self._threads += 1
                self._spawned_total += 1
                threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"{self._name}-{self._spawned_total}").start()
            self._cv.notify()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._shutdown:
                    self._idle += 1
                    signaled = self._cv.wait(timeout=self._idle_timeout)
                    self._idle -= 1
                    if not signaled and not self._q:
                        self._threads -= 1  # idle timeout: retire
                        return
                if self._shutdown and not self._q:
                    self._threads -= 1
                    return
                fn, args = self._q.popleft()
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 - never kill the worker
                logger.exception("task pool fn raised")

    def shutdown(self, wait: bool = False, cancel_futures: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            if cancel_futures:
                self._q.clear()
            self._cv.notify_all()


class LocalRuntime:
    """The single-node engine behind ``ray_tpu.init()`` (default mode)."""

    def __init__(self, resources: ResourceSet, config: Config,
                 job_id: Optional[JobID] = None):
        self.config = config
        self.node_id = NodeID.from_random()
        self.job_id = job_id or JobID.from_int(1)
        self.driver_task_id = TaskID.for_driver_task(self.job_id)
        # Same graceful-degradation contract as the cluster arena: a
        # spiller turns budget overruns into disk spill instead of
        # ObjectStoreFullError (reference: plasma external store).
        self._spiller = None
        if getattr(config, "object_spill_enabled", False):
            from .spill import SpillManager, resolve_spill_dir

            spill_dir = resolve_spill_dir(
                config, f"local-{self.node_id.hex()[:12]}")
            try:
                self._spiller = SpillManager(spill_dir)
            except OSError:
                self._spiller = None
        self.store = MemoryStore(max_bytes=config.object_store_memory,
                                 spiller=self._spiller)
        self.node = NodeResources(resources)
        self.events = _EventLog(config.event_log_enabled)
        self.serialization = get_serialization_context()

        self._lock = threading.Lock()
        self._resource_cv = threading.Condition(self._lock)
        # Ready tasks indexed by SchedulingClass (= ResourceSet.key()), the
        # reference's ReadyQueue structure (scheduling_queue.h:123,148): one
        # feasibility check admits/skips a whole class, and dispatch
        # round-robins classes for fairness.
        self._ready: Dict[Tuple, deque] = {}
        self._pending: Dict[TaskID, PendingTask] = {}
        self._actors: Dict[ActorID, LocalActor] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._actor_seq: Dict[ActorID, itertools.count] = {}
        self._pool = _TaskPool(max_threads=4096, name="task")
        # Placement groups (single-node gang admission): pg_id -> record.
        self._placement_groups: Dict[bytes, Dict[str, Any]] = {}
        # Counter namespace for user-thread contexts; starts high so it never
        # collides with the driver thread's own task counters.
        self._thread_scope_counter = itertools.count(1 << 31)
        self._shutdown = False
        self.stats = {"tasks_submitted": 0, "tasks_finished": 0, "tasks_failed": 0}
        # Local-mode task records: the same lifecycle/exec stamps the GCS
        # task table keeps (ts_submit/ts_dispatch/ts_exec_start/
        # ts_exec_end/ts_finish + the pending-reason ledger), so
        # state.tasks() and the job profiler work identically in local
        # runs instead of silently reading zeros. Bounded like GCS
        # lineage: oldest terminal records evicted past the cap.
        self._task_records: Dict[str, Dict[str, Any]] = {}
        self._task_order: deque = deque()
        self._task_records_max = 20_000

        # Reference counting (reference: core_worker/reference_count.h:33).
        # Local python refs = live ObjectRef instances; pins = in-flight task
        # arguments ("submitted task references"). An object is deleted when
        # both hit zero. Owner-only model: everything is in-process, so the
        # borrowed-ref WaitForRefRemoved protocol collapses away.
        self._ref_lock = threading.Lock()
        self._local_refs: Dict[ObjectID, int] = {}
        self._arg_pins: Dict[ObjectID, int] = {}

        _LOCAL.ctx = WorkerContext(self.job_id, self.driver_task_id)
        # Tag so ensure_context replaces it if a DIFFERENT runtime (e.g. a
        # later cluster init in this process) takes over this thread.
        _LOCAL.ctx.scoped_runtime = self

        if getattr(config, "flight_recorder", True):
            from . import flight_recorder

            # Local mode samples as "driver" (the only component here);
            # shutdown() stops the thread so init()/shutdown() cycles
            # never accumulate samplers.
            flight_recorder.start("driver")

    # -------------------------------------------------------------- refcount
    def add_local_ref(self, oid: ObjectID) -> None:
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
            if self._arg_pins.get(oid, 0) > 0:
                return
        if self.config.ref_counting_enabled:
            self.store.delete([oid])

    def _pin_args(self, oids) -> None:
        with self._ref_lock:
            for oid in oids:
                self._arg_pins[oid] = self._arg_pins.get(oid, 0) + 1

    def _unpin_args(self, oids) -> None:
        to_delete = []
        with self._ref_lock:
            for oid in oids:
                n = self._arg_pins.get(oid, 0) - 1
                if n > 0:
                    self._arg_pins[oid] = n
                    continue
                self._arg_pins.pop(oid, None)
                if self._local_refs.get(oid, 0) == 0:
                    to_delete.append(oid)
        if to_delete and self.config.ref_counting_enabled:
            self.store.delete(to_delete)

    def free(self, refs) -> None:
        """Eager delete (reference: ray.internal.free)."""
        self.store.delete([r.id for r in refs])

    def reference_counts(self) -> Dict[str, Dict[str, int]]:
        """Debug view (feeds the reference's `ray memory`-style accounting)."""
        with self._ref_lock:
            out: Dict[str, Dict[str, int]] = {}
            for oid, n in self._local_refs.items():
                out.setdefault(oid.hex(), {})["local_refs"] = n
            for oid, n in self._arg_pins.items():
                out.setdefault(oid.hex(), {})["task_arg_pins"] = n
            return out

    # ---------------------------------------------------------- task records
    def _task_record(self, spec: TaskSpec) -> Dict[str, Any]:
        """Get-or-create the lifecycle record for a spec (cluster task-
        table row shape). Actor methods arrive here lazily from the
        dispatch thread; plain tasks are created at submit."""
        tid = spec.task_id.hex()
        rec = self._task_records.get(tid)
        if rec is not None:
            return rec
        if spec.is_actor_creation:
            kind = "actor_creation"
        elif spec.is_actor_task:
            kind = "actor_method"
        else:
            kind = "task"
        rec = {
            "task_id": tid,
            "name": spec.function.repr_name,
            "kind": kind,
            "state": "PENDING",
            "node_id": self.node_id.hex(),
            "pending_reason": "",
            "ts_submit": time.time(),
            "ts_dispatch": 0.0, "ts_exec_start": 0.0,
            "ts_exec_end": 0.0, "ts_finish": 0.0,
            "exec_s": 0.0,
            "reason_s": {},
            "deps": [oid.binary()[:16].hex()
                     for oid in spec.dependencies()],
        }
        with self._lock:
            self._task_records[tid] = rec
            self._task_order.append(tid)
            while len(self._task_order) > self._task_records_max:
                self._task_records.pop(self._task_order.popleft(), None)
        return rec

    def _stamp_ready(self, spec: TaskSpec) -> None:
        """Deps satisfied → the record's waiting-for-deps stretch closes
        and the capacity wait opens (PR 7 reason taxonomy)."""
        rec = self._task_records.get(spec.task_id.hex())
        if rec is None or rec["state"] != "PENDING":
            return
        now = time.time()
        if rec["pending_reason"] == "waiting-for-deps":
            ledger = rec["reason_s"]
            ledger["waiting-for-deps"] = ledger.get(
                "waiting-for-deps", 0.0) + max(0.0, now - rec["ts_submit"])
        rec["pending_reason"] = "waiting-for-capacity"
        rec["_ready_ts"] = now

    def _stamp_dispatch(self, spec: TaskSpec) -> None:
        rec = self._task_record(spec)
        if rec["state"] != "PENDING":
            return
        now = time.time()
        t0 = rec.pop("_ready_ts", 0.0)
        if rec["pending_reason"] == "waiting-for-capacity" and t0:
            ledger = rec["reason_s"]
            ledger["waiting-for-capacity"] = ledger.get(
                "waiting-for-capacity", 0.0) + max(0.0, now - t0)
        rec["pending_reason"] = ""
        rec["state"] = "DISPATCHED"
        rec["ts_dispatch"] = now

    def _stamp_terminal(self, spec: TaskSpec, state: str,
                        exec_win: Tuple[float, float] = (0.0, 0.0),
                        exec_s: float = 0.0) -> None:
        """Terminal stamp — used by EVERY end-of-life path (finish, task
        error, cancel, deadline expiry, dead-actor fast-fail) so
        durations never silently read 0. First terminal wins the state
        and ts_finish; a later exec window (deadline zombie finishing
        after the watchdog already failed the task) still lands."""
        rec = self._task_record(spec)
        if exec_win[1] > 0.0:
            rec["ts_exec_start"], rec["ts_exec_end"] = exec_win
            rec["exec_s"] = exec_s
        if rec["state"] in ("FINISHED", "FAILED"):
            return
        rec["state"] = state
        rec["pending_reason"] = ""
        rec["ts_finish"] = time.time()

    def task_rows(self) -> List[Dict[str, Any]]:
        """Snapshot every record (state.tasks()' local-mode source)."""
        with self._lock:
            return [dict(rec) for rec in self._task_records.values()]

    # ------------------------------------------------------------------ tasks
    def submit_task(self, fn: Callable, spec: TaskSpec) -> List[ObjectRef]:
        from . import tracing

        trace = tracing.maybe_sample()
        if trace is not None:
            # Local-mode parity with the cluster tracer: sampled tasks get
            # a phase lane in timeline() (single process => the only
            # control-plane phase with real wall time is worker_exec).
            spec.metadata["trace"] = trace.hex()
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        pending = PendingTask(spec, fn, retries_left=spec.max_retries)
        deps = spec.dependencies()
        self._pin_args(deps)
        rec = self._task_record(spec)
        if deps:
            rec["pending_reason"] = "waiting-for-deps"
        with self._lock:
            if self._shutdown:
                raise RuntimeError("runtime is shut down")
            self.stats["tasks_submitted"] += 1
            self._pending[spec.task_id] = pending
            pending.remaining_deps = len(deps)
        if deps:
            for oid in deps:
                self.store.on_available(oid, lambda _oid, p=pending: self._dep_ready(p))
        else:
            self._enqueue_ready(pending)
        return refs

    def _dep_ready(self, pending: PendingTask):
        with self._lock:
            pending.remaining_deps -= 1
            if pending.remaining_deps > 0 or pending.cancelled:
                return
        self._enqueue_ready(pending)

    def _enqueue_ready(self, pending: PendingTask):
        self._stamp_ready(pending.spec)
        with self._lock:
            klass = pending.spec.resources.key()
            dq = self._ready.get(klass)
            if dq is None:
                dq = self._ready[klass] = deque()
            dq.append(pending)
        self._dispatch()

    def _dispatch(self):
        """Admit as many ready tasks as resources allow (ref DispatchTasks)."""
        to_run: List[PendingTask] = []
        with self._lock:
            for klass in list(self._ready.keys()):
                dq = self._ready.get(klass)
                while dq:
                    p = dq[0]
                    if p.cancelled:
                        dq.popleft()
                        continue
                    if not self.node.acquire(p.spec.resources):
                        break  # class infeasible right now; try next class
                    dq.popleft()
                    to_run.append(p)
                if not dq:
                    del self._ready[klass]
            # Mark admission under the lock: cancel() checks future under
            # the same lock, so store-error/unpin can never run twice.
            for p in to_run:
                p.future = _ADMITTED
        for p in to_run:
            self._pool.submit(self._run_task, p)

    def _run_task(self, pending: PendingTask):
        spec = pending.spec
        ctx = WorkerContext(spec.job_id, spec.task_id)
        ctx.acquired = spec.resources
        _LOCAL.ctx = ctx
        try:
            if pending.cancelled:
                self._store_error(spec, TaskCancelledError(spec.task_id))
                self._unpin_args(spec.dependencies())
                return
            self._execute_callable(
                spec, lambda a, k: pending.fn(*a, **k), pending=pending
            )
        finally:
            acquired = ctx.acquired
            ctx.acquired = None
            with self._lock:
                if acquired is not None:
                    self.node.release(acquired)
                self._pending.pop(spec.task_id, None)
                self._resource_cv.notify_all()
            self._dispatch()

    def _execute_callable(self, spec: TaskSpec, call: Callable,
                          pending: Optional[PendingTask] = None):
        t0 = time.monotonic()
        w0 = time.time()
        self._stamp_dispatch(spec)
        final_state = "FINISHED"
        timer = None
        if getattr(spec, "timeout_s", None):
            # Local-mode deadline parity: threads can't be killed, so the
            # watchdog resolves the refs to TaskTimeoutError at expiry and
            # the store's first-write-wins makes the late result a no-op.
            # (The cluster backend actually kills the worker process.)
            from ..exceptions import TaskTimeoutError

            def _expire():
                self._store_error(spec, TaskTimeoutError(
                    task_id=spec.task_id.hex()[:16],
                    timeout_s=spec.timeout_s))
                self.events.record(
                    "task_deadline", spec.function.repr_name,
                    time.monotonic(), time.monotonic(),
                    task_id=spec.task_id.hex())

            timer = threading.Timer(float(spec.timeout_s), _expire)
            timer.daemon = True
            timer.start()
        try:
            args, kwargs = self._resolve_args_from_spec(spec)
            result = call(args, kwargs)
            self._store_returns(spec, result)
            self.stats["tasks_finished"] += 1
            self._unpin_args(spec.dependencies())
        except BaseException as e:  # noqa: BLE001 - task errors are data
            # Retry semantics match the reference (task_manager.cc): only
            # *system* failures (worker crash / node death) consume
            # max_retries; application exceptions are stored immediately.
            # In this in-process runtime tasks cannot crash a worker, so the
            # retry path is exercised by the cluster backend.
            from ..exceptions import WorkerCrashedError

            if (isinstance(e, WorkerCrashedError) and pending is not None
                    and pending.retries_left > 0):
                pending.retries_left -= 1
                rec = self._task_records.get(spec.task_id.hex())
                if rec is not None:  # retried: back to the pending state
                    rec["state"] = "PENDING"
                self._enqueue_ready(pending)
                return
            final_state = "FAILED"
            self.stats["tasks_failed"] += 1
            if isinstance(e, (TaskError, ActorDiedError,
                              ReplicaUnavailableError)):
                err = e  # propagate the original failure through chains
            else:
                err = TaskError(spec.function.repr_name, e)
            self._store_error(spec, err)
            self._unpin_args(spec.dependencies())
        finally:
            if timer is not None:
                timer.cancel()
            now = time.monotonic()
            # Exec window + terminal stamps (ts_finish already set if
            # the deadline watchdog or a cancel got there first).
            self._stamp_terminal(spec, final_state,
                                 (w0, time.time()), now - t0)
            self.events.record(
                "task", spec.function.repr_name, t0, now,
                task_id=spec.task_id.hex(),
            )
            trace = spec.metadata.get("trace")
            if trace:
                self.events.record(
                    "phase", "worker_exec", t0, now,
                    trace=trace, task_id=spec.task_id.hex())

    # -------------------------------------------------------------- arguments
    def _resolve_args_from_spec(self, spec: TaskSpec) -> Tuple[list, dict]:
        args = []
        for kind, val in spec.args:
            if kind == "ref":
                obj = self.store.get([val])[0]
                if obj.error is not None:
                    raise obj.error
                args.append(obj.value)
            else:
                args.append(val)
        kwargs = spec.metadata.get("kwargs", {})
        resolved_kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, ObjectRef):
                obj = self.store.get([v.id])[0]
                if obj.error is not None:
                    raise obj.error
                resolved_kwargs[k] = obj.value
            else:
                resolved_kwargs[k] = v
        return args, resolved_kwargs

    def _resolve_args(self, args, kwargs) -> Tuple[list, dict]:
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                obj = self.store.get([a.id])[0]
                if obj.error is not None:
                    raise obj.error
                out.append(obj.value)
            else:
                out.append(a)
        out_k = {}
        for k, v in (kwargs or {}).items():
            if isinstance(v, ObjectRef):
                obj = self.store.get([v.id])[0]
                if obj.error is not None:
                    raise obj.error
                out_k[k] = obj.value
            else:
                out_k[k] = v
        return out, out_k

    # ---------------------------------------------------------------- returns
    def _store_returns(self, spec: TaskSpec, result: Any):
        oids = spec.return_ids()
        if len(oids) == 1:
            self.store.put(oids[0], StoredObject(value=result, nbytes=_sizeof(result)))
            self._gc_if_unreferenced(spec, oids)
            return
        if not isinstance(result, tuple) or len(result) != len(oids):
            raise ValueError(
                f"task {spec.function.repr_name} declared num_returns="
                f"{len(oids)} but returned {type(result).__name__}"
            )
        for oid, value in zip(oids, result):
            self.store.put(oid, StoredObject(value=value, nbytes=_sizeof(value)))
        self._gc_if_unreferenced(spec, oids)

    def _store_error(self, spec: TaskSpec, error: BaseException):
        # Every error path is a terminal lifecycle transition — cancel,
        # deadline expiry, task exception, dead-actor fail — so the
        # record is stamped here, at the single sink they all share.
        self._stamp_terminal(spec, "FAILED")
        oids = spec.return_ids()
        for oid in oids:
            self.store.put(oid, StoredObject(error=error))
        self._gc_if_unreferenced(spec, oids)

    def _gc_if_unreferenced(self, spec: TaskSpec, oids) -> None:
        """Free return objects whose refs all died before the task finished
        (the reference's owner deletes such returns on completion too)."""
        if not self.config.ref_counting_enabled or spec.is_actor_creation:
            return  # creation markers have no user-visible ObjectRef
        dead = []
        with self._ref_lock:
            for oid in oids:
                if (self._local_refs.get(oid, 0) == 0
                        and self._arg_pins.get(oid, 0) == 0):
                    dead.append(oid)
        if dead:
            self.store.delete(dead)

    # ----------------------------------------------------------------- actors
    def _release_actor_resources(self, actor: "LocalActor"):
        """Release an actor's lifetime resources exactly once."""
        with self._lock:
            if actor.resources_released or actor.lifetime_resources.is_empty():
                actor.resources_released = True
                return
            actor.resources_released = True
            self.node.release(actor.lifetime_resources)
            self._resource_cv.notify_all()
        self._dispatch()

    def create_actor(self, cls: type, spec: TaskSpec, args, kwargs) -> ActorID:
        actor = LocalActor(
            spec.actor_id, spec.name, self,
            max_concurrency=spec.max_concurrency,
            is_asyncio=spec.is_asyncio,
            lifetime_resources=spec.resources,
        )
        actor.class_info = (
            cls.__name__,
            cls.__module__,
            tuple(n for n in dir(cls) if not n.startswith("_")),
        )
        with self._lock:
            if spec.name:
                if spec.name in self._named_actors:
                    raise ValueError(f"actor name {spec.name!r} already taken")
                self._named_actors[spec.name] = spec.actor_id
            self._actors[spec.actor_id] = actor
            self._actor_seq[spec.actor_id] = itertools.count()
        # Reserve lifetime resources (may block-free fail: queue until free).
        if not spec.resources.is_empty():
            with self._resource_cv:
                while not self.node.acquire(spec.resources):
                    self._resource_cv.wait(timeout=1.0)
        actor.start(spec, cls, args, kwargs)
        return spec.actor_id

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        self._pin_args(spec.dependencies())
        with self._lock:
            actor = self._actors.get(spec.actor_id)
            seq = self._actor_seq.get(spec.actor_id)
        if actor is None:
            self._stamp_terminal(spec, "FAILED")
            for oid in spec.return_ids():
                self.store.put(oid, StoredObject(error=ActorDiedError(spec.actor_id)))
            self._unpin_args(spec.dependencies())
            return refs
        self._task_record(spec)  # ts_submit at enqueue, not dispatch
        actor.submit(next(seq), spec)
        return refs

    def get_actor(self, name: str) -> ActorID:
        with self._lock:
            actor_id = self._named_actors.get(name)
        if actor_id is None:
            raise ValueError(f"no actor named {name!r}")
        return actor_id

    def actor_handle_alive(self, actor_id: ActorID) -> bool:
        with self._lock:
            actor = self._actors.get(actor_id)
        return actor is not None and not actor.dead

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is None:
            return
        restarting = actor.kill(no_restart)
        if restarting:
            return  # actor keeps its resources, name, and handle validity
        self._release_actor_resources(actor)  # idempotent on repeated kill()
        with self._lock:
            if actor.name:
                self._named_actors.pop(actor.name, None)

    def actor_class_info(self, actor_id: ActorID):
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is None:
            raise ValueError(f"unknown actor {actor_id}")
        return actor.class_info

    # ---------------------------------------------------------------- objects
    def put(self, value: Any) -> ObjectRef:
        ctx = ensure_context(self)
        oid = ObjectID.for_put(ctx.current_task_id, next(ctx.put_counter))
        self.store.put(oid, StoredObject(value=value, nbytes=_sizeof(value)))
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id for r in refs]
        objs = self._blocking_get(oids, timeout)
        out = []
        for obj in objs:
            if obj.error is not None:
                raise obj.error
            out.append(obj.value)
        return out

    def _blocking_get(self, oids: Sequence[ObjectID], timeout: Optional[float]):
        """Get that releases the calling task's resources while blocked.

        Reference protocol: HandleDirectCallTaskBlocked/Unblocked
        (node_manager.h:385-392). On unblock we oversubscribe rather than wait,
        exactly as the reference re-acquires CPU for unblocked workers.
        """
        if all(self.store.contains(oid) for oid in oids):
            return self.store.get(oids, timeout=0.01)
        ctx = current_context()
        released = None
        if ctx is not None and ctx.acquired is not None and not ctx.acquired.is_empty():
            released = ctx.acquired
            with self._lock:
                self.node.release(released)
                self._resource_cv.notify_all()
            self._dispatch()
        try:
            return self.store.get(oids, timeout=timeout)
        finally:
            if released is not None:
                with self._lock:
                    # Oversubscribe: force re-acquire without waiting.
                    self.node.available = self.node.available.subtract(released)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        oids = [r.id for r in refs]
        by_id = {r.id: r for r in refs}
        ready, rest = self.store.wait(oids, num_returns, timeout)
        return [by_id[o] for o in ready], [by_id[o] for o in rest]

    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def on_ready(_oid):
            obj = self.store.get_if_exists(ref.id)
            if obj.error is not None:
                fut.set_exception(obj.error)
            else:
                fut.set_result(obj.value)

        self.store.on_available(ref.id, on_ready)
        return fut

    def cancel(self, ref: ObjectRef, force: bool = False):
        task_id = ref.id.task_id()
        with self._lock:
            pending = self._pending.get(task_id)
            if pending is None or pending.cancelled:
                return  # unknown, finished, or already cancelled
            pending.cancelled = True
            # Admission (future = _ADMITTED) happens under this lock in
            # _dispatch; once admitted, _run_task owns the error/unpin.
            not_admitted = pending.future is None
        if not_admitted:
            self._store_error(pending.spec, TaskCancelledError(task_id))
            self._unpin_args(pending.spec.dependencies())

    # ------------------------------------------------------------------ state
    def cluster_resources(self) -> Dict[str, float]:
        return self.node.total.to_dict()

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            return self.node.available.to_dict()

    def nodes(self) -> List[Dict[str, Any]]:
        return [{
            "NodeID": self.node_id.hex(),
            "Alive": True,
            "Resources": self.node.total.to_dict(),
        }]

    def actors(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                aid.hex(): {
                    "ActorID": aid.hex(),
                    "State": "DEAD" if a.dead else "ALIVE",
                    "Name": a.name,
                }
                for aid, a in self._actors.items()
            }

    def set_resource(self, name: str, capacity: float) -> None:
        """Create/update/delete a custom resource at runtime (reference:
        python/ray/experimental/dynamic_resources.py via raylet).
        Re-runs dispatch: a queued task demanding the new resource must be
        admitted now, not at the next unrelated completion."""
        fixed = int(round(capacity * 1000))
        with self._resource_cv:
            old_total = self.node.total.custom.get(name, 0)
            delta = fixed - old_total
            new_total = dict(self.node.total.custom)
            new_avail = dict(self.node.available.custom)
            if fixed == 0:
                new_total.pop(name, None)
                new_avail.pop(name, None)
            else:
                new_total[name] = fixed
                new_avail[name] = new_avail.get(name, 0) + delta
            self.node.total = ResourceSet(self.node.total.predefined,
                                          new_total)
            self.node.available = ResourceSet(self.node.available.predefined,
                                              new_avail)
            self._resource_cv.notify_all()
        self._dispatch()

    def next_task_id(self) -> TaskID:
        ctx = ensure_context(self)
        return TaskID.for_normal_task(
            ctx.job_id, ctx.current_task_id, next(ctx.task_counter)
        )

    # -------------------------------------------------------- placement groups
    def _pg_apply_custom(self, grants: Dict[str, float], sign: int) -> None:
        """Add (+1) / remove (-1) group-scoped custom resources on this
        node. Caller holds _resource_cv."""
        new_total = dict(self.node.total.custom)
        new_avail = dict(self.node.available.custom)
        for name, qty in grants.items():
            fixed = sign * int(round(qty * KILO))
            if sign > 0:
                new_total[name] = new_total.get(name, 0) + fixed
                new_avail[name] = new_avail.get(name, 0) + fixed
            else:
                new_total.pop(name, None)
                new_avail.pop(name, None)
        self.node.total = ResourceSet(self.node.total.predefined, new_total)
        self.node.available = ResourceSet(
            self.node.available.predefined, new_avail)

    def create_placement_group(self, pg_id: bytes, bundles, strategy: str,
                               name: str = "") -> None:
        """Single-node gang admission: all bundles must co-reside here, so
        the gang fits iff the bundle SUM fits (all-or-nothing by
        construction) — except STRICT_SPREAD with more than one bundle,
        which can never be satisfied by one node and is INFEASIBLE."""
        total = {}
        for b in bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        total_set = ResourceSet.from_dict(total)
        rec = {
            "pg_id": pg_id, "bundles": [dict(b) for b in bundles],
            "strategy": strategy, "name": name, "state": "PENDING",
            "reason": "", "nodes": [], "created": threading.Event(),
            "base": total_set,
        }
        with self._lock:
            self._placement_groups[pg_id] = rec
        if (strategy == "STRICT_SPREAD" and len(bundles) > 1) or \
                not total_set.is_subset_of(self.node.total):
            rec["reason"] = "infeasible"
            return
        threading.Thread(target=self._pg_admit_local, args=(rec,),
                         daemon=True,
                         name=f"pg-{pg_id.hex()[:8]}").start()

    def _pg_admit_local(self, rec: Dict[str, Any]) -> None:
        from .resources import pg_bundle_grants

        with self._resource_cv:
            while rec["state"] == "PENDING" and \
                    not self.node.acquire(rec["base"]):
                rec["reason"] = "waiting-for-capacity"
                self._resource_cv.wait(timeout=0.5)
            if rec["state"] != "PENDING":
                if rec.get("base_acquired"):
                    self.node.release(rec["base"])
                return
            rec["base_acquired"] = True
            grants: Dict[str, float] = {}
            for g in pg_bundle_grants(rec["bundles"], rec["pg_id"].hex()):
                for k, v in g.items():
                    grants[k] = grants.get(k, 0.0) + v
            rec["grants"] = grants
            self._pg_apply_custom(grants, +1)
            rec["state"] = "CREATED"
            rec["reason"] = ""
            rec["nodes"] = [self.node_id.hex()] * len(rec["bundles"])
            self._resource_cv.notify_all()
        rec["created"].set()
        self._dispatch()

    def remove_placement_group(self, pg_id: bytes) -> None:
        from ..exceptions import PlacementGroupError

        with self._lock:
            rec = self._placement_groups.get(pg_id)
        if rec is None:
            return
        with self._resource_cv:
            was_created = rec["state"] == "CREATED"
            rec["state"] = "REMOVED"
            if was_created:
                self._pg_apply_custom(rec.get("grants", {}), -1)
                self.node.release(rec["base"])
                rec["base_acquired"] = False
            self._resource_cv.notify_all()
        # Fail queued tasks pinned to the removed group: their demands can
        # never be admitted again (the group names are gone from totals).
        marker = "_group_"
        hexid = pg_id.hex()
        victims: List[PendingTask] = []
        with self._lock:
            for klass in list(self._ready.keys()):
                _, custom = klass
                if any(marker in k and k.endswith(hexid)
                       for k, _v in custom):
                    dq = self._ready.pop(klass)
                    victims.extend(dq)
        for p in victims:
            p.cancelled = True
            self._store_error(p.spec, PlacementGroupError(
                f"placement group {hexid[:12]} was removed"))
            self._unpin_args(p.spec.dependencies())
        self._dispatch()

    def placement_group_wait(self, pg_id: bytes,
                             timeout: Optional[float] = None) -> bool:
        with self._lock:
            rec = self._placement_groups.get(pg_id)
        if rec is None:
            return False
        rec["created"].wait(timeout)
        return rec["state"] == "CREATED"

    def placement_group_table(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            recs = list(self._placement_groups.values())
        return {
            rec["pg_id"].hex(): {
                "state": rec["state"], "strategy": rec["strategy"],
                "name": rec["name"], "bundles": rec["bundles"],
                "nodes": list(rec["nodes"]), "reason": rec["reason"],
            }
            for rec in recs
        }

    def shutdown(self):
        with self._lock:
            self._shutdown = True
            actors = list(self._actors.values())
        for actor in actors:
            actor.kill()
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._spiller is not None:
            self._spiller.close(remove=True)
        from . import flight_recorder

        rec = flight_recorder.get()
        if rec is not None and rec.component == "driver":
            flight_recorder.stop()


def _sizeof(value: Any) -> int:
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return value.nbytes
        if hasattr(value, "nbytes"):
            return int(value.nbytes)
        if isinstance(value, (bytes, bytearray)):
            return len(value)
    except Exception:  # pragma: no cover
        pass
    return 64  # nominal accounting for small python objects
