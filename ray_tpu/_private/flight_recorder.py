"""Flight recorder: always-on wall-clock stack sampling (reference: the
role ``py-spy``/``ray stack`` play for Ray, turned continuous — the GCS
profiling tables of arXiv:1712.05889 §4.1 are the template for shipping the
samples centrally; py-spy isn't in this image, so the sampler walks
``sys._current_frames()`` in-process).

One :class:`FlightRecorder` daemon thread per process samples every live
thread at a configurable rate (default 20 Hz, ``RAY_TPU_FLIGHT_RECORDER_HZ``;
kill switch ``RAY_TPU_FLIGHT_RECORDER=0``), folds each stack into the
collapsed ``outer;...;leaf`` form flamegraph tools consume directly, and
accumulates per-stack sample counts. Producers drain the counts on their
existing 2 s stats cadence and piggyback them to the GCS profile-stacks
table (controllers on ``node_stats``, workers/drivers as
``add_profile_stacks`` frames); ``cli profile`` snapshot-diffs that table
into a top-N self-time report.

Overhead model: a 20 Hz walk of a handful of threads is ~100 µs/s of work —
the interleaved A/B smoke in tests/test_control_plane.py pins it under 3%
of warm batched throughput.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from .loopmon import enabled as _loopmon_enabled
from .loopmon import thread_cpu_ns as _thread_cpu_ns

COMPONENTS = ("gcs", "controller", "worker", "driver")

DEFAULT_HZ = 20.0
MAX_DEPTH = 64          # frames kept per stack (outermost truncated)
MAX_STACKS = 8192       # distinct folded stacks per drain window
OVERFLOW_KEY = "<overflow>"

_lock = threading.Lock()
_recorder: Optional["FlightRecorder"] = None


def enabled() -> bool:
    """Process-wide kill switch (``RAY_TPU_FLIGHT_RECORDER=0``)."""
    return os.environ.get("RAY_TPU_FLIGHT_RECORDER", "1") not in ("", "0")


def sample_hz() -> float:
    try:
        hz = float(os.environ.get("RAY_TPU_FLIGHT_RECORDER_HZ", "") or
                   DEFAULT_HZ)
    except ValueError:
        hz = DEFAULT_HZ
    return min(max(hz, 0.1), 250.0)


def fold_frame(frame) -> str:
    """One collapsed-stack element: ``file.py:function`` (basenames only —
    line numbers would explode cardinality without aiding attribution)."""
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class FlightRecorder:
    """Wall-clock stack sampler for THIS process.

    ``start()``/``stop()`` are idempotent; ``drain()`` atomically swaps the
    accumulated {folded_stack: samples} map out (the piggyback flush),
    ``snapshot()`` copies it non-destructively (local introspection).
    """

    def __init__(self, component: str, hz: Optional[float] = None):
        self.component = component
        self.hz = float(hz) if hz else sample_hz()
        self._counts: Dict[str, int] = {}
        # Parallel on-CPU weight per folded stack: each sample adds the
        # fraction of the inter-sample window its thread spent on-CPU
        # (schedstat delta / wall delta), so a thread blocked in recv
        # accumulates wall samples but ~0 on-CPU weight — the PR 12
        # self-time lie, closed at the source.
        self._oncpu: Dict[str, float] = {}
        self._cpu_prev: Dict[int, int] = {}     # python ident -> cpu ns
        self._cpu_prev_t: float = 0.0           # perf_counter of last pass
        self.cpu_tagging = False                # procfs delivered at least once
        # RAY_TPU_LOOPMON=0 also drops the tagging reads, so the
        # observatory kill switch yields a byte-stock sampler hot path.
        self._tag_cpu = _loopmon_enabled()
        self._counts_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Steady state resamples the SAME stacks over and over: cache the
        # string folding per code object and per whole stack (keys keep
        # their code objects alive, bounding both to the program's code).
        # Without these the per-sample formatting cost was measurable
        # against the 3% overhead budget on a saturated 1-vCPU box.
        self._code_cache: Dict[Any, str] = {}
        self._stack_cache: Dict[tuple, str] = {}
        self.samples = 0          # thread-walk passes taken
        self.stacks_folded = 0    # individual thread stacks folded
        self.sample_seconds = 0.0  # wall time inside the sampler itself

    # --------------------------------------------------------------- control
    def start(self) -> bool:
        """Idempotent: one sampler thread per recorder, ever."""
        if self._thread is not None and self._thread.is_alive():
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="flight-recorder", daemon=True)
        self._thread.start()
        return True

    def stop(self, timeout: float = 2.0) -> None:
        """Idempotent; joins the sampler thread so shutdown() leaves no
        stray thread behind (pinned by tests)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -------------------------------------------------------------- sampling
    def _run(self) -> None:
        interval = 1.0 / self.hz
        own = threading.get_ident()
        while not self._stop.wait(interval):
            t0 = time.perf_counter()
            try:
                self._sample_once(own)
            except Exception:  # noqa: BLE001 - sampling must never crash
                pass
            self.sample_seconds += time.perf_counter() - t0

    def _sample_once(self, own_ident: int) -> None:
        frames = sys._current_frames()
        self.samples += 1
        code_cache = self._code_cache
        stack_cache = self._stack_cache
        # Python ident -> native tid map for the on-CPU clock reads
        # (sys._current_frames keys are Python idents; /proc/self/task
        # wants kernel tids). enumerate() is a lock + list copy — cheap
        # against the procfs reads that follow.
        native = {}
        if self._tag_cpu:
            for t in threading.enumerate():
                nid = getattr(t, "native_id", None)
                if t.ident is not None and nid is not None:
                    native[t.ident] = nid
        now = time.perf_counter()
        wall_ns = (now - self._cpu_prev_t) * 1e9 \
            if self._cpu_prev_t else 0.0
        folded = []
        cpu_seen: Dict[int, int] = {}
        for ident, frame in frames.items():
            if ident == own_ident:
                continue
            codes = []
            depth = 0
            while frame is not None and depth < MAX_DEPTH:
                codes.append(frame.f_code)
                frame = frame.f_back
                depth += 1
            codes_t = tuple(codes)
            key = stack_cache.get(codes_t)
            if key is None:
                parts = []
                for code in reversed(codes):
                    s = code_cache.get(code)
                    if s is None:
                        s = code_cache[code] = (
                            f"{os.path.basename(code.co_filename)}"
                            f":{code.co_name}")
                    parts.append(s)
                key = ";".join(parts)
                if len(stack_cache) < 4 * MAX_STACKS:
                    stack_cache[codes_t] = key
            # on-CPU fraction of the inter-sample window for this thread:
            # schedstat cpu-ns delta / wall-ns. 1.0 when procfs is
            # unavailable (wall==on-CPU, the old degraded semantics).
            frac = 1.0
            tid = native.get(ident)
            if tid is not None:
                ns = _thread_cpu_ns(tid)
                if ns is not None:
                    self.cpu_tagging = True
                    cpu_seen[ident] = ns
                    prev = self._cpu_prev.get(ident)
                    if prev is None or wall_ns <= 0:
                        frac = 0.0  # first sight: no window to judge
                    else:
                        frac = min(max((ns - prev) / wall_ns, 0.0), 1.0)
            folded.append((key, frac))
        del frames
        self._cpu_prev = cpu_seen
        self._cpu_prev_t = now
        with self._counts_lock:
            for key, frac in folded:
                if key not in self._counts and \
                        len(self._counts) >= MAX_STACKS:
                    key = OVERFLOW_KEY
                self._counts[key] = self._counts.get(key, 0) + 1
                if frac:
                    self._oncpu[key] = self._oncpu.get(key, 0.0) + frac
                self.stacks_folded += 1

    # ----------------------------------------------------------------- sinks
    def drain(self) -> Dict[str, int]:
        """Swap out the accumulated folded-stack counts (the flush path:
        whoever drains first owns the window's samples)."""
        with self._counts_lock:
            counts, self._counts = self._counts, {}
            self._oncpu = {}
        return counts

    def drain_tagged(self) -> tuple:
        """(wall_counts, oncpu_weights) — the tagged flush the producers
        ship so `cli profile` can print wall and on-CPU columns instead
        of one conflated self-time figure."""
        with self._counts_lock:
            counts, self._counts = self._counts, {}
            oncpu, self._oncpu = self._oncpu, {}
        return counts, {k: round(v, 2) for k, v in oncpu.items() if v}

    def snapshot(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self._counts)

    def snapshot_oncpu(self) -> Dict[str, float]:
        with self._counts_lock:
            return dict(self._oncpu)


# --------------------------------------------------------------------------
# per-process singleton: every component's flush path talks to ONE sampler
# (the head process hosts the GCS *and* a colocated controller thread — two
# samplers there would double-count every stack).
# --------------------------------------------------------------------------

def start(component: str) -> Optional[FlightRecorder]:
    """Start (or return) this process's recorder. The FIRST caller's
    component labels all of the process's samples; later callers (e.g. the
    head's colocated controller) share the instance. None when disabled."""
    global _recorder
    if not enabled():
        return None
    with _lock:
        if _recorder is None:
            _recorder = FlightRecorder(component)
        _recorder.start()
        rec = _recorder
    _recorder_metrics(rec.component)
    return rec


def get() -> Optional[FlightRecorder]:
    return _recorder


def stop() -> None:
    """Stop and discard this process's recorder (shutdown path)."""
    global _recorder
    with _lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.stop()


def _recorder_metrics(component: str) -> None:
    """Register the flight_recorder_* series (Prometheus-visible through
    metrics.render_prometheus); records one start marker."""
    try:
        from ..metrics import flight_recorder_metrics

        flight_recorder_metrics()["starts"].record(
            1.0, tags={"component": component})
    except Exception:  # noqa: BLE001 - metrics must never block startup
        pass


def flush_metrics(rec: FlightRecorder, n_stacks: int) -> None:
    """Account one drain flush into the flight_recorder_* series."""
    try:
        from ..metrics import flight_recorder_metrics

        m = flight_recorder_metrics()
        m["samples"].record(float(n_stacks),
                            tags={"component": rec.component})
        m["overhead_s"].record(rec.sample_seconds,
                               tags={"component": rec.component})
    except Exception:  # noqa: BLE001
        pass


# --------------------------------------------------------------------------
# consumers: self-time attribution for `cli profile`
# --------------------------------------------------------------------------

def self_time_table(counts: Dict[str, int], top: int = 25) -> list:
    """Top-N frames by SELF samples (leaf of each folded stack), with
    cumulative (anywhere-on-stack) counts — the table that localizes
    microsecond residuals to named frames.

    Returns [(frame, self_n, cum_n, self_pct)], self-descending."""
    total = sum(counts.values())
    if not total:
        return []
    self_n: Dict[str, int] = {}
    cum_n: Dict[str, int] = {}
    for stack, n in counts.items():
        frames = stack.split(";")
        self_n[frames[-1]] = self_n.get(frames[-1], 0) + n
        for f in set(frames):
            cum_n[f] = cum_n.get(f, 0) + n
    ranked = sorted(self_n.items(), key=lambda kv: -kv[1])[:top]
    return [(f, n, cum_n.get(f, n), 100.0 * n / total) for f, n in ranked]


def attribution_table(counts: Dict[str, int],
                      oncpu: Optional[Dict[str, float]] = None,
                      top: int = 25) -> list:
    """Top-N leaf frames with wall AND on-CPU columns (the PR 12 fix:
    blocked-in-recv shows big wall, ~0 on-CPU — never again a single
    "self-time" number that conflates the two).

    Returns [(frame, wall_n, oncpu_n, cum_n, wall_pct)], wall-descending.
    ``oncpu`` is the per-stack on-CPU sample weight from
    ``drain_tagged()``; a missing stack key means ~0 on-CPU (weightless
    entries are dropped at drain). ``oncpu=None`` means no tagging ran —
    every oncpu_n comes back None so renderers show the honest '-'
    rather than a wall==on-CPU lie."""
    total = sum(counts.values())
    if not total:
        return []
    wall_n: Dict[str, int] = {}
    cpu_n: Dict[str, float] = {}
    cum_n: Dict[str, int] = {}
    for stack, n in counts.items():
        frames = stack.split(";")
        leaf = frames[-1]
        wall_n[leaf] = wall_n.get(leaf, 0) + n
        if oncpu is not None:
            cpu_n[leaf] = cpu_n.get(leaf, 0.0) \
                + float(oncpu.get(stack, 0.0))
        for f in set(frames):
            cum_n[f] = cum_n.get(f, 0) + n
    ranked = sorted(wall_n.items(), key=lambda kv: -kv[1])[:top]
    return [(f, n,
             round(cpu_n.get(f, 0.0), 1) if oncpu is not None else None,
             cum_n.get(f, n), 100.0 * n / total) for f, n in ranked]
