"""Per-task distributed tracing (reference: Ray's per-task state tracking +
timeline primitives, arXiv:1712.05889 §4; the critical-path observation that
stragglers are located by per-task span data, not aggregates,
arXiv:1711.01912).

A *trace* is one sampled task followed across every control-plane hop. The
trace context is 8 random bytes carried inside the task spec (binary wire
frames encode it as a versioned spec-header extension — see
``cluster/wire.py`` SPEC_VERSION 2; pickle frames just carry the dict key).
Each hop records wall-clock *spans* for the phases it owns — the same 7
phases the aggregate profiler (PR 2) defines:

    driver_serialize -> submit_rpc -> gcs_place -> dispatch_relay
    -> worker_exec -> result_register -> driver_fetch

Spans flush in batches to the GCS trace table (a ring buffer beside
``profile_events``) where three consumers read them: ``ray_tpu.timeline()``
(chrome-trace lanes, one lane per trace), the straggler report
(``cli trace`` / ``scripts/cluster_lat.py --traces``), and the dashboard.

Sampling (default 1/64, ``RAY_TPU_TRACE_SAMPLE``; 0 disables, 1 traces
everything) keeps the submit hot path at one counter increment per task.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Phase order IS the trace's causal order; reports and monotonicity checks
# key off this tuple.
PHASES = ("driver_serialize", "submit_rpc", "gcs_place", "dispatch_relay",
          "worker_exec", "result_register", "driver_fetch")

_DEFAULT_RATE = 64

_counter = itertools.count()
_lock = threading.Lock()
_metrics_box: Dict[str, Any] = {}


_rate_cache = ("\0unset", _DEFAULT_RATE)

# Runtime override (``cli trace --sample N`` broadcast through the GCS kv,
# applied by each process's stats/heartbeat poll): takes precedence over
# the env var so the rate is adjustable on a LIVE cluster without
# restarting every process. None = no override (env/default applies).
TRACE_SAMPLE_KV_KEY = "__ray_tpu_trace_sample__"
_rate_override: Optional[int] = None


def set_rate_override(rate: Optional[int]) -> None:
    """Install (or clear, with None) the cluster-broadcast sampling rate."""
    global _rate_override
    _rate_override = max(0, int(rate)) if rate is not None else None


def rate_override() -> Optional[int]:
    return _rate_override


def apply_kv_rate(raw: Optional[bytes]) -> None:
    """Fold the GCS kv cell for TRACE_SAMPLE_KV_KEY into the override
    (shared by the controller heartbeat and driver stats polls). A missing
    or unparsable cell clears the override back to env/default."""
    if raw is None:
        set_rate_override(None)
        return
    try:
        set_rate_override(int(bytes(raw).decode()))
    except (ValueError, UnicodeDecodeError):
        set_rate_override(None)


def sample_rate() -> int:
    """1-in-N sampling rate (0 = off): the kv-broadcast runtime override
    when one is installed, else ``RAY_TPU_TRACE_SAMPLE``. The env var is
    re-read per call (tests monkeypatch it) but parsed once per distinct
    value — this runs on the per-task submit hot path."""
    global _rate_cache
    if _rate_override is not None:
        return _rate_override
    raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "")
    cached = _rate_cache
    if cached[0] == raw:
        return cached[1]
    if not raw:
        rate = _DEFAULT_RATE
    else:
        try:
            rate = max(0, int(raw))
        except ValueError:
            rate = _DEFAULT_RATE
    _rate_cache = (raw, rate)
    return rate


def maybe_sample() -> Optional[bytes]:
    """Per-task sampling decision: every Nth submission gets a fresh 8-byte
    trace id; everything else pays one counter increment."""
    rate = sample_rate()
    if rate <= 0:
        return None
    if next(_counter) % rate:
        return None
    _trace_metrics()["sampled"].record(1.0)
    return os.urandom(8)


def _trace_metrics() -> Dict[str, Any]:
    """Lazily-registered tracing counters (driver/worker side; rides the
    same registry the Prometheus endpoint renders)."""
    with _lock:
        if not _metrics_box:
            from ..metrics import Count, Histogram, get_or_create

            _metrics_box["sampled"] = get_or_create(
                Count, "trace_tasks_sampled",
                description="tasks selected for per-task tracing")
            _metrics_box["spans"] = get_or_create(
                Count, "trace_spans_recorded", tag_keys=("phase",),
                description="trace spans recorded in this process")
            _metrics_box["phase_ms"] = get_or_create(
                Histogram, "trace_phase_ms", tag_keys=("phase",),
                description="per-phase wall time of sampled tasks",
                boundaries=[0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000])
        return _metrics_box


def make_span(trace: bytes, task_id: Optional[bytes], phase: str,
              start_mono: float, end_mono: float,
              src: str = "", via: str = "") -> Dict[str, Any]:
    """One phase span. Takes time.monotonic() endpoints (exact durations)
    and anchors them to wall clock here — the offset is constant per
    process, so durations stay exact while epochs become comparable
    across machines (same convention as profile-event flush).

    ``via`` attributes a span to its delivery mechanism — for
    driver_fetch, whether the result arrived through the shm completion
    ring ("ring"), rode inline in the completion record ("inline"), was
    pushed with the directory answer ("inline_push"), or took a fetch
    RPC ("rpc") — so a straggler report can separate data-plane tails
    from control-plane ones."""
    off = time.time() - time.monotonic()
    m = _trace_metrics()
    tags = {"phase": phase}
    m["spans"].record(1.0, tags=tags)
    m["phase_ms"].record((end_mono - start_mono) * 1e3, tags=tags)
    out = {
        "trace": trace.hex() if isinstance(trace, bytes) else str(trace),
        "task_id": (task_id.hex() if isinstance(task_id, bytes)
                    else str(task_id or "")),
        "phase": phase,
        "start": start_mono + off,
        "end": end_mono + off,
        "src": src,
    }
    if via:
        out["via"] = via
    return out


# --------------------------------------------------------------------------
# consumers: trace grouping + the straggler report
# --------------------------------------------------------------------------

def group_traces(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Group raw spans by trace id:
    {trace: {"task_id", "phases": {phase: [start, end]}, "total_ms"}}.
    A phase reported twice (e.g. a re-dispatched retry) keeps the widest
    window. total_ms spans first start -> last end across phases."""
    out: Dict[str, Dict[str, Any]] = {}
    for sp in spans:
        tr = sp.get("trace")
        if not tr:
            continue
        rec = out.setdefault(tr, {"task_id": sp.get("task_id", ""),
                                  "phases": {}})
        if sp.get("task_id"):
            rec["task_id"] = sp["task_id"]
        if sp.get("via") and sp["phase"] == "driver_fetch":
            # Result-plane attribution: how the owner got the bytes.
            rec["fetch_via"] = sp["via"]
        cur = rec["phases"].get(sp["phase"])
        if cur is None:
            rec["phases"][sp["phase"]] = [sp["start"], sp["end"]]
        else:
            cur[0] = min(cur[0], sp["start"])
            cur[1] = max(cur[1], sp["end"])
    for rec in out.values():
        ph = rec["phases"]
        rec["total_ms"] = round(
            (max(p[1] for p in ph.values())
             - min(p[0] for p in ph.values())) * 1e3, 3) if ph else 0.0
    return out


def straggler_report(spans: List[Dict[str, Any]], top_k: int = 10) -> str:
    """Top-k slowest sampled tasks with their latency attributed by phase —
    the per-task answer to "why was this task's p99 37x its p50" that the
    aggregate phase table cannot give."""
    traces = group_traces(spans)
    if not traces:
        return "no sampled traces (is RAY_TPU_TRACE_SAMPLE > 0?)"
    complete = sorted(traces.items(), key=lambda kv: -kv[1]["total_ms"])
    head = (f"{'TRACE':<18} {'TASK':<18} {'TOTAL':>9} "
            + " ".join(f"{p.replace('driver_', 'drv_').replace('result_', 'res_'):>11}"
                       for p in PHASES))
    lines = [f"{len(traces)} sampled traces; top {min(top_k, len(complete))}"
             f" by end-to-end latency (ms per phase; . = no span)", head]
    for tr, rec in complete[:top_k]:
        cells = []
        for p in PHASES:
            win = rec["phases"].get(p)
            cells.append(f"{(win[1] - win[0]) * 1e3:>11.3f}" if win
                         else f"{'.':>11}")
        via = rec.get("fetch_via")
        lines.append(f"{tr:<18} {rec['task_id'][:16]:<18} "
                     f"{rec['total_ms']:>9.3f} " + " ".join(cells)
                     + (f"  [{via}]" if via else ""))
    return "\n".join(lines)
