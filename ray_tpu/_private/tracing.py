"""Per-task distributed tracing (reference: Ray's per-task state tracking +
timeline primitives, arXiv:1712.05889 §4; the critical-path observation that
stragglers are located by per-task span data, not aggregates,
arXiv:1711.01912).

A *trace* is one sampled task followed across every control-plane hop. The
trace context is 8 random bytes carried inside the task spec (binary wire
frames encode it as a versioned spec-header extension — see
``cluster/wire.py`` SPEC_VERSION 2; pickle frames just carry the dict key).
Each hop records wall-clock *spans* for the phases it owns — the same 7
phases the aggregate profiler (PR 2) defines:

    driver_serialize -> submit_rpc -> gcs_place -> dispatch_relay
    -> worker_exec -> result_register -> driver_fetch

Spans flush in batches to the GCS trace table (a ring buffer beside
``profile_events``) where three consumers read them: ``ray_tpu.timeline()``
(chrome-trace lanes, one lane per trace), the straggler report
(``cli trace`` / ``scripts/cluster_lat.py --traces``), and the dashboard.

Sampling (default 1/64, ``RAY_TPU_TRACE_SAMPLE``; 0 disables, 1 traces
everything) keeps the submit hot path at one counter increment per task.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Phase order IS the trace's causal order; reports and monotonicity checks
# key off this tuple.
PHASES = ("driver_serialize", "submit_rpc", "gcs_place", "dispatch_relay",
          "worker_exec", "result_register", "driver_fetch")

_DEFAULT_RATE = 64

_counter = itertools.count()
_lock = threading.Lock()
_metrics_box: Dict[str, Any] = {}


_rate_cache = ("\0unset", _DEFAULT_RATE)

# Runtime override (``cli trace --sample N`` broadcast through the GCS kv,
# applied by each process's stats/heartbeat poll): takes precedence over
# the env var so the rate is adjustable on a LIVE cluster without
# restarting every process. None = no override (env/default applies).
TRACE_SAMPLE_KV_KEY = "__ray_tpu_trace_sample__"
_rate_override: Optional[int] = None


def set_rate_override(rate: Optional[int]) -> None:
    """Install (or clear, with None) the cluster-broadcast sampling rate."""
    global _rate_override
    _rate_override = max(0, int(rate)) if rate is not None else None


def rate_override() -> Optional[int]:
    return _rate_override


def apply_kv_rate(raw: Optional[bytes]) -> None:
    """Fold the GCS kv cell for TRACE_SAMPLE_KV_KEY into the override
    (shared by the controller heartbeat and driver stats polls). A missing
    or unparsable cell clears the override back to env/default."""
    if raw is None:
        set_rate_override(None)
        return
    try:
        set_rate_override(int(bytes(raw).decode()))
    except (ValueError, UnicodeDecodeError):
        set_rate_override(None)


def sample_rate() -> int:
    """1-in-N sampling rate (0 = off): the kv-broadcast runtime override
    when one is installed, else ``RAY_TPU_TRACE_SAMPLE``. The env var is
    re-read per call (tests monkeypatch it) but parsed once per distinct
    value — this runs on the per-task submit hot path."""
    global _rate_cache
    if _rate_override is not None:
        return _rate_override
    raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "")
    cached = _rate_cache
    if cached[0] == raw:
        return cached[1]
    if not raw:
        rate = _DEFAULT_RATE
    else:
        try:
            rate = max(0, int(raw))
        except ValueError:
            rate = _DEFAULT_RATE
    _rate_cache = (raw, rate)
    return rate


def maybe_sample() -> Optional[bytes]:
    """Per-task sampling decision: every Nth submission gets a fresh 8-byte
    trace id; everything else pays one counter increment."""
    rate = sample_rate()
    if rate <= 0:
        return None
    if next(_counter) % rate:
        return None
    _trace_metrics()["sampled"].record(1.0)
    return os.urandom(8)


def _trace_metrics() -> Dict[str, Any]:
    """Lazily-registered tracing counters (driver/worker side; rides the
    same registry the Prometheus endpoint renders)."""
    with _lock:
        if not _metrics_box:
            from ..metrics import Count, Histogram, get_or_create

            _metrics_box["sampled"] = get_or_create(
                Count, "trace_tasks_sampled",
                description="tasks selected for per-task tracing")
            _metrics_box["spans"] = get_or_create(
                Count, "trace_spans_recorded", tag_keys=("phase",),
                description="trace spans recorded in this process")
            _metrics_box["phase_ms"] = get_or_create(
                Histogram, "trace_phase_ms", tag_keys=("phase",),
                description="per-phase wall time of sampled tasks",
                boundaries=[0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000])
        return _metrics_box


def make_span(trace: bytes, task_id: Optional[bytes], phase: str,
              start_mono: float, end_mono: float,
              src: str = "", via: str = "") -> Dict[str, Any]:
    """One phase span. Takes time.monotonic() endpoints (exact durations)
    and anchors them to wall clock here — the offset is constant per
    process, so durations stay exact while epochs become comparable
    across machines (same convention as profile-event flush).

    ``via`` attributes a span to its delivery mechanism — for
    driver_fetch, whether the result arrived through the shm completion
    ring ("ring"), rode inline in the completion record ("inline"), was
    pushed with the directory answer ("inline_push"), or took a fetch
    RPC ("rpc") — so a straggler report can separate data-plane tails
    from control-plane ones."""
    off = time.time() - time.monotonic()
    m = _trace_metrics()
    tags = {"phase": phase}
    m["spans"].record(1.0, tags=tags)
    m["phase_ms"].record((end_mono - start_mono) * 1e3, tags=tags)
    out = {
        "trace": trace.hex() if isinstance(trace, bytes) else str(trace),
        "task_id": (task_id.hex() if isinstance(task_id, bytes)
                    else str(task_id or "")),
        "phase": phase,
        "start": start_mono + off,
        "end": end_mono + off,
        "src": src,
    }
    if via:
        out["via"] = via
    return out


# --------------------------------------------------------------------------
# consumers: trace grouping + the straggler report
# --------------------------------------------------------------------------

def group_traces(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Group raw spans by trace id:
    {trace: {"task_id", "phases": {phase: [start, end]}, "total_ms"}}.
    A phase reported twice (e.g. a re-dispatched retry) keeps the widest
    window. total_ms spans first start -> last end across phases."""
    out: Dict[str, Dict[str, Any]] = {}
    for sp in spans:
        tr = sp.get("trace")
        if not tr:
            continue
        rec = out.setdefault(tr, {"task_id": sp.get("task_id", ""),
                                  "phases": {}})
        if sp.get("task_id"):
            rec["task_id"] = sp["task_id"]
        if sp.get("via") and sp["phase"] == "driver_fetch":
            # Result-plane attribution: how the owner got the bytes.
            rec["fetch_via"] = sp["via"]
        cur = rec["phases"].get(sp["phase"])
        if cur is None:
            rec["phases"][sp["phase"]] = [sp["start"], sp["end"]]
        else:
            cur[0] = min(cur[0], sp["start"])
            cur[1] = max(cur[1], sp["end"])
    for rec in out.values():
        ph = rec["phases"]
        rec["total_ms"] = round(
            (max(p[1] for p in ph.values())
             - min(p[0] for p in ph.values())) * 1e3, 3) if ph else 0.0
    return out


def straggler_report(spans: List[Dict[str, Any]], top_k: int = 10) -> str:
    """Top-k slowest sampled tasks with their latency attributed by phase —
    the per-task answer to "why was this task's p99 37x its p50" that the
    aggregate phase table cannot give."""
    traces = group_traces(spans)
    if not traces:
        return "no sampled traces (is RAY_TPU_TRACE_SAMPLE > 0?)"
    complete = sorted(traces.items(), key=lambda kv: -kv[1]["total_ms"])
    head = (f"{'TRACE':<18} {'TASK':<18} {'TOTAL':>9} "
            + " ".join(f"{p.replace('driver_', 'drv_').replace('result_', 'res_'):>11}"
                       for p in PHASES))
    lines = [f"{len(traces)} sampled traces; top {min(top_k, len(complete))}"
             f" by end-to-end latency (ms per phase; . = no span)", head]
    for tr, rec in complete[:top_k]:
        cells = []
        for p in PHASES:
            win = rec["phases"].get(p)
            cells.append(f"{(win[1] - win[0]) * 1e3:>11.3f}" if win
                         else f"{'.':>11}")
        via = rec.get("fetch_via")
        lines.append(f"{tr:<18} {rec['task_id'][:16]:<18} "
                     f"{rec['total_ms']:>9.3f} " + " ".join(cells)
                     + (f"  [{via}]" if via else ""))
    return "\n".join(lines)


# --------------------------------------------------------------------------
# wall-clock conservation ledger (the PR-18 observatory invariant)
# --------------------------------------------------------------------------

# Gap buckets the ledger can name, in ledger order. ``worker_queue`` is
# measured per-task from the trace itself (dispatch handoff -> exec
# start, i.e. time spent queued behind other tasks at the worker); the
# other four are inferred from the observatory's window aggregates.
GAP_BUCKETS = ("worker_queue", "head_loop_lag", "callback_run",
               "socket_dwell", "ctx_switch")

# Context-switch cost proxy: direct switch cost plus the cache/GIL
# reacquisition tail — a *proxy*, stated as such everywhere it prints
# (microbenchmarks put a Linux switch at 1–5 µs; we take the low end so
# the bucket can only under-claim).
CTX_SWITCH_US = 2.0


def conservation_ledger(traces: Dict[str, Dict[str, Any]],
                        window: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
    """Phases + named gap buckets must sum to end-to-end wall within ε.

    ``traces``: :func:`group_traces` output for the window's sampled
    tasks — per task, e2e = last span end - first span start and the gap
    is the inter-span wall the 7 phases do NOT cover. ``window``: the
    observatory aggregates over the same wall window::

        {"tasks": n,            # tasks completed in the window
         "lag_s": float,        # head loop-lag sum (loopmon heartbeat)
         "cb_s": float,         # head callback run time (loopmon)
         "handler_s": float,    # head handler seconds (already inside
                                # the gcs-side phases; subtracted from
                                # cb_s so callback_run is the *extra*)
         "dwell_s": float,      # head select/poll dwell (informational)
         "socket_dwell_s": float,  # driver blocked-in-recv seconds
         "ctx": int}            # process ctx switches in the window

    Each gap bucket is scaled to µs/task and *capped at the measured
    gap* — the ledger may under-explain (coverage < 1) but can never
    invent wall time. Returns phase/gap µs-per-task rows plus
    ``coverage`` = (phases + explained gaps) / e2e."""
    phase_us = {p: 0.0 for p in PHASES}
    e2e_us = 0.0
    queue_us = 0.0
    n = 0
    for rec in traces.values():
        ph = rec.get("phases") or {}
        if not ph:
            continue
        n += 1
        e2e_us += (max(w[1] for w in ph.values())
                   - min(w[0] for w in ph.values())) * 1e6
        for p, w in ph.items():
            if p in phase_us:
                phase_us[p] += (w[1] - w[0]) * 1e6
        # Worker-queue wait is exact per task: the dispatch frame is on
        # the worker's wire, execution hasn't started — the task is
        # sitting behind others in the worker's run queue.
        if "dispatch_relay" in ph and "worker_exec" in ph:
            queue_us += max(
                0.0, (ph["worker_exec"][0] - ph["dispatch_relay"][1]) * 1e6)
    if not n:
        return {"tasks": 0, "e2e_us": 0.0, "phase_us": {},
                "gap_us": 0.0, "buckets_us": {}, "explained_us": 0.0,
                "coverage": 0.0}
    e2e_us /= n
    phase_us = {p: v / n for p, v in phase_us.items()}
    phase_sum = sum(phase_us.values())
    gap_us = max(0.0, e2e_us - phase_sum)

    buckets = {b: 0.0 for b in GAP_BUCKETS}
    buckets["worker_queue"] = queue_us / n
    if window and window.get("tasks"):
        per = 1e6 / max(float(window["tasks"]), 1.0)
        buckets["head_loop_lag"] = float(window.get("lag_s") or 0.0) * per
        buckets["callback_run"] = max(
            0.0, float(window.get("cb_s") or 0.0)
            - float(window.get("handler_s") or 0.0)) * per
        buckets["socket_dwell"] = \
            float(window.get("socket_dwell_s") or 0.0) * per
        buckets["ctx_switch"] = \
            float(window.get("ctx") or 0) * CTX_SWITCH_US \
            / max(float(window["tasks"]), 1.0)
    # Conservation discipline: never explain more gap than exists.
    claimed = sum(buckets.values())
    if claimed > gap_us and claimed > 0:
        scale = gap_us / claimed
        buckets = {b: v * scale for b, v in buckets.items()}
    explained = sum(buckets.values())
    return {
        "tasks": n, "e2e_us": e2e_us, "phase_us": phase_us,
        "phase_sum_us": phase_sum, "gap_us": gap_us,
        "buckets_us": buckets, "explained_us": explained,
        "coverage": min(1.0, (phase_sum + explained) / max(e2e_us, 1e-9)),
    }


def ledger_table(ledger: Dict[str, Any]) -> str:
    """Render a conservation ledger as the fixed-width table `cli loops`,
    scripts/cluster_lat.py --ledger and PERF.md share."""
    if not ledger.get("tasks"):
        return "conservation ledger: no sampled traces in window"
    lines = [f"conservation ledger over {ledger['tasks']} sampled tasks "
             f"(µs/task; e2e = {ledger['e2e_us']:.1f})",
             f"{'BUCKET':<22} {'µs/task':>10} {'% e2e':>7}"]
    e2e = max(ledger["e2e_us"], 1e-9)
    for p in PHASES:
        v = ledger["phase_us"].get(p, 0.0)
        lines.append(f"{p:<22} {v:>10.1f} {100 * v / e2e:>6.1f}%")
    for b in GAP_BUCKETS:
        v = ledger["buckets_us"].get(b, 0.0)
        lines.append(f"gap:{b:<18} {v:>10.1f} {100 * v / e2e:>6.1f}%")
    resid = e2e - ledger["phase_sum_us"] - ledger["explained_us"]
    lines.append(f"{'(unattributed)':<22} {resid:>10.1f} "
                 f"{100 * resid / e2e:>6.1f}%")
    lines.append(f"{'coverage':<22} {'':>10} "
                 f"{100 * ledger['coverage']:>6.1f}%")
    return "\n".join(lines)
