"""Fixed-resolution metrics time-series rollups (reference: the GCS-backed
stats tables Ray's dashboard trends from, arXiv:1712.05889 §4.1; the
retention discipline mirrors Prometheus' fixed-step TSDB blocks, shrunk to
an in-memory ring per series).

One :class:`TimeSeriesStore` lives in the GCS beside the event/trace ring
buffers (``cluster/gcs.py``): every rollup tick folds counter deltas, gauge
samples, and histogram-delta snapshots into aligned fixed-width buckets
(default 10 s), each series bounded by a retention ring — the storage model
the dashboard's ``/api/timeseries`` sparklines, ``cli top``, and the SLO
burn-rate rules (``monitor.py``) all read.

Three cell kinds, chosen so every consumer question is one bucket scan:

* ``delta``  — increments observed during the bucket (counter deltas;
  tasks/s is ``sum / bucket_s``);
* ``gauge``  — last/min/max/avg of samples within the bucket;
* ``hist``   — a bucketed distribution of the events that happened during
  the bucket (sources ship per-flush deltas, merged additively), from
  which :func:`quantile_from_hist` estimates p50/p99.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


class TimeSeriesStore:
    """Per-name ring of aligned fixed-width buckets.

    Thread-safe: producers (the GCS rollup loop, node/driver stat handlers)
    and consumers (RPC snapshot) may interleave. Buckets are aligned to
    ``bucket_s`` boundaries of the wall clock so two stores (or a restart)
    produce comparable timestamps; late samples (clock skew, delayed
    flushes) fold into the newest bucket rather than minting out-of-order
    entries.
    """

    def __init__(self, bucket_s: float = 10.0, retention_buckets: int = 360):
        if bucket_s <= 0:
            raise ValueError("bucket_s must be positive")
        if retention_buckets <= 0:
            raise ValueError("retention_buckets must be positive")
        self.bucket_s = float(bucket_s)
        self.retention_buckets = int(retention_buckets)
        self._lock = threading.Lock()
        # name -> (kind, deque[[bucket_start, cell]]) — the deque maxlen IS
        # the retention policy (same discipline as the GCS event rings).
        self._series: Dict[str, tuple] = {}

    # ------------------------------------------------------------- recording
    def _bucket_start(self, ts: Optional[float]) -> float:
        if ts is None:
            ts = time.time()
        return (int(ts) // int(self.bucket_s)) * int(self.bucket_s) \
            if self.bucket_s >= 1 else ts - (ts % self.bucket_s)

    def _cell(self, name: str, kind: str, ts: Optional[float]) -> Dict:
        """Current bucket's cell for ``name`` (created/rotated as needed).
        Caller holds the lock."""
        entry = self._series.get(name)
        if entry is None:
            entry = (kind, deque(maxlen=self.retention_buckets))
            self._series[name] = entry
        stored_kind, ring = entry
        if stored_kind != kind:
            raise ValueError(
                f"series {name!r} is {stored_kind}, not {kind}")
        start = self._bucket_start(ts)
        if ring and ring[-1][0] >= start:
            # Same bucket — or a late/straggling sample: fold into newest.
            return ring[-1][1]
        cell: Dict[str, Any]
        if kind == "delta":
            cell = {"sum": 0.0}
        elif kind == "gauge":
            cell = {"last": 0.0, "min": None, "max": None,
                    "sum": 0.0, "n": 0}
        else:  # hist
            cell = {"buckets": {}, "sum": 0.0, "count": 0}
        ring.append([start, cell])
        return cell

    def add_delta(self, name: str, value: float,
                  ts: Optional[float] = None) -> None:
        """Fold counter *increments* (not cumulative totals) into the
        current bucket. Rate over a bucket = sum / bucket_s."""
        with self._lock:
            cell = self._cell(name, "delta", ts)
            cell["sum"] += float(value)

    def add_gauge(self, name: str, value: float,
                  ts: Optional[float] = None) -> None:
        value = float(value)
        with self._lock:
            cell = self._cell(name, "gauge", ts)
            cell["last"] = value
            cell["min"] = value if cell["min"] is None \
                else min(cell["min"], value)
            cell["max"] = value if cell["max"] is None \
                else max(cell["max"], value)
            cell["sum"] += value
            cell["n"] += 1

    def add_hist(self, name: str, buckets: Dict[str, int],
                 total: float = 0.0, count: int = 0,
                 ts: Optional[float] = None) -> None:
        """Merge one histogram *delta* snapshot (bucket-boundary -> count of
        events since the source's last flush) into the current bucket.
        Additive across sources — two drivers flushing into the same bucket
        produce their combined distribution."""
        with self._lock:
            cell = self._cell(name, "hist", ts)
            dst = cell["buckets"]
            for bound, n in buckets.items():
                if n:
                    dst[bound] = dst.get(bound, 0) + int(n)
            cell["sum"] += float(total)
            cell["count"] += int(count)

    # ------------------------------------------------------------- consuming
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str, last: Optional[int] = None) -> List[list]:
        """[[bucket_start, cell], ...] oldest-first (copies, detached from
        the live ring)."""
        with self._lock:
            entry = self._series.get(name)
            if entry is None:
                return []
            pts = list(entry[1])
        if last is not None:
            pts = pts[-int(last):]
        return [[t, dict(c)] for t, c in pts]

    def snapshot(self, names: Optional[Iterable[str]] = None,
                 last: Optional[int] = None) -> Dict[str, Dict]:
        """The RPC/dashboard payload: {name: {kind, points}}."""
        with self._lock:
            wanted = list(names) if names is not None \
                else sorted(self._series)
            raw = {n: (self._series[n][0], list(self._series[n][1]))
                   for n in wanted if n in self._series}
        out = {}
        for n, (kind, pts) in raw.items():
            if last is not None:
                pts = pts[-int(last):]
            out[n] = {"kind": kind,
                      "points": [[t, dict(c)] for t, c in pts]}
        return out


# --------------------------------------------------------------------------
# consumers: windows, quantiles, sparklines
# --------------------------------------------------------------------------

def window_sum(points: Sequence[Sequence], since: float) -> float:
    """Sum of delta-cell increments in buckets starting at/after ``since``."""
    return sum(c["sum"] for t, c in points if t >= since)


def window_rate(points: Sequence[Sequence], since: float,
                now: Optional[float] = None) -> float:
    """Average events/second over the window — denominated in wall time,
    not bucket count, so sparse rings don't overstate the rate."""
    if now is None:
        now = time.time()
    span = max(now - since, 1e-9)
    return window_sum(points, since) / span


def latest_value(points: Sequence[Sequence],
                 key: str = "last") -> Optional[float]:
    """Newest bucket's cell value (``last`` for gauges, pass ``sum`` for
    delta cells); None when the series is empty. The one-liner every
    'current value of this gauge series' consumer (`cli top` rows,
    `cli doctor` snapshots) kept re-writing."""
    if not points:
        return None
    cell = points[-1][1]
    return cell.get(key)


def gauge_window(points: Sequence[Sequence], since: float,
                 key: str = "last") -> List[float]:
    """Every gauge-cell ``key`` value in buckets at/after ``since``,
    oldest-first. The sustained-breach primitive: a gauge-ceiling SLO
    fires only when min() of this window exceeds the threshold, and the
    `cli top`/`cli loops` loop-lag rows read the same slice."""
    return [c[key] for t, c in points
            if t >= since and c.get(key) is not None]


def merge_hist(cells: Iterable[Dict]) -> Dict:
    """Additively merge hist cells (e.g. every bucket of a window) into one
    {buckets, sum, count} distribution."""
    out: Dict[str, Any] = {"buckets": {}, "sum": 0.0, "count": 0}
    for c in cells:
        for bound, n in c.get("buckets", {}).items():
            out["buckets"][bound] = out["buckets"].get(bound, 0) + int(n)
        out["sum"] += float(c.get("sum", 0.0))
        out["count"] += int(c.get("count", 0))
    return out


def quantile_from_hist(cell: Dict, q: float) -> Optional[float]:
    """Estimate the q-quantile from a bucketed distribution (upper-bound
    convention, same as Prometheus ``histogram_quantile``): the first
    boundary whose cumulative count covers q. None when empty. ``+inf``
    entries clamp to the largest finite boundary."""
    total = cell.get("count") or sum(cell.get("buckets", {}).values())
    if not total:
        return None
    import math

    finite = []
    for bound, n in cell.get("buckets", {}).items():
        try:
            b = float(bound)
        except (TypeError, ValueError):
            continue
        if math.isfinite(b):
            finite.append((b, int(n)))
    finite.sort()
    target = q * total
    cum = 0
    for bound, n in finite:
        cum += n
        if cum >= target:
            return bound
    return finite[-1][0] if finite else None


def sparkline(values: Sequence[float], width: int = 30) -> str:
    """Unicode block sparkline (``cli top`` / dashboard panels)."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK_BLOCKS[0] * len(vals)
    idx_hi = len(SPARK_BLOCKS) - 1
    return "".join(
        SPARK_BLOCKS[round((v - lo) / span * idx_hi)] for v in vals)
