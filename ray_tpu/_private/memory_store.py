"""In-process object store.

Plays the role of the reference's ``CoreWorkerMemoryStore`` (reference:
``src/ray/core_worker/store_provider/memory_store/``) for the local runtime:
immutable objects keyed by ObjectID, blocking gets with timeout, async
listeners used by the dependency manager, LRU-ish accounting against a byte
budget. In the cluster backend the same interface fronts the shared-memory
arena (ray_tpu/cluster), so callers never care which plane an object is on.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..exceptions import GetTimeoutError, ObjectStoreFullError
from .ids import ObjectID


class StoredObject:
    """One immutable stored value.

    ``value`` is the in-process deserialized object (stored once; callers must
    not mutate — same contract as plasma's immutable buffers). ``error`` holds
    a TaskError/ActorError to re-raise at get().
    """

    __slots__ = ("value", "error", "nbytes", "created_at")

    def __init__(self, value: Any = None, error: Optional[BaseException] = None,
                 nbytes: int = 0):
        self.value = value
        self.error = error
        self.nbytes = nbytes
        self.created_at = time.monotonic()


class MemoryStore:
    def __init__(self, max_bytes: int = 0, spiller=None):
        """``spiller``: an optional ``_private.spill.SpillManager``. With
        one attached, puts over budget spill the oldest picklable values to
        disk (same graceful-degradation contract as the shared-memory
        arena's SpillingStore) instead of raising ObjectStoreFullError;
        gets transparently restore. Error objects and values that fail to
        pickle stay resident (the budget is best-effort for them)."""
        self._objects: Dict[ObjectID, StoredObject] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._listeners: Dict[ObjectID, List[Callable[[ObjectID], None]]] = {}
        self._max_bytes = max_bytes
        self._used_bytes = 0
        self._spiller = spiller
        self._spilled: Dict[ObjectID, int] = {}  # oid -> spilled nbytes
        self._unspillable: set = set()  # values that failed to pickle

    # -- spill ----------------------------------------------------------------
    def _spill_lru_locked(self, need: int) -> None:
        """Move the oldest spillable values to disk until ``need`` more
        bytes fit (insertion order ~= LRU for an immutable store). Lock
        held by the caller."""
        if self._spiller is None or not self._max_bytes:
            return
        import cloudpickle

        for oid in list(self._objects):
            if self._used_bytes + need <= self._max_bytes:
                return
            obj = self._objects[oid]
            if obj.error is not None or oid in self._unspillable:
                continue  # errors stay resident (tiny, must re-raise)
            try:
                blob = cloudpickle.dumps(obj.value)
            except Exception:  # noqa: BLE001 - unpicklable: pin resident
                self._unspillable.add(oid)
                continue
            try:
                self._spiller.write(oid.binary(), blob)
            except OSError:
                return  # spill disk full/unwritable: stop trying
            self._spilled[oid] = obj.nbytes
            del self._objects[oid]
            self._used_bytes -= obj.nbytes

    def _restore_locked(self, object_id: ObjectID) -> Optional[StoredObject]:
        """Disk-second half of get: unpickle a spilled value back into the
        store (spilling others if the budget demands). Lock held."""
        if self._spiller is None or object_id not in self._spilled:
            return None
        import pickle

        blob = self._spiller.read(object_id.binary())
        nbytes = self._spilled.pop(object_id)
        if blob is None:
            return None  # torn/corrupt copy: lost (recovery is upstream)
        obj = StoredObject(value=pickle.loads(blob), nbytes=nbytes)
        if self._max_bytes and self._used_bytes + nbytes > self._max_bytes:
            self._spill_lru_locked(nbytes)
        self._objects[object_id] = obj
        self._used_bytes += nbytes
        self._spiller.delete(object_id.binary())
        return obj

    # -- write ----------------------------------------------------------------
    def put(self, object_id: ObjectID, obj: StoredObject) -> None:
        with self._lock:
            existing = self._objects.get(object_id)
            if existing is not None or object_id in self._spilled:
                return  # objects are immutable; double-put is a no-op
            if self._max_bytes and self._used_bytes + obj.nbytes > self._max_bytes:
                self._spill_lru_locked(obj.nbytes)
            if self._max_bytes and self._spiller is None \
                    and self._used_bytes + obj.nbytes > self._max_bytes:
                raise ObjectStoreFullError(
                    f"object store over budget: {self._used_bytes + obj.nbytes} "
                    f"> {self._max_bytes} bytes"
                )
            # With a spiller the budget is soft: when even spilling could
            # not make room (everything unspillable) the put still lands —
            # degradation, not failure.
            self._objects[object_id] = obj
            self._used_bytes += obj.nbytes
            listeners = self._listeners.pop(object_id, [])
            self._cv.notify_all()
        for cb in listeners:
            cb(object_id)

    def delete(self, object_ids: Sequence[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                obj = self._objects.pop(oid, None)
                if obj is not None:
                    self._used_bytes -= obj.nbytes
                if self._spilled.pop(oid, None) is not None:
                    self._spiller.delete(oid.binary())
                self._unspillable.discard(oid)

    # -- read -----------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects or object_id in self._spilled

    def get_if_exists(self, object_id: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                obj = self._restore_locked(object_id)
            return obj

    def get(self, object_ids: Sequence[ObjectID],
            timeout: Optional[float] = None) -> List[StoredObject]:
        """Blocking batched get; raises GetTimeoutError on expiry."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                for oid in object_ids:
                    if oid not in self._objects:
                        self._restore_locked(oid)
                missing = [oid for oid in object_ids if oid not in self._objects]
                if not missing:
                    return [self._objects[oid] for oid in object_ids]
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise GetTimeoutError(
                            f"get timed out; {len(missing)} of {len(object_ids)} "
                            f"objects not ready (first missing: {missing[0]})"
                        )
                self._cv.wait(timeout=remaining)

    def wait(self, object_ids: Sequence[ObjectID], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectID], List[ObjectID]]:
        """ray.wait semantics: block until num_returns ready or timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [oid for oid in object_ids
                         if oid in self._objects or oid in self._spilled]
                if len(ready) >= num_returns:
                    ready_set = set(ready[:num_returns])
                    # preserve input order in both lists
                    ready_list = [o for o in object_ids if o in ready_set]
                    rest = [o for o in object_ids if o not in ready_set]
                    return ready_list, rest
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        ready_set = set(ready)
                        return (
                            [o for o in object_ids if o in ready_set],
                            [o for o in object_ids if o not in ready_set],
                        )
                self._cv.wait(timeout=remaining)

    # -- async notification (dependency manager hook) -------------------------
    def on_available(self, object_id: ObjectID,
                     callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when object_id becomes available (maybe immediately)."""
        with self._lock:
            if object_id in self._objects or object_id in self._spilled:
                fire = True
            else:
                self._listeners.setdefault(object_id, []).append(callback)
                fire = False
        if fire:
            callback(object_id)

    # -- stats ----------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "used_bytes": self._used_bytes,
                "max_bytes": self._max_bytes,
                "spilled_objects": len(self._spilled),
                "spilled_bytes": sum(self._spilled.values()),
            }
