"""Task specifications.

Equivalent of the reference's ``TaskSpecification`` (reference:
``src/ray/common/task/task_spec.h:26``): an immutable record describing one
invocation — function, args (inline values or ObjectID refs), resource demand,
retry policy, actor linkage — plus the interned ``SchedulingClass`` (ref
``task_spec.h:190-192``) that groups tasks with identical resource shapes so
the scheduler and worker pool can treat them as one class.

No protobuf here: specs live in-process or are pickled across the control
socket; the dense scheduling representation is produced by
``resources.dense_matrix`` for the placement kernel instead.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, TaskID
from .resources import ResourceSet


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2
    DRIVER_TASK = 3


# --- SchedulingClass interning (ref task_spec.h:190-192, static maps) ---------
_sched_class_lock = threading.Lock()
_sched_class_table: Dict[Tuple, int] = {}
_sched_class_rev: List[Tuple] = []


def scheduling_class_of(resources: ResourceSet, fn_key: Optional[str] = None) -> int:
    """Intern (resource shape, function) into a small int id."""
    key = (resources.key(), fn_key)
    with _sched_class_lock:
        sc = _sched_class_table.get(key)
        if sc is None:
            sc = len(_sched_class_rev)
            _sched_class_table[key] = sc
            _sched_class_rev.append(key)
        return sc


def scheduling_class_resources(sc: int) -> ResourceSet:
    key = _sched_class_rev[sc][0]
    predefined, custom = key
    import numpy as np

    return ResourceSet(np.array(predefined), dict(custom))


@dataclass(frozen=True)
class FunctionDescriptor:
    """Identifies a remote function or actor method across processes."""

    module: str
    qualname: str
    function_hash: bytes = b""

    @property
    def repr_name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function: FunctionDescriptor
    # Args: list of ("value", pickled_bytes_or_obj) or ("ref", ObjectID).
    args: List[Tuple[str, Any]]
    num_returns: int
    resources: ResourceSet
    parent_task_id: Optional[TaskID] = None
    max_retries: int = 0
    # Actor linkage
    actor_id: Optional[ActorID] = None
    actor_counter: int = 0  # per-caller sequence number for ordered delivery
    max_restarts: int = 0
    max_concurrency: int = 1
    is_asyncio: bool = False
    name: Optional[str] = None
    # Placement hints
    placement_node: Optional[Any] = None
    # Placement-group linkage (observability; the scheduling effect is
    # carried entirely by the translated group-scoped resource names).
    placement_group_id: Optional[bytes] = None
    placement_group_bundle_index: int = -1
    # Deadline: the controller kills the task (SIGTERM -> SIGKILL) once it
    # has executed for timeout_s and fails it with TaskTimeoutError. Deadline
    # kills don't consume max_retries unless retry_on_timeout opts in.
    timeout_s: Optional[float] = None
    retry_on_timeout: bool = False
    metadata: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.scheduling_class = scheduling_class_of(
            self.resources, self.function.repr_name
        )
        self._return_ids: Optional[List[ObjectID]] = None

    @property
    def is_actor_task(self) -> bool:
        return self.task_type == TaskType.ACTOR_TASK

    @property
    def is_actor_creation(self) -> bool:
        return self.task_type == TaskType.ACTOR_CREATION_TASK

    def return_ids(self) -> List[ObjectID]:
        # Memoized: the submit hot path asks several times per task and the
        # ids are pure functions of (task_id, num_returns).
        rids = self._return_ids
        if rids is None:
            rids = self._return_ids = [
                ObjectID.for_task_return(self.task_id, i + 1)
                for i in range(self.num_returns)
            ]
        return rids

    def dependencies(self) -> List[ObjectID]:
        """ObjectIDs this task needs materialized before it can run.

        Scans positional ref-args AND ObjectRefs passed as kwargs — both must
        gate dispatch, otherwise a task could be admitted and then block
        holding its resources while a kwarg dependency is still pending.
        """
        deps = [arg for kind, arg in self.args if kind == "ref"]
        for v in self.metadata.get("kwargs", {}).values():
            oid = getattr(v, "id", None)
            if isinstance(oid, ObjectID):
                deps.append(oid)
        return deps

    def __repr__(self):
        return (
            f"TaskSpec({self.function.repr_name}, id={self.task_id.hex()[:8]}, "
            f"type={self.task_type.name}, deps={len(self.dependencies())})"
        )
