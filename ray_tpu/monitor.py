"""Monitor: head-node daemon driving the autoscaler
(reference: python/ray/monitor.py Monitor :21).

Polls the GCS for node membership/resources and unplaceable placement
demands, feeds LoadMetrics, and calls StandardAutoscaler.update() each tick.
The reference consumes the heartbeat pubsub stream; polling the same tables
gives identical information on our asyncio GCS.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

from .autoscaler import LoadMetrics, StandardAutoscaler
from .autoscaler.node_provider import NodeProvider
from .cluster.protocol import RpcClient


class Monitor:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 autoscaler_config: Optional[Dict[str, Any]] = None,
                 update_interval_s: float = 1.0):
        host, port = gcs_address.rsplit(":", 1)
        self.gcs = RpcClient(host, int(port))
        self.load_metrics = LoadMetrics()
        self.autoscaler = StandardAutoscaler(
            provider, self.load_metrics, autoscaler_config)
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_updates = 0
        # Pending placement groups from the last poll, with when each was
        # first seen pending — feeds the stuck-gang report.
        self._pg_pending_since: Dict[str, float] = {}
        self._pg_report_last = 0.0
        self.pg_table: Dict[str, Dict[str, Any]] = {}

    def poll_once(self) -> None:
        nodes = self.gcs.call({"type": "list_nodes"})["nodes"]
        seen = set()
        for n in nodes:
            # Autoscaler-launched nodes carry their provider node id as the
            # GCS label; keying LoadMetrics by it puts idle_ips() in the
            # same namespace as provider.internal_ip() so idle termination
            # actually matches. Head/manual nodes fall back to the NodeID.
            key = n.get("Label") or n["NodeID"]
            if not n["Alive"]:
                self.load_metrics.mark_dead(key)
                continue
            seen.add(key)
            self.load_metrics.update(key, n["Resources"], n["Available"])
        for ip in list(self.load_metrics.static_resources):
            if ip not in seen:
                self.load_metrics.mark_dead(ip)
        resp = self.gcs.call({"type": "pending_demands"})
        self.load_metrics.set_pending_demands(resp["demands"])
        # Pending gangs are atomic demand units for the scaler.
        self.load_metrics.set_pending_placement_groups(
            resp.get("pg_demands", []))
        try:
            self.pg_table = self.gcs.call(
                {"type": "list_placement_groups"})["groups"]
        except (KeyError, ConnectionError, OSError):
            self.pg_table = {}
        now = time.monotonic()
        pending_ids = set()
        for pg_hex, info in self.pg_table.items():
            if info.get("state") in ("PENDING", "RESCHEDULING"):
                pending_ids.add(pg_hex)
                self._pg_pending_since.setdefault(pg_hex, now)
        for pg_hex in list(self._pg_pending_since):
            if pg_hex not in pending_ids:
                del self._pg_pending_since[pg_hex]

    def stuck_placement_groups(self, min_pending_s: float = 10.0
                               ) -> Dict[str, Dict[str, Any]]:
        """Gangs stuck un-created past ``min_pending_s``, with the reason
        the GCS classified: "infeasible" (the fleet can never hold the
        gang — new/bigger nodes needed) vs "waiting-for-capacity"
        (running work must drain first)."""
        now = time.monotonic()
        out: Dict[str, Dict[str, Any]] = {}
        for pg_hex, since in self._pg_pending_since.items():
            if now - since < min_pending_s:
                continue
            info = self.pg_table.get(pg_hex, {})
            out[pg_hex] = {
                "pending_s": round(now - since, 1),
                "state": info.get("state", "PENDING"),
                "reason": info.get("reason", ""),
                "strategy": info.get("strategy", ""),
                "bundles": info.get("bundles", []),
            }
        return out

    def update(self) -> None:
        self.poll_once()
        self.autoscaler.update()
        self.num_updates += 1
        stuck = self.stuck_placement_groups()
        if stuck and time.monotonic() - self._pg_report_last > 30.0:
            self._pg_report_last = time.monotonic()
            for pg_hex, info in stuck.items():
                logger.warning(
                    "placement group %s stuck %s for %.0fs (%s): %s x%d",
                    pg_hex[:12], info["state"], info["pending_s"],
                    info["reason"] or "unknown", info["strategy"],
                    len(info["bundles"]))

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except (ConnectionError, OSError):
                break  # GCS gone: head is shutting down
            self._stop.wait(self.update_interval_s)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name="monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.gcs.close()
