"""Monitor: head-node daemon driving the autoscaler
(reference: python/ray/monitor.py Monitor :21).

Polls the GCS for node membership/resources and unplaceable placement
demands, feeds LoadMetrics, and calls StandardAutoscaler.update() each tick.
The reference consumes the heartbeat pubsub stream; polling the same tables
gives identical information on our asyncio GCS.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .autoscaler import LoadMetrics, StandardAutoscaler
from .autoscaler.node_provider import NodeProvider
from .cluster.protocol import RpcClient


class Monitor:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 autoscaler_config: Optional[Dict[str, Any]] = None,
                 update_interval_s: float = 1.0):
        host, port = gcs_address.rsplit(":", 1)
        self.gcs = RpcClient(host, int(port))
        self.load_metrics = LoadMetrics()
        self.autoscaler = StandardAutoscaler(
            provider, self.load_metrics, autoscaler_config)
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_updates = 0

    def poll_once(self) -> None:
        nodes = self.gcs.call({"type": "list_nodes"})["nodes"]
        seen = set()
        for n in nodes:
            # Autoscaler-launched nodes carry their provider node id as the
            # GCS label; keying LoadMetrics by it puts idle_ips() in the
            # same namespace as provider.internal_ip() so idle termination
            # actually matches. Head/manual nodes fall back to the NodeID.
            key = n.get("Label") or n["NodeID"]
            if not n["Alive"]:
                self.load_metrics.mark_dead(key)
                continue
            seen.add(key)
            self.load_metrics.update(key, n["Resources"], n["Available"])
        for ip in list(self.load_metrics.static_resources):
            if ip not in seen:
                self.load_metrics.mark_dead(ip)
        demands = self.gcs.call({"type": "pending_demands"})["demands"]
        self.load_metrics.set_pending_demands(demands)

    def update(self) -> None:
        self.poll_once()
        self.autoscaler.update()
        self.num_updates += 1

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except (ConnectionError, OSError):
                break  # GCS gone: head is shutting down
            self._stop.wait(self.update_interval_s)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name="monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.gcs.close()
