"""Monitor: head-node daemon driving the autoscaler and the SLO rule
engine (reference: python/ray/monitor.py Monitor :21; the burn-rate
discipline is the SRE multi-window alert — short AND long windows must
both overspend the error budget before a rule fires, so a blip neither
pages nor masks a slow leak).

Polls the GCS for node membership/resources and unplaceable placement
demands, feeds LoadMetrics, and calls StandardAutoscaler.update() each tick.
The reference consumes the heartbeat pubsub stream; polling the same tables
gives identical information on our asyncio GCS. A slower cadence polls the
GCS time-series rollups (``get_timeseries``) and evaluates the SLO rules:
threshold floors/ceilings (warm throughput, per-phase p99) and burn-rate
rules (event-log error rate), emitting ``slo_*`` cluster events and the
``slo_alert_active`` Prometheus gauge on transitions.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

from ._private.timeseries import (
    gauge_window, merge_hist, quantile_from_hist, window_rate, window_sum,
)
from .autoscaler import LoadMetrics, StandardAutoscaler
from .autoscaler.node_provider import NodeProvider
from .cluster.protocol import RpcClient


# --------------------------------------------------------------------------
# SLO rules over the GCS time-series
# --------------------------------------------------------------------------

class SloRule:
    """One declarative rule over the time-series rollups.

    kind:
      * ``floor``   — windowed rate of a delta series must stay >=
        ``threshold`` (evaluated only once ``min_count`` events landed in
        the window, so an idle cluster never pages on "0 tasks/s");
      * ``ceiling`` — a windowed value must stay <= ``threshold``: the
        q-``quantile`` of the window's merged histogram when ``quantile``
        is set, else the newest gauge sample;
      * ``burn``    — error-budget burn rate: the fraction
        bad/(bad+total) over BOTH a short and a long window, divided by
        ``budget``, must stay <= ``burn_threshold``;
      * ``gauge-floor`` — the newest gauge sample in the window must
        stay >= ``threshold`` (no sample in the window = not firing, so
        a cluster that hasn't produced the gauge yet never pages);
      * ``gauge-ceiling`` — SUSTAINED breach: every gauge sample in the
        window (and at least ``min_count`` of them) must exceed
        ``threshold`` before the rule fires. One spiky bucket — a GC
        pause, a cold import — never pages; a head event loop that
        stays lagged for the whole window does.
    """

    def __init__(self, name: str, kind: str, series: str,
                 threshold: float, window_s: float = 60.0,
                 min_count: float = 0.0, quantile: Optional[float] = None,
                 total_series: str = "", budget: float = 0.01,
                 burn_threshold: float = 1.0,
                 long_window_s: Optional[float] = None):
        if kind not in ("floor", "ceiling", "burn", "gauge-floor",
                        "gauge-ceiling"):
            raise ValueError(f"unknown SLO rule kind {kind!r}")
        self.name = name
        self.kind = kind
        self.series = series
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.min_count = float(min_count)
        self.quantile = quantile
        self.total_series = total_series
        self.budget = float(budget)
        self.burn_threshold = float(burn_threshold)
        self.long_window_s = float(long_window_s or window_s * 6)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_slo_rules() -> List[SloRule]:
    """The shipped rule set (each knob env-tunable): the ROADMAP's warm
    throughput floor, p99 ceilings on the phases that dominate task
    latency, and an event-log error-rate burn rule."""
    return [
        SloRule("warm_throughput", "floor", "tasks_finished",
                threshold=_env_f("RAY_TPU_SLO_TPS_FLOOR", 100.0),
                window_s=60.0,
                min_count=_env_f("RAY_TPU_SLO_TPS_MIN_TASKS", 500.0)),
        SloRule("worker_exec_p99", "ceiling", "trace_phase_ms:worker_exec",
                threshold=_env_f("RAY_TPU_SLO_PHASE_P99_MS", 500.0),
                window_s=120.0, quantile=0.99, min_count=20),
        SloRule("driver_fetch_p99", "ceiling", "trace_phase_ms:driver_fetch",
                threshold=_env_f("RAY_TPU_SLO_PHASE_P99_MS", 500.0),
                window_s=120.0, quantile=0.99, min_count=20),
        SloRule("task_error_burn", "burn", "events:task_failed",
                threshold=0.0, total_series="tasks_finished",
                budget=_env_f("RAY_TPU_SLO_ERROR_BUDGET", 0.01),
                burn_threshold=_env_f("RAY_TPU_SLO_BURN_THRESHOLD", 2.0),
                window_s=300.0, long_window_s=1800.0, min_count=50),
        # Event-loop observatory: sustained head loop lag is the one
        # signal that precedes every control-plane latency regression
        # (all GCS work queues behind it). The gauge is the per-window
        # MAX heartbeat lag (loopmon); gauge-ceiling semantics require
        # every window of the last minute to breach, so one blocking
        # import or GC pause never pages. min_count=3 refuses to call
        # a single bucket "sustained".
        SloRule("head_loop_lag", "gauge-ceiling", "head_loop_lag_ms",
                threshold=_env_f("RAY_TPU_SLO_HEAD_LOOP_LAG_MS", 250.0),
                window_s=60.0, min_count=3),
        # Head HA: a standby falling behind the leader's replication
        # stream stretches the failover recovery window — page before it
        # becomes a data-loss-shaped hole. Gauge is leader-side (set while
        # serving repl_tail), so it reads 0 with no standby attached.
        SloRule("standby_replication_lag", "ceiling",
                "gcs_standby_lag_bytes",
                threshold=_env_f("RAY_TPU_SLO_STANDBY_LAG_BYTES", 4_000_000.0),
                window_s=60.0),
        # Job profiler: scheduler-efficiency floor on the last completed
        # job (critical-path exec lower bound / actual makespan, from
        # the job_profile pass). A ratio near 0 means the job's
        # wall-clock went to scheduling gaps — queueing, dep waits,
        # dispatch latency — rather than compute; the default floor only
        # pages on pathological jobs, raise it to tighten the bound.
        SloRule("job_efficiency", "gauge-floor", "job_sched_efficiency",
                threshold=_env_f("RAY_TPU_SLO_JOB_EFFICIENCY_FLOOR", 0.05),
                window_s=600.0),
        # Serving fleet: the ServeMaster's reconcile loop mirrors the
        # router's per-route windows into untagged worst-case gauges
        # (serve_route_p99_ms_max / serve_route_error_rate_max); these
        # ceilings page when ANY route blows its latency or error budget
        # — e.g. replicas flapping faster than replacements spin up. Both
        # gauges read 0 with no serve instance running, so the rules are
        # inert outside serving jobs.
        SloRule("serve_route_p99", "ceiling", "serve_route_p99_ms_max",
                threshold=_env_f("RAY_TPU_SLO_SERVE_P99_MS", 2000.0),
                window_s=120.0),
        SloRule("serve_error_rate", "ceiling", "serve_route_error_rate_max",
                threshold=_env_f("RAY_TPU_SLO_SERVE_ERROR_RATE", 0.01),
                window_s=120.0),
    ]


class SloEngine:
    """Evaluates SLO rules against a ``get_timeseries`` payload and tracks
    firing state. Pure over its inputs (tests drive it with synthetic
    payloads and explicit ``now``); side effects are limited to the
    ``slo_*`` metric gauges."""

    def __init__(self, rules: Optional[Sequence[SloRule]] = None):
        self.rules = list(rules) if rules is not None \
            else default_slo_rules()
        self.active: Dict[str, float] = {}  # rule name -> firing since

    @staticmethod
    def _points(payload: Dict[str, Any], name: str) -> list:
        return (payload.get("series", {}).get(name) or {}).get("points", [])

    def _eval_rule(self, rule: SloRule, payload: Dict[str, Any],
                   now: float) -> Dict[str, Any]:
        out: Dict[str, Any] = {"rule": rule.name, "kind": rule.kind,
                               "threshold": rule.threshold,
                               "firing": False, "value": None}
        pts = self._points(payload, rule.series)
        since = now - rule.window_s
        if rule.kind == "floor":
            n = window_sum(pts, since)
            if n < rule.min_count:
                return out  # idle window: the floor doesn't apply
            rate = window_rate(pts, since, now)
            out["value"] = round(rate, 3)
            out["firing"] = rate < rule.threshold
            return out
        if rule.kind == "ceiling":
            if rule.quantile is not None:
                merged = merge_hist(
                    c for t, c in pts if t >= since)
                if merged["count"] < rule.min_count:
                    return out
                q = quantile_from_hist(merged, rule.quantile)
                if q is None:
                    return out
                out["value"] = q
                out["firing"] = q > rule.threshold
                return out
            gauge = [c for t, c in pts if t >= since]
            if not gauge:
                return out
            out["value"] = gauge[-1].get("last")
            out["firing"] = (out["value"] or 0.0) > rule.threshold
            return out
        if rule.kind == "gauge-floor":
            gauge = [c for t, c in pts if t >= since]
            if not gauge:
                return out  # gauge never produced: the floor can't apply
            out["value"] = gauge[-1].get("last")
            out["firing"] = (out["value"] or 0.0) < rule.threshold
            return out
        if rule.kind == "gauge-ceiling":
            vals = gauge_window(pts, since)
            if not vals or len(vals) < rule.min_count:
                return out  # no/too few samples: can't claim "sustained"
            # Sustained = the BEST bucket of the window still breaches.
            out["value"] = min(vals)
            out["firing"] = out["value"] > rule.threshold
            return out
        # burn: bad fraction vs budget over short AND long windows.
        total_pts = self._points(payload, rule.total_series)
        burns = []
        for win in (rule.window_s, rule.long_window_s):
            w_since = now - win
            bad = window_sum(pts, w_since)
            total = window_sum(total_pts, w_since) + bad
            if total < rule.min_count:
                out["value"] = 0.0
                return out  # too little traffic to burn meaningfully
            burns.append((bad / total) / max(rule.budget, 1e-9))
        out["value"] = round(burns[0], 3)
        out["burn_long"] = round(burns[1], 3)
        out["firing"] = all(b > rule.burn_threshold for b in burns)
        return out

    def evaluate(self, payload: Dict[str, Any],
                 now: Optional[float] = None) -> Dict[str, Any]:
        """One pass over every rule. Returns {"results": [...],
        "fired": [names], "resolved": [names]} — the transitions the
        caller turns into ``slo_*`` cluster events."""
        if now is None:
            now = time.time()
        results, fired, resolved = [], [], []
        metrics = self._metrics()
        for rule in self.rules:
            try:
                res = self._eval_rule(rule, payload, now)
            except Exception as e:  # noqa: BLE001 - one bad rule != outage
                res = {"rule": rule.name, "kind": rule.kind,
                       "firing": False, "value": None,
                       "error": f"{type(e).__name__}: {e}"}
            results.append(res)
            was = rule.name in self.active
            if res["firing"] and not was:
                self.active[rule.name] = now
                fired.append(rule.name)
            elif not res["firing"] and was:
                del self.active[rule.name]
                resolved.append(rule.name)
            if metrics is not None:
                tags = {"rule": rule.name}
                metrics["evaluations"].record(1.0, tags=tags)
                metrics["active"].record(
                    1.0 if res["firing"] else 0.0, tags=tags)
                if rule.kind == "burn" and res.get("value") is not None:
                    metrics["burn"].record(float(res["value"]), tags=tags)
        return {"results": results, "fired": fired, "resolved": resolved}

    @staticmethod
    def _metrics():
        try:
            from .metrics import slo_metrics

            return slo_metrics()
        except Exception:  # noqa: BLE001 - metrics must never fail rules
            return None


class Monitor:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 autoscaler_config: Optional[Dict[str, Any]] = None,
                 update_interval_s: float = 1.0):
        host, port = gcs_address.rsplit(":", 1)
        self.gcs = RpcClient(host, int(port))
        self.load_metrics = LoadMetrics()
        self.autoscaler = StandardAutoscaler(
            provider, self.load_metrics, autoscaler_config,
            drain_fn=self._drain_node)
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_updates = 0
        # Pending placement groups from the last poll, with when each was
        # first seen pending — feeds the stuck-gang report.
        self._pg_pending_since: Dict[str, float] = {}
        self._pg_report_last = 0.0
        self.pg_table: Dict[str, Dict[str, Any]] = {}
        # SLO rule engine over the GCS time-series rollups; evaluated on
        # its own (slower) cadence since the rollup buckets are 10 s wide.
        self.slo_engine = SloEngine()
        self.slo_results: List[Dict[str, Any]] = []
        self._slo_last = 0.0
        self.slo_interval_s = 10.0

    def _drain_node(self, provider_node_id: str) -> bool:
        """Autoscaler scale-down hook: start (or check) a graceful drain of
        the GCS node backing this provider node. Returns True once the
        node has fully retired (or was never registered), so the
        autoscaler can terminate the provider instance; False while the
        drain is still in progress."""
        nodes = self.gcs.call({"type": "list_nodes"})["nodes"]
        row = next((n for n in nodes
                    if (n.get("Label") or n["NodeID"]) == provider_node_id),
                   None)
        if row is None or not row["Alive"]:
            return True  # never joined, or already retired
        if not row.get("Draining"):
            self.gcs.call({"type": "drain_node", "node_id": row["NodeID"]})
        return False

    def poll_once(self) -> None:
        nodes = self.gcs.call({"type": "list_nodes"})["nodes"]
        seen = set()
        for n in nodes:
            # Autoscaler-launched nodes carry their provider node id as the
            # GCS label; keying LoadMetrics by it puts idle_ips() in the
            # same namespace as provider.internal_ip() so idle termination
            # actually matches. Head/manual nodes fall back to the NodeID.
            key = n.get("Label") or n["NodeID"]
            if not n["Alive"]:
                self.load_metrics.mark_dead(key)
                continue
            seen.add(key)
            self.load_metrics.update(key, n["Resources"], n["Available"])
        for ip in list(self.load_metrics.static_resources):
            if ip not in seen:
                self.load_metrics.mark_dead(ip)
        resp = self.gcs.call({"type": "pending_demands"})
        self.load_metrics.set_pending_demands(resp["demands"])
        # Pending gangs are atomic demand units for the scaler.
        self.load_metrics.set_pending_placement_groups(
            resp.get("pg_demands", []))
        try:
            self.pg_table = self.gcs.call(
                {"type": "list_placement_groups"})["groups"]
        except (KeyError, ConnectionError, OSError):
            self.pg_table = {}
        now = time.monotonic()
        pending_ids = set()
        for pg_hex, info in self.pg_table.items():
            if info.get("state") in ("PENDING", "RESCHEDULING"):
                pending_ids.add(pg_hex)
                self._pg_pending_since.setdefault(pg_hex, now)
        for pg_hex in list(self._pg_pending_since):
            if pg_hex not in pending_ids:
                del self._pg_pending_since[pg_hex]

    def stuck_placement_groups(self, min_pending_s: float = 10.0
                               ) -> Dict[str, Dict[str, Any]]:
        """Gangs stuck un-created past ``min_pending_s``, with the reason
        the GCS classified: "infeasible" (the fleet can never hold the
        gang — new/bigger nodes needed) vs "waiting-for-capacity"
        (running work must drain first)."""
        now = time.monotonic()
        out: Dict[str, Dict[str, Any]] = {}
        for pg_hex, since in self._pg_pending_since.items():
            if now - since < min_pending_s:
                continue
            info = self.pg_table.get(pg_hex, {})
            out[pg_hex] = {
                "pending_s": round(now - since, 1),
                "state": info.get("state", "PENDING"),
                "reason": info.get("reason", ""),
                "strategy": info.get("strategy", ""),
                "bundles": info.get("bundles", []),
            }
        return out

    def poll_slo_once(self) -> None:
        """Evaluate the SLO rules against the latest rollups; emit
        ``slo_fired``/``slo_resolved`` cluster events on transitions (the
        gauge side lives in the engine)."""
        try:
            payload = self.gcs.call({"type": "get_timeseries", "last": 200})
        except (KeyError, ConnectionError, OSError):
            return
        verdict = self.slo_engine.evaluate(payload)
        self.slo_results = verdict["results"]
        by_rule = {r["rule"]: r for r in verdict["results"]}
        for kind_key, names in (("slo_fired", verdict["fired"]),
                                ("slo_resolved", verdict["resolved"])):
            for name in names:
                res = by_rule.get(name, {})
                if kind_key == "slo_fired":
                    logger.warning(
                        "SLO rule %s firing: value=%s threshold=%s",
                        name, res.get("value"), res.get("threshold"))
                try:
                    self.gcs.send_oneway({
                        "type": "log_event", "kind": kind_key,
                        "rule": name, "value": res.get("value"),
                        "threshold": res.get("threshold")})
                except (ConnectionError, OSError):
                    pass

    def update(self) -> None:
        self.poll_once()
        self.autoscaler.update()
        self.num_updates += 1
        if time.monotonic() - self._slo_last > self.slo_interval_s:
            self._slo_last = time.monotonic()
            self.poll_slo_once()
        stuck = self.stuck_placement_groups()
        if stuck and time.monotonic() - self._pg_report_last > 30.0:
            self._pg_report_last = time.monotonic()
            for pg_hex, info in stuck.items():
                logger.warning(
                    "placement group %s stuck %s for %.0fs (%s): %s x%d",
                    pg_hex[:12], info["state"], info["pending_s"],
                    info["reason"] or "unknown", info["strategy"],
                    len(info["bundles"]))

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except (ConnectionError, OSError):
                break  # GCS gone: head is shutting down
            self._stop.wait(self.update_interval_s)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name="monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.gcs.close()
