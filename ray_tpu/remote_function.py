"""@remote functions.

Reference: ``python/ray/remote_function.py`` — a decorated function becomes a
RemoteFunction whose ``.remote(*args)`` builds a TaskSpec and submits it;
``.options(...)`` overrides resources/returns per-call site.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

from ._private.ids import ObjectID
from ._private.resources import ResourceSet
from ._private.task_spec import FunctionDescriptor, TaskSpec, TaskType
from ._private.worker import global_worker
from .object_ref import ObjectRef


class RemoteFunction:
    def __init__(self, fn: Callable, *, num_returns: int = 1,
                 num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 max_retries: Optional[int] = None, name: Optional[str] = None):
        self._function = fn
        self._name = name or getattr(fn, "__qualname__", repr(fn))
        self._module = getattr(fn, "__module__", "__main__")
        self._num_returns = num_returns
        res = dict(resources or {})
        res.setdefault("CPU", 1 if num_cpus is None else num_cpus)
        if num_tpus:
            res["TPU"] = num_tpus
        self._resources = ResourceSet.from_dict(res)
        self._max_retries = max_retries
        self._descriptor = FunctionDescriptor(self._module, self._name)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name} cannot be called directly; "
            f"use {self._name}.remote()."
        )

    def options(self, *, num_returns: Optional[int] = None,
                num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
                resources: Optional[Dict[str, float]] = None,
                max_retries: Optional[int] = None, name: Optional[str] = None,
                placement_group=None,
                placement_group_bundle_index: int = -1,
                timeout_s: Optional[float] = None,
                retry_on_timeout: bool = False):
        """Per-call-site overrides; returns a submit-only wrapper.

        ``placement_group`` pins the task into a reserved bundle: its
        demand is rewritten to the group-scoped resource names, so it can
        only run on the bundle's node, consuming the bundle's reservation
        (``placement_group_bundle_index=-1`` = any bundle of the group).

        ``timeout_s`` sets an execution deadline: the controller kills the
        task (SIGTERM, then SIGKILL) once it has run that long and the ref
        resolves to ``TaskTimeoutError``. Deadline kills don't consume
        ``max_retries`` unless ``retry_on_timeout=True``."""
        parent = self

        class _Options:
            def remote(self, *args, **kwargs):
                return parent._remote(
                    args, kwargs,
                    num_returns=num_returns, num_cpus=num_cpus, num_tpus=num_tpus,
                    resources=resources, max_retries=max_retries, name=name,
                    placement_group=placement_group,
                    placement_group_bundle_index=placement_group_bundle_index,
                    timeout_s=timeout_s, retry_on_timeout=retry_on_timeout,
                )

        return _Options()

    def remote(self, *args, **kwargs) -> Any:
        return self._remote(args, kwargs)

    def _remote(self, args, kwargs, *, num_returns=None, num_cpus=None,
                num_tpus=None, resources=None, max_retries=None, name=None,
                placement_group=None, placement_group_bundle_index=-1,
                timeout_s=None, retry_on_timeout=False):
        worker = global_worker()
        worker.check_connected()
        core = worker.core
        from ._private.config import get_config

        if num_cpus is not None or num_tpus is not None or resources is not None:
            res = dict(resources or {})
            res.setdefault("CPU", 1 if num_cpus is None else num_cpus)
            if num_tpus:
                res["TPU"] = num_tpus
            resource_set = ResourceSet.from_dict(res)
        else:
            resource_set = self._resources
        if placement_group is not None:
            resource_set = ResourceSet.from_dict(
                placement_group.translated_resources(
                    resource_set.to_dict(), placement_group_bundle_index))

        task_id = core.next_task_id()
        spec = TaskSpec(
            task_id=task_id,
            job_id=core.job_id,
            task_type=TaskType.NORMAL_TASK,
            function=self._descriptor,
            args=[_pack_arg(a) for a in args],
            num_returns=num_returns if num_returns is not None else self._num_returns,
            resources=resource_set,
            max_retries=(
                max_retries if max_retries is not None
                else (self._max_retries if self._max_retries is not None
                      else get_config().max_retries_default)
            ),
            name=name or self._name,
            metadata={"kwargs": kwargs} if kwargs else {},
            placement_group_id=(placement_group.id
                                if placement_group is not None else None),
            placement_group_bundle_index=placement_group_bundle_index,
            timeout_s=timeout_s,
            retry_on_timeout=retry_on_timeout,
        )
        refs = core.submit_task(self._function, spec)
        if spec.num_returns == 1:
            return refs[0]
        return refs


def _pack_arg(arg):
    if isinstance(arg, ObjectRef):
        return ("ref", arg.id)
    return ("value", arg)


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_returns=...)`` decorator.

    Dispatches to RemoteFunction for functions and ActorClass for classes
    (reference: python/ray/worker.py:1799 make_decorator).

    Export semantics (cluster mode): a function object is pickled and
    exported ONCE, on its first submission — the same as the reference's
    export-at-decoration (python/ray/function_manager.py). Mutating a
    captured global/closure cell after the first ``.remote()`` call does NOT
    re-export; cluster workers keep executing the first-export snapshot.
    Re-decorate (or define a new function) to ship new captured state.
    """
    from .actor import ActorClass

    def make(target):
        if isinstance(target, type):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword arguments only")
    return make
