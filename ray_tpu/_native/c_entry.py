"""JSON bridge behind the C frontend (ray_tpu/_native/src/capi.cc).

Reference counterpart: the runtime glue under cpp/src/ray/runtime/ that
backs cpp/include/ray/api.h. The C library embeds CPython and calls these
helpers with plain strings; every value crossing the C boundary is JSON, so
non-Python callers never see pickles.

Refs handed to C are tracked here (hex -> ObjectRef) both to keep the
distributed refcount alive while C holds the handle and so get/wait can
resolve hexes without re-deriving ownership.
"""

from __future__ import annotations

import importlib
import json
from typing import Dict

import ray_tpu
from ray_tpu.object_ref import ObjectRef

_refs: Dict[str, ObjectRef] = {}
_actors: Dict[str, object] = {}  # actor id hex -> ActorHandle


def _track(ref: ObjectRef) -> str:
    h = ref.hex()
    _refs[h] = ref
    return h


def _resolve_arg_refs(value):
    """Recursively replace {"__ref__": "<hex>"} markers with live
    ObjectRefs so a C caller can chain tasks/actor calls on stored
    objects (arrays included) without pulling them through JSON."""
    if isinstance(value, dict):
        if set(value.keys()) == {"__ref__"}:
            return _resolve(value["__ref__"])
        return {k: _resolve_arg_refs(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_resolve_arg_refs(v) for v in value]
    return value


def _resolve(ref_hex: str) -> ObjectRef:
    ref = _refs.get(ref_hex)
    if ref is None:
        # The C client only holds hexes it got from put/submit here:
        # unknown means released (use-after-release) or corrupted — fail
        # fast instead of fabricating an owner-less ref that would silently
        # re-pin the object and can hang a get until timeout.
        raise KeyError(f"unknown or released ref {ref_hex!r}")
    return ref


def init(address: str) -> bool:
    if address:
        ray_tpu.init(address=address)
    else:
        ray_tpu.init()
    return True


def shutdown() -> bool:
    _refs.clear()
    _actors.clear()
    ray_tpu.shutdown()
    return True


def put_json(payload: str) -> str:
    return _track(ray_tpu.put(json.loads(payload)))


def get_json(ref_hex: str, timeout: float) -> str:
    value = ray_tpu.get(_resolve(ref_hex),
                        timeout=None if timeout <= 0 else timeout)
    return json.dumps(value)


def submit(entrypoint: str, args_json: str, num_cpus: float) -> str:
    """entrypoint = "module:function", importable on the workers (functions
    pickle by reference, so any installed module works — e.g.
    "operator:add")."""
    mod_name, sep, fn_name = entrypoint.partition(":")
    if not sep or not mod_name or not fn_name:
        raise ValueError(
            f"entrypoint must be 'module:function', got {entrypoint!r}")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    if num_cpus and num_cpus > 0:
        remote_fn = ray_tpu.remote(num_cpus=num_cpus)(fn)
    else:
        remote_fn = ray_tpu.remote(fn)
    args = _resolve_arg_refs(json.loads(args_json))
    return _track(remote_fn.remote(*args))


def release(ref_hex: str) -> bool:
    """Drop the C side's handle; the ObjectRef's __del__ decrements the
    distributed refcount. Long-running C clients call this per finished
    ref or the results stay pinned cluster-wide until shutdown."""
    return _refs.pop(ref_hex, None) is not None


def wait(refs_json: str, num_returns: int, timeout: float) -> int:
    refs = [_resolve(h) for h in json.loads(refs_json)]
    ready, _ = ray_tpu.wait(
        refs, num_returns=num_returns,
        timeout=None if timeout <= 0 else timeout)
    return len(ready)


# ---------------------------------------------------------------- actors
def actor_create(entrypoint: str, args_json: str, num_cpus: float) -> str:
    """entrypoint = "module:Class" importable on the workers (reference:
    the typed actor factories of cpp/include/ray/api.h; here the class IS
    the factory)."""
    mod_name, sep, cls_name = entrypoint.partition(":")
    if not sep or not mod_name or not cls_name:
        raise ValueError(
            f"entrypoint must be 'module:Class', got {entrypoint!r}")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    if not isinstance(cls, type):
        raise TypeError(f"{entrypoint!r} is not a class")
    if num_cpus and num_cpus > 0:
        actor_cls = ray_tpu.remote(num_cpus=num_cpus)(cls)
    else:
        actor_cls = ray_tpu.remote(cls)
    args = _resolve_arg_refs(json.loads(args_json))
    handle = actor_cls.remote(*args)
    h = handle._actor_id.hex()
    _actors[h] = handle
    return h


def _actor(actor_hex: str):
    handle = _actors.get(actor_hex)
    if handle is None:
        raise KeyError(f"unknown or killed actor {actor_hex!r}")
    return handle


def actor_call(actor_hex: str, method: str, args_json: str) -> str:
    args = _resolve_arg_refs(json.loads(args_json))
    return _track(getattr(_actor(actor_hex), method).remote(*args))


def actor_kill(actor_hex: str) -> bool:
    ray_tpu.kill(_actor(actor_hex))
    del _actors[actor_hex]
    return True


# ------------------------------------------------------- array buffers
def put_buffer(view, dtype: str, shape_json: str) -> str:
    """view: a C-memory memoryview (zero-copy from the caller's pointer);
    the np.frombuffer wrap is also zero-copy — the single copy is the
    object-store write inside put()."""
    import numpy as np

    shape = json.loads(shape_json)
    # copy(): in local mode put() stores the object by reference, and an
    # aliasing array would dangle the moment the C caller frees or reuses
    # its buffer (the header promises the buffer is not retained).
    arr = np.frombuffer(view, dtype=np.dtype(dtype)).reshape(shape).copy()
    return _track(ray_tpu.put(arr))


def get_array(ref_hex: str, timeout: float):
    """Returns a C-contiguous ndarray for capi.cc to expose through the
    buffer protocol (scalars become 0-d arrays)."""
    import numpy as np

    value = ray_tpu.get(_resolve(ref_hex),
                        timeout=None if timeout <= 0 else timeout)
    arr = np.asarray(value)
    if not arr.flags["C_CONTIGUOUS"]:
        # ascontiguousarray only when needed: it would promote 0-d
        # scalars to shape (1,), losing the rank.
        arr = np.ascontiguousarray(arr)
    return arr
