"""JSON bridge behind the C frontend (ray_tpu/_native/src/capi.cc).

Reference counterpart: the runtime glue under cpp/src/ray/runtime/ that
backs cpp/include/ray/api.h. The C library embeds CPython and calls these
helpers with plain strings; every value crossing the C boundary is JSON, so
non-Python callers never see pickles.

Refs handed to C are tracked here (hex -> ObjectRef) both to keep the
distributed refcount alive while C holds the handle and so get/wait can
resolve hexes without re-deriving ownership.
"""

from __future__ import annotations

import importlib
import json
from typing import Dict

import ray_tpu
from ray_tpu.object_ref import ObjectRef

_refs: Dict[str, ObjectRef] = {}


def _track(ref: ObjectRef) -> str:
    h = ref.hex()
    _refs[h] = ref
    return h


def _resolve(ref_hex: str) -> ObjectRef:
    ref = _refs.get(ref_hex)
    if ref is None:
        # The C client only holds hexes it got from put/submit here:
        # unknown means released (use-after-release) or corrupted — fail
        # fast instead of fabricating an owner-less ref that would silently
        # re-pin the object and can hang a get until timeout.
        raise KeyError(f"unknown or released ref {ref_hex!r}")
    return ref


def init(address: str) -> bool:
    if address:
        ray_tpu.init(address=address)
    else:
        ray_tpu.init()
    return True


def shutdown() -> bool:
    _refs.clear()
    ray_tpu.shutdown()
    return True


def put_json(payload: str) -> str:
    return _track(ray_tpu.put(json.loads(payload)))


def get_json(ref_hex: str, timeout: float) -> str:
    value = ray_tpu.get(_resolve(ref_hex),
                        timeout=None if timeout <= 0 else timeout)
    return json.dumps(value)


def submit(entrypoint: str, args_json: str, num_cpus: float) -> str:
    """entrypoint = "module:function", importable on the workers (functions
    pickle by reference, so any installed module works — e.g.
    "operator:add")."""
    mod_name, sep, fn_name = entrypoint.partition(":")
    if not sep or not mod_name or not fn_name:
        raise ValueError(
            f"entrypoint must be 'module:function', got {entrypoint!r}")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    if num_cpus and num_cpus > 0:
        remote_fn = ray_tpu.remote(num_cpus=num_cpus)(fn)
    else:
        remote_fn = ray_tpu.remote(fn)
    return _track(remote_fn.remote(*json.loads(args_json)))


def release(ref_hex: str) -> bool:
    """Drop the C side's handle; the ObjectRef's __del__ decrements the
    distributed refcount. Long-running C clients call this per finished
    ref or the results stay pinned cluster-wide until shutdown."""
    return _refs.pop(ref_hex, None) is not None


def wait(refs_json: str, num_returns: int, timeout: float) -> int:
    refs = [_resolve(h) for h in json.loads(refs_json)]
    ready, _ = ray_tpu.wait(
        refs, num_returns=num_returns,
        timeout=None if timeout <= 0 else timeout)
    return len(ready)
