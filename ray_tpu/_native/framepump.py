"""ctypes wrapper for the native frame pump (framepump.cc).

The recv→frame and frame→wire inner loops of the cluster protocol
(``cluster/protocol.py``), moved into C so the per-frame byte-shuffling
runs with the GIL released and frames are delivered to Python in batches
(one call returning N bodies per wakeup) instead of 2+ ``recv`` calls and
a bytearray dance per frame. Python keeps everything semantic: magic-byte
dispatch, pickle fallback, chaos hooks, handlers.

Every hot entry point is ONE foreign call per wakeup into preallocated,
reusable buffers. The first cut of this wrapper paid 4 ctypes crossings
plus fresh ctypes array TYPES per frame batch (``c_char * total`` with a
varying total allocates a new class, ~10 µs, and grows an unbounded
type cache) — measured SLOWER than the pure-Python loops it replaced.

Three entry points, each gated on the g++-built library AND the
``RAY_TPU_NATIVE_FRAMEPUMP=0`` kill switch (pure-Python behavior is the
fallback, never an error):

  * :func:`reader_pump` — fd-owning blocking pump for ``RpcClient``'s
    reader thread (``None`` when the native path is off);
  * :func:`feed_framer` — feed-mode splitter for the asyncio ``RpcServer``
    (the event loop still owns the socket; native when available, else the
    byte-identical :class:`PyFeedFramer`);
  * :func:`sendv` — iovec scatter-gather ``sendmsg`` with IOV-cap
    continuation for ``RpcClient._send_buffers`` (returns False when the
    native path is off so the caller falls through to Python).
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import List, Optional, Sequence

from .build import load_native_library

_LEN = struct.Struct("<Q")

# Frames per take call (sizes arrays hold one extra slot: the C side
# reports leftover-frame count in sizes[taken]).
_TAKE_CAP = 512
# Initial reusable body buffer; grows by powers of two on demand, so the
# ctypes array-type cache sees a handful of sizes over a process life.
_DST_INIT = 256 * 1024

_SIZES_T = ctypes.c_uint64 * (_TAKE_CAP + 1)


class FrameError(Exception):
    """Protocol violation (oversize frame): the connection must drop."""


_lib = None
_lib_tried = False


def _load():
    """Build+dlopen once; None (cached) when the toolchain is missing."""
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        lib = load_native_library("framepump")
        if lib is not None:
            lib.fp_create.restype = ctypes.c_void_p
            lib.fp_create.argtypes = [ctypes.c_int, ctypes.c_uint64]
            lib.fp_destroy.argtypes = [ctypes.c_void_p]
            lib.fp_pump.restype = ctypes.c_int64
            lib.fp_pump.argtypes = [ctypes.c_void_p]
            lib.fp_feed.restype = ctypes.c_int64
            lib.fp_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
            lib.fp_pending_frames.restype = ctypes.c_uint64
            lib.fp_pending_frames.argtypes = [ctypes.c_void_p]
            lib.fp_pending_bytes.restype = ctypes.c_uint64
            lib.fp_pending_bytes.argtypes = [ctypes.c_void_p]
            lib.fp_take.restype = ctypes.c_int64
            lib.fp_take.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.c_uint64]
            lib.fp_pump_take.restype = ctypes.c_int64
            lib.fp_pump_take.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_uint64,
                                         ctypes.POINTER(ctypes.c_uint64),
                                         ctypes.c_uint64]
            lib.fp_feed_take.restype = ctypes.c_int64
            lib.fp_feed_take.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64, ctypes.c_void_p,
                                         ctypes.c_uint64,
                                         ctypes.POINTER(ctypes.c_uint64),
                                         ctypes.c_uint64]
            lib.fp_sendv.restype = ctypes.c_int
            lib.fp_sendv.argtypes = [ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_char_p),
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.c_uint64]
        _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def enabled() -> bool:
    """Kill switch (``RAY_TPU_NATIVE_FRAMEPUMP=0`` pins the Python path).
    Re-read per call — connections are rare, tests monkeypatch it."""
    if os.environ.get("RAY_TPU_NATIVE_FRAMEPUMP", "") in ("0",):
        return False
    return native_available()


def site_enabled(site: str) -> bool:
    """Per-site gate under the global kill switch: set
    ``RAY_TPU_NATIVE_FRAMEPUMP_SITES=pump,sendv`` to run only those
    native integration sites (A/B bisection of a perf or correctness
    suspicion without patching code). Default: every site."""
    if not enabled():
        return False
    sites = os.environ.get("RAY_TPU_NATIVE_FRAMEPUMP_SITES", "")
    if not sites:
        return True
    return site in {s.strip() for s in sites.split(",")}


class _PumpBase:
    """Shared batch-take over one native pump handle. NOT thread-safe:
    one pumping thread per handle, destroy only after it exits."""

    def __init__(self, fd: int, max_message: int):
        lib = _load()
        if lib is None:
            raise ImportError("native framepump library unavailable")
        self._lib = lib
        self._h = lib.fp_create(fd, max_message)
        if not self._h:
            raise MemoryError("framepump allocation failed")
        self._cap = _DST_INIT
        self._dst = ctypes.create_string_buffer(self._cap)
        self._mv = memoryview(self._dst)
        self._sizes = _SIZES_T()
        # Bound foreign functions: the hot methods make exactly one
        # attribute-free call per wakeup.
        self._pump_take = lib.fp_pump_take
        self._feed_take = lib.fp_feed_take

    def _grow(self) -> None:
        """Power-of-two growth toward the buffered bytes (big frames are
        rare — blobs ride the arena — so sizes stay few and cached)."""
        need = int(self._lib.fp_pending_bytes(self._h)) or self._cap * 2
        cap = self._cap
        while cap < need:
            cap *= 2
        self._cap = cap
        self._dst = ctypes.create_string_buffer(cap)
        self._mv = memoryview(self._dst)

    # raylint: hotpath — slices one take's bodies out of the shared buffer
    def _slice(self, n: int, out: List[bytes]) -> List[bytes]:
        mv = self._mv
        sizes = self._sizes
        off = 0
        for i in range(n):
            end = off + sizes[i]
            out.append(bytes(mv[off:end]))
            off = end
        return out

    def _drain_rest(self, out: List[bytes]) -> List[bytes]:
        """Rare overflow path: more frames buffered than one take could
        copy (cap overflow or > _TAKE_CAP frames)."""
        lib, h = self._lib, self._h
        while True:
            n = int(lib.fp_take(h, self._dst, self._cap, self._sizes,
                                _TAKE_CAP))
            if n == -1:  # first frame larger than the buffer
                self._grow()
                continue
            if n <= 0:
                return out
            self._slice(n, out)
            if not lib.fp_pending_frames(h):
                return out

    def close(self) -> None:
        if self._h:
            self._lib.fp_destroy(self._h)
            self._h = None


class NativeReaderPump(_PumpBase):
    """fd mode: the pump does the blocking recv (GIL released) and frame
    split; ``pump()`` returns one batch of frame bodies per wakeup."""

    # raylint: hotpath — the RpcClient reader thread's inner loop
    def pump(self) -> Optional[List[bytes]]:
        """One blocking wakeup: a non-empty batch of frame bodies, or
        None on EOF / socket error / oversize frame (drop the conn,
        matching the Python path)."""
        h = self._h
        if not h:
            return None
        n = self._pump_take(h, self._dst, self._cap, self._sizes,
                            _TAKE_CAP)
        if n >= 0:
            out = self._slice(n, [])
            if self._sizes[n]:
                return self._drain_rest(out)
            return out
        if n == -3:  # frame bigger than the reusable buffer
            self._grow()
            return self._drain_rest([])
        return None

    # fp_pump/fp_take kept callable for tests and diagnostics.


class NativeFeedFramer(_PumpBase):
    """feed mode for the asyncio server: the event loop reads in bulk and
    feeds chunks; complete frames come back per feed."""

    def __init__(self, max_message: int):
        super().__init__(-1, max_message)

    # raylint: hotpath — every inbound server byte funnels through here
    def feed(self, data: bytes) -> List[bytes]:
        h = self._h
        if not h:
            raise FrameError("framer closed")
        n = self._feed_take(h, data, len(data), self._dst, self._cap,
                            self._sizes, _TAKE_CAP)
        if n > 0:
            out = self._slice(n, [])
            if self._sizes[n]:
                return self._drain_rest(out)
            return out
        if n == 0:
            return []
        if n == -3:  # frame bigger than the reusable buffer
            self._grow()
            return self._drain_rest([])
        raise FrameError("message too large")


class PyFeedFramer:
    """Pure-Python twin of :class:`NativeFeedFramer` — byte-identical
    split semantics (the equivalence fuzz in test_wire_codec pins this),
    used when the native library is unavailable or killed."""

    def __init__(self, max_message: int):
        self._buf = bytearray()
        self._max = max_message

    # raylint: hotpath — the fallback server framer
    def feed(self, data: bytes) -> List[bytes]:
        buf = self._buf
        buf += data
        out: List[bytes] = []
        off = 0
        n = len(buf)
        while n - off >= 8:
            (length,) = _LEN.unpack_from(buf, off)
            if length > self._max:
                raise FrameError("message too large")
            if n - off - 8 < length:
                break
            out.append(bytes(buf[off + 8:off + 8 + length]))
            off += 8 + length
        if off:
            del buf[:off]
        return out

    def close(self) -> None:
        self._buf.clear()


def reader_pump(fd: int, max_message: int) -> Optional[NativeReaderPump]:
    """fd-owning pump for a blocking reader thread, or None when the
    native path is off (caller keeps its per-frame Python loop)."""
    if not site_enabled("pump"):
        return None
    try:
        return NativeReaderPump(fd, max_message)
    except (ImportError, MemoryError):
        return None


def feed_framer(max_message: int):
    """Framer for an asyncio bulk-read loop: native when available,
    Python otherwise — the caller never branches."""
    if site_enabled("feed"):
        try:
            return NativeFeedFramer(max_message)
        except (ImportError, MemoryError):
            pass
    return PyFeedFramer(max_message)


# Reusable per-thread sendv scratch: pointer + length arrays built ONCE
# (fresh `(c_char_p * n)(*bufs)` per call re-created ctypes array types
# for every new n). Per-thread because concurrent clients send in
# parallel; each RpcClient serializes its own sends under _wlock.
_SEND_CAP = 1024
_send_tls = threading.local()
# Below this buffer count the pure-Python socket.sendmsg path wins (it
# is C inside CPython and pays no per-call env check, scratch fill, or
# foreign-call overhead; measured crossover ~300-500 on the CI box) —
# sendv declines so the caller falls through. Native absorbs the big
# scatter waves: dispatch fan-outs and coalesced task_done batches.
_SENDV_MIN = 256


# raylint: hotpath — every large scatter wave a client sends funnels here
def sendv(fd: int, bufs: Sequence[bytes]) -> bool:
    """Scatter-gather sendmsg of ``bufs`` over blocking ``fd`` with the
    GIL released and IOV-cap continuation in C. False when the list is
    below the native win threshold or the native path is off (caller
    falls back); OSError on a send failure, matching socket.sendmsg."""
    total = len(bufs)
    if total < _SENDV_MIN:
        return False
    if not site_enabled("sendv"):
        return False
    lib = _load()
    scratch = getattr(_send_tls, "arrs", None)
    if scratch is None:
        scratch = _send_tls.arrs = (
            (ctypes.c_char_p * _SEND_CAP)(),
            (ctypes.c_uint64 * _SEND_CAP)())
    ptrs, lens = scratch
    fp_sendv = lib.fp_sendv
    i = 0
    while i < total:
        m = min(total - i, _SEND_CAP)
        for j in range(m):
            b = bufs[i + j]
            if type(b) is not bytes:
                # ctypes c_char_p wants real bytes; encoders only emit
                # bytes today (guards a future bytearray/memoryview buf).
                b = bytes(b)
            ptrs[j] = b
            lens[j] = len(b)
        if fp_sendv(fd, ptrs, lens, m) != 0:
            raise OSError("sendv failed")
        i += m
    return True
