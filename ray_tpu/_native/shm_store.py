"""Python view of the C++ shared-memory object store.

Zero-copy contract: the C++ library (shm_store.cc) owns layout, locking and
eviction; this wrapper keeps its *own* mmap of the same ``/dev/shm`` segment
and turns the offsets the library returns into memoryviews, so neither puts
nor gets copy object bytes through a socket or the allocator.

Reference counterpart: plasma client API
(src/ray/object_manager/plasma/client.h) — create/seal/get/release/delete
with pinned buffers; here a `get` returns a context-managed pinned view.

Falls back to `PyObjectStore` (same interface, plain dicts, single-process)
when the native library cannot be built.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
from typing import Dict, List, Optional

from .build import load_native_library

ID_LEN = 24  # == ObjectID.SIZE

_OK = 0
_NOT_FOUND = -1
_OOM = -2
_NOT_SEALED = -3
_EXISTS = -4
_IN_USE = -5


class StoreFullError(Exception):
    """The arena cannot fit the object even after evicting everything idle."""


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64 = ctypes.c_uint64
    p_u64 = ctypes.POINTER(u64)
    buf = ctypes.c_char_p
    lib.tps_create.restype = ctypes.c_void_p
    lib.tps_create.argtypes = [ctypes.c_char_p, u64]
    lib.tps_open.restype = ctypes.c_void_p
    lib.tps_open.argtypes = [ctypes.c_char_p]
    lib.tps_close.argtypes = [ctypes.c_void_p]
    lib.tps_unlink.argtypes = [ctypes.c_char_p]
    lib.tps_create_obj.argtypes = [ctypes.c_void_p, buf, u64, p_u64]
    lib.tps_seal.argtypes = [ctypes.c_void_p, buf]
    lib.tps_abort.argtypes = [ctypes.c_void_p, buf]
    lib.tps_put.argtypes = [ctypes.c_void_p, buf, ctypes.c_char_p, u64]
    lib.tps_get.argtypes = [ctypes.c_void_p, buf, p_u64, p_u64]
    lib.tps_release.argtypes = [ctypes.c_void_p, buf]
    lib.tps_contains.argtypes = [ctypes.c_void_p, buf]
    lib.tps_delete.argtypes = [ctypes.c_void_p, buf]
    lib.tps_stats.argtypes = [ctypes.c_void_p, p_u64]
    lib.tps_list.restype = ctypes.c_int
    lib.tps_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    return lib


def _pad_id(object_id: bytes) -> bytes:
    if len(object_id) == ID_LEN:
        return object_id
    return object_id[:ID_LEN].ljust(ID_LEN, b"\0")


class PinnedBuffer:
    """A pinned, zero-copy view of a sealed object. Use as a context manager
    (or call .release()) so eviction/delete can reclaim the space."""

    __slots__ = ("store", "object_id", "view", "_released")

    def __init__(self, store: "ShmObjectStore", object_id: bytes,
                 view: memoryview):
        self.store = store
        self.object_id = object_id
        self.view = view
        self._released = False

    def __enter__(self) -> memoryview:
        return self.view

    def __exit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.view.release()
            self.store._release(self.object_id)

    def tobytes(self) -> bytes:
        return bytes(self.view)

    def __len__(self) -> int:
        return len(self.view)


class ShmObjectStore:
    """One node's shared-memory object arena (create via create=True once per
    node; workers attach with create=False)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        lib = load_native_library("shm_store")
        if lib is None:
            raise OSError("native shm_store library unavailable")
        self._lib = _bind(lib)
        self.name = name
        self._owner = create
        cname = name.encode()
        if create:
            self._handle = self._lib.tps_create(cname, capacity)
        else:
            self._handle = self._lib.tps_open(cname)
        if not self._handle:
            raise OSError(f"could not {'create' if create else 'open'} "
                          f"shm store {name!r}")
        # Private mapping of the same segment for zero-copy views.
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._mmap = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mmap)
        self._lock = threading.Lock()
        self._closed = False

    # -- write ----------------------------------------------------------------
    def put(self, object_id: bytes, data) -> bool:
        """Stores an immutable object. Returns False if it already exists.
        Raises StoreFullError when the arena can't fit it."""
        object_id = _pad_id(object_id)
        data = memoryview(data).cast("B") if not isinstance(data, bytes) else data
        size = len(data)
        off = ctypes.c_uint64()
        rc = self._lib.tps_create_obj(self._handle, object_id, size,
                                      ctypes.byref(off))
        if rc == _EXISTS:
            return False
        if rc == _OOM:
            raise StoreFullError(
                f"object of {size} bytes does not fit in store {self.name!r}")
        if rc != _OK:
            raise OSError(f"create_obj failed: rc={rc}")
        self._mv[off.value:off.value + size] = data
        self._lib.tps_seal(self._handle, object_id)
        return True

    def create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        """Two-phase create: returns a writable view (or None if the object
        exists); caller fills it and calls seal()."""
        object_id = _pad_id(object_id)
        off = ctypes.c_uint64()
        rc = self._lib.tps_create_obj(self._handle, object_id, size,
                                      ctypes.byref(off))
        if rc == _EXISTS:
            return None
        if rc == _OOM:
            raise StoreFullError(
                f"object of {size} bytes does not fit in store {self.name!r}")
        if rc != _OK:
            raise OSError(f"create_obj failed: rc={rc}")
        return self._mv[off.value:off.value + size]

    def seal(self, object_id: bytes) -> None:
        self._lib.tps_seal(self._handle, _pad_id(object_id))

    def abort(self, object_id: bytes) -> None:
        self._lib.tps_abort(self._handle, _pad_id(object_id))

    # -- read -----------------------------------------------------------------
    def get(self, object_id: bytes) -> Optional[PinnedBuffer]:
        """Returns a pinned zero-copy buffer, or None if absent/unsealed."""
        object_id = _pad_id(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.tps_get(self._handle, object_id, ctypes.byref(off),
                               ctypes.byref(size))
        if rc in (_NOT_FOUND, _NOT_SEALED):
            return None
        if rc != _OK:
            raise OSError(f"get failed: rc={rc}")
        view = self._mv[off.value:off.value + size.value]
        return PinnedBuffer(self, object_id, view)

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        buf = self.get(object_id)
        if buf is None:
            return None
        try:
            return buf.tobytes()
        finally:
            buf.release()

    def get_bytes_many(self, object_ids) -> Dict[bytes, bytes]:
        """Batched probe: {id: bytes} for every sealed id found. One pair
        of reused ctypes out-params across the whole loop — the per-call
        marshalling allocations dominated large miss-heavy scans (a 5k-ref
        driver harvest probes every pending id per wake)."""
        out: Dict[bytes, bytes] = {}
        lib, handle, mv = self._lib, self._handle, self._mv
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        boff, bsize = ctypes.byref(off), ctypes.byref(size)
        for oid in object_ids:
            pid = _pad_id(oid)
            rc = lib.tps_get(handle, pid, boff, bsize)
            if rc in (_NOT_FOUND, _NOT_SEALED):
                continue
            if rc != _OK:
                raise OSError(f"get failed: rc={rc}")
            try:
                out[oid] = bytes(mv[off.value:off.value + size.value])
            finally:
                lib.tps_release(handle, pid)
        return out

    def contains(self, object_id: bytes) -> bool:
        return self._lib.tps_contains(self._handle, _pad_id(object_id)) == 1

    def _release(self, object_id: bytes) -> None:
        if not self._closed:
            self._lib.tps_release(self._handle, object_id)

    # -- manage ---------------------------------------------------------------
    def delete(self, object_id: bytes) -> None:
        self._lib.tps_delete(self._handle, _pad_id(object_id))

    def list_ids(self, max_ids: int = 1 << 16) -> List[bytes]:
        out = ctypes.create_string_buffer(max_ids * ID_LEN)
        n = self._lib.tps_list(self._handle, out, max_ids)
        raw = out.raw
        return [raw[i * ID_LEN:(i + 1) * ID_LEN] for i in range(max(n, 0))]

    def stats(self) -> Dict[str, int]:
        arr = (ctypes.c_uint64 * 6)()
        self._lib.tps_stats(self._handle, arr)
        return {
            "num_objects": arr[0], "used_bytes": arr[1],
            "arena_bytes": arr[2], "num_evictions": arr[3],
            "table_slots": arr[4], "capacity": arr[5],
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._mv.release()
        self._mmap.close()
        self._lib.tps_close(self._handle)
        self._handle = None
        if self._owner:
            self._lib.tps_unlink(self.name.encode())

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class PyObjectStore:
    """Pure-Python fallback with the ShmObjectStore interface (one process,
    no sharing — used only when the native build is impossible)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = True):
        self.name = name
        self.capacity = capacity or (1 << 30)
        self._objects: Dict[bytes, bytes] = {}
        self._pins: Dict[bytes, int] = {}
        self._order: List[bytes] = []
        self._used = 0
        self._evictions = 0
        self._lock = threading.Lock()

    def put(self, object_id: bytes, data) -> bool:
        object_id = _pad_id(object_id)
        data = bytes(data)
        with self._lock:
            if object_id in self._objects:
                return False
            while self._used + len(data) > self.capacity:
                victim = next((oid for oid in self._order
                               if not self._pins.get(oid)), None)
                if victim is None:
                    raise StoreFullError(f"{len(data)} bytes do not fit")
                self._order.remove(victim)
                self._used -= len(self._objects.pop(victim))
                self._evictions += 1
            self._objects[object_id] = data
            self._order.append(object_id)
            self._used += len(data)
            return True

    def create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        object_id = _pad_id(object_id)
        with self._lock:
            if object_id in self._objects:
                return None
        buf = bytearray(size)
        self._staging = (object_id, buf)
        return memoryview(buf)

    def seal(self, object_id: bytes) -> None:
        object_id = _pad_id(object_id)
        staged = getattr(self, "_staging", None)
        if staged and staged[0] == object_id:
            self.put(object_id, bytes(staged[1]))
            self._staging = None

    def abort(self, object_id: bytes) -> None:
        self._staging = None

    def get(self, object_id: bytes) -> Optional[PinnedBuffer]:
        object_id = _pad_id(object_id)
        with self._lock:
            data = self._objects.get(object_id)
            if data is None:
                return None
            self._pins[object_id] = self._pins.get(object_id, 0) + 1
        return PinnedBuffer(self, object_id, memoryview(data))

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        buf = self.get(object_id)
        if buf is None:
            return None
        try:
            return buf.tobytes()
        finally:
            buf.release()

    def get_bytes_many(self, object_ids) -> Dict[bytes, bytes]:
        """Batched probe (interface parity with ShmObjectStore)."""
        out: Dict[bytes, bytes] = {}
        with self._lock:
            for oid in object_ids:
                data = self._objects.get(_pad_id(oid))
                if data is not None:
                    out[oid] = data
        return out

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return _pad_id(object_id) in self._objects

    def _release(self, object_id: bytes) -> None:
        with self._lock:
            n = self._pins.get(object_id, 0)
            if n > 1:
                self._pins[object_id] = n - 1
            else:
                self._pins.pop(object_id, None)

    def delete(self, object_id: bytes) -> None:
        object_id = _pad_id(object_id)
        with self._lock:
            data = self._objects.pop(object_id, None)
            if data is not None:
                self._order.remove(object_id)
                self._used -= len(data)

    def list_ids(self, max_ids: int = 1 << 16) -> List[bytes]:
        with self._lock:
            return list(self._objects)[:max_ids]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._objects), "used_bytes": self._used,
                "arena_bytes": self.capacity, "num_evictions": self._evictions,
                "table_slots": 0, "capacity": self.capacity,
            }

    def close(self) -> None:
        self._objects.clear()


def create_store(name: str, capacity: int, spill_dir: Optional[str] = None,
                 high_watermark: float = 0.85, low_watermark: float = 0.60,
                 owner_quota: int = 0):
    """Creates a node store, preferring the native arena. With a
    ``spill_dir`` the store is wrapped in the spill policy
    (``_private/spill.SpillingStore``): memory pressure spills cold objects
    to disk instead of surfacing StoreFullError."""
    try:
        base = ShmObjectStore(name, capacity, create=True)
    except OSError:
        base = PyObjectStore(name, capacity)
    if spill_dir:
        from .._private.spill import SpillingStore, SpillManager

        try:
            return SpillingStore(base, SpillManager(spill_dir),
                                 high_watermark=high_watermark,
                                 low_watermark=low_watermark,
                                 owner_quota=owner_quota)
        except OSError:
            return base  # unwritable spill dir: degrade to arena-only
    return base


def open_store(name: str):
    """Attaches to an existing node store; None if unavailable (caller then
    falls back to RPC fetches)."""
    try:
        return ShmObjectStore(name, create=False)
    except OSError:
        return None
