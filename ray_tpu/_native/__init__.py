"""Native runtime components, built lazily with the system toolchain.

The C++ sources live in ``src/``; the first import compiles them with g++
into this directory (cached by source mtime). Anything that fails to build
falls back to a pure-Python implementation with the same interface, so the
framework always works — the native path is the fast path, not a hard dep.
"""

from .build import load_native_library  # noqa: F401
from .shm_store import (  # noqa: F401
    PyObjectStore,
    ShmObjectStore,
    StoreFullError,
    create_store,
    open_store,
)


def __getattr__(name):
    # Spill policy types re-exported lazily (they live in _private to keep
    # this package import-light; importing them eagerly would pull metrics
    # into every worker that only wants the raw arena).
    if name in ("SpillingStore", "SpillManager"):
        from .._private import spill

        return getattr(spill, name)
    raise AttributeError(name)
