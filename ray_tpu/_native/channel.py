"""ctypes wrapper for the native SPSC streaming channel (channel.cc).

Reference counterpart: streaming/python's DataWriter/DataReader over the
C++ channel layer. One writer process, one reader process per channel.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from .build import load_native_library


class ChannelClosed(Exception):
    """Writer closed and the ring is drained."""


class ChannelTimeout(Exception):
    pass


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = load_native_library("channel")
        if lib is None:
            raise ImportError("native channel library unavailable")
        lib.tch_create.restype = ctypes.c_void_p
        lib.tch_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.tch_open.restype = ctypes.c_void_p
        lib.tch_open.argtypes = [ctypes.c_char_p]
        lib.tch_write.restype = ctypes.c_int
        lib.tch_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64, ctypes.c_uint64]
        lib.tch_read.restype = ctypes.c_int64
        lib.tch_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64, ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.tch_pending_bytes.restype = ctypes.c_uint64
        lib.tch_pending_bytes.argtypes = [ctypes.c_void_p]
        lib.tch_mark_reader_dead.argtypes = [ctypes.c_void_p]
        lib.tch_reader_dead.restype = ctypes.c_int
        lib.tch_reader_dead.argtypes = [ctypes.c_void_p]
        lib.tch_total_messages.restype = ctypes.c_uint64
        lib.tch_total_messages.argtypes = [ctypes.c_void_p]
        lib.tch_close_write.argtypes = [ctypes.c_void_p]
        lib.tch_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
    return _lib


class ChannelWriter:
    def __init__(self, name: str, capacity: int = 8 * 1024 * 1024):
        lib = _load()
        self._lib = lib
        self._h = lib.tch_create(name.encode(), capacity)
        if not self._h:
            raise OSError(f"channel create failed: {name}")
        self.name = name

    def write(self, payload: bytes, timeout: Optional[float] = 30.0) -> None:
        if not self._h:
            raise ChannelClosed(self.name)  # guard: NULL into C segfaults
        rc = self._lib.tch_write(
            self._h, payload, len(payload),
            0 if timeout is None else int(timeout * 1000))
        if rc == 0:
            return
        if rc == -1:
            raise ChannelTimeout(f"ring full for {timeout}s: {self.name}")
        if rc == -2:
            raise ChannelClosed(self.name)
        raise ValueError(f"message larger than channel capacity: {self.name}")

    def pending_bytes(self) -> int:
        if not self._h:
            raise ChannelClosed(self.name)
        return self._lib.tch_pending_bytes(self._h)

    def reader_dead(self) -> bool:
        """Did the consumer declare it will never drain again?"""
        if not self._h:
            raise ChannelClosed(self.name)
        return bool(self._lib.tch_reader_dead(self._h))

    def close(self, unlink: bool = False) -> None:
        """Reader normally owns the unlink; pass unlink=True when no reader
        ever attached (failed handshake) so the segment doesn't leak."""
        if self._h:
            self._lib.tch_close_write(self._h)
            self._lib.tch_close(self._h, 1 if unlink else 0)
            self._h = None


class ChannelReader:
    def __init__(self, name: str, open_timeout: float = 30.0):
        import time

        lib = _load()
        self._lib = lib
        deadline = time.monotonic() + open_timeout
        self._h = lib.tch_open(name.encode())
        while not self._h and time.monotonic() < deadline:
            time.sleep(0.02)          # writer may not have created it yet
            self._h = lib.tch_open(name.encode())
        if not self._h:
            raise OSError(f"channel open timed out: {name}")
        self.name = name
        self._buf = ctypes.create_string_buffer(1 << 20)

    def read(self, timeout: Optional[float] = 30.0) -> bytes:
        if not self._h:
            raise ChannelClosed(self.name)  # guard a concurrent close()
        needed = ctypes.c_uint64(0)
        while True:
            n = self._lib.tch_read(
                self._h, self._buf, len(self._buf),
                0 if timeout is None else int(timeout * 1000),
                ctypes.byref(needed))
            if n >= 0:
                return self._buf.raw[:n]
            if n == -1:
                raise ChannelTimeout(self.name)
            if n == -2:
                raise ChannelClosed(self.name)
            # -3: grow the read buffer to the reported message size
            self._buf = ctypes.create_string_buffer(int(needed.value))

    def pending_bytes(self) -> int:
        if not self._h:
            raise ChannelClosed(self.name)
        return self._lib.tch_pending_bytes(self._h)

    def mark_dead(self) -> None:
        """Consumer error path: unblock a writer waiting on ring space by
        declaring this reader permanently gone."""
        if self._h:
            self._lib.tch_mark_reader_dead(self._h)

    def total_messages(self) -> int:
        if not self._h:
            raise ChannelClosed(self.name)
        return self._lib.tch_total_messages(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.tch_close(self._h, 1)  # reader owns the unlink
            self._h = None
