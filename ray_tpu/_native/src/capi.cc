// C frontend implementation: embeds CPython and delegates to the JSON
// bridge (ray_tpu/_native/c_entry.py). Public surface:
// ray_tpu/_native/include/ray_tpu_c.h.
//
// Reference counterpart: cpp/src/ray/runtime/ (the native runtime behind
// cpp/include/ray/api.h). The compute/runtime substrate here is the
// Python+jax worker stack, so the native API binds INTO it (CPython
// embedding) rather than re-implementing the client protocol; the C caller
// never sees Python objects — strings in, strings out.

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "ray_tpu_c.h"  // keep impl signatures pinned to the public ABI

namespace {

char g_err[4096] = "";
std::mutex g_init_mutex;

void set_error(const char *msg) {
  std::snprintf(g_err, sizeof(g_err), "%s", msg ? msg : "unknown error");
}

// Capture the pending Python exception into g_err (GIL held).
void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  const char *txt = "python error (unprintable)";
  PyObject *str = value ? PyObject_Str(value) : nullptr;
  if (str != nullptr) {
    const char *u = PyUnicode_AsUTF8(str);
    if (u != nullptr) txt = u;
  }
  std::snprintf(g_err, sizeof(g_err), "%s", txt);
  Py_XDECREF(str);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Call c_entry.<fn>(args...); returns a NEW reference or nullptr (error
// recorded). GIL must be held.
PyObject *call_bridge(const char *fn, PyObject *args) {
  if (args == nullptr && PyErr_Occurred()) {
    // A failed Py_BuildValue at the call site (e.g. non-UTF-8 input):
    // surface ITS error instead of calling the bridge with a pending
    // exception and zero args.
    set_error_from_python();
    return nullptr;
  }
  PyObject *mod = PyImport_ImportModule("ray_tpu._native.c_entry");
  if (mod == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *callable = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (callable == nullptr) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *out = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  Py_XDECREF(args);
  if (out == nullptr) set_error_from_python();
  return out;
}

// Copy a Python str result into a malloc'd C string.
char *steal_string(PyObject *obj) {
  if (obj == nullptr) return nullptr;
  const char *u = PyUnicode_AsUTF8(obj);
  char *out = nullptr;
  if (u != nullptr) {
    out = static_cast<char *>(std::malloc(std::strlen(u) + 1));
    if (out != nullptr) std::strcpy(out, u);
  } else {
    set_error_from_python();
  }
  Py_DECREF(obj);
  return out;
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace

extern "C" {

const char *ray_tpu_last_error(void) { return g_err; }

int ray_tpu_release(const char *ref_hex) {
  if (ref_hex == nullptr) {
    set_error("ref_hex must not be NULL");
    return -1;
  }
  Gil gil;
  PyObject *out = call_bridge("release", Py_BuildValue("(s)", ref_hex));
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

void ray_tpu_free(char *s) { std::free(s); }

int ray_tpu_init(const char *address) {
  {
    std::lock_guard<std::mutex> lock(g_init_mutex);
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);  // no signal handlers: the host app owns them
      // Release the GIL acquired by initialization so any thread
      // (including this one, via Gil below) can take it symmetrically.
      // The interpreter is deliberately never finalized: shutdown()
      // disconnects the runtime, but finalizing CPython under a loaded
      // jax/XLA runtime is not supported.
      PyEval_SaveThread();
    }
  }
  Gil gil;
  PyObject *out = call_bridge(
      "init", Py_BuildValue("(s)", address ? address : ""));
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

int ray_tpu_shutdown(void) {
  if (!Py_IsInitialized()) return 0;
  Gil gil;
  PyObject *out = call_bridge("shutdown", nullptr);
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

char *ray_tpu_put_json(const char *json) {
  if (json == nullptr) {
    set_error("json must not be NULL");
    return nullptr;
  }
  Gil gil;
  return steal_string(call_bridge("put_json", Py_BuildValue("(s)", json)));
}

char *ray_tpu_get_json(const char *ref_hex, double timeout_s) {
  if (ref_hex == nullptr) {
    set_error("ref_hex must not be NULL");
    return nullptr;
  }
  Gil gil;
  return steal_string(
      call_bridge("get_json", Py_BuildValue("(sd)", ref_hex, timeout_s)));
}

char *ray_tpu_submit_json(const char *entrypoint, const char *args_json,
                          double num_cpus) {
  if (entrypoint == nullptr || args_json == nullptr) {
    set_error("entrypoint/args_json must not be NULL");
    return nullptr;
  }
  Gil gil;
  return steal_string(call_bridge(
      "submit", Py_BuildValue("(ssd)", entrypoint, args_json, num_cpus)));
}

char *ray_tpu_actor_create(const char *entrypoint, const char *args_json,
                           double num_cpus) {
  if (entrypoint == nullptr || args_json == nullptr) {
    set_error("entrypoint/args_json must not be NULL");
    return nullptr;
  }
  Gil gil;
  return steal_string(call_bridge(
      "actor_create",
      Py_BuildValue("(ssd)", entrypoint, args_json, num_cpus)));
}

char *ray_tpu_actor_call_json(const char *actor_hex, const char *method,
                              const char *args_json) {
  if (actor_hex == nullptr || method == nullptr || args_json == nullptr) {
    set_error("actor_hex/method/args_json must not be NULL");
    return nullptr;
  }
  Gil gil;
  return steal_string(call_bridge(
      "actor_call", Py_BuildValue("(sss)", actor_hex, method, args_json)));
}

int ray_tpu_actor_kill(const char *actor_hex) {
  if (actor_hex == nullptr) {
    set_error("actor_hex must not be NULL");
    return -1;
  }
  Gil gil;
  PyObject *out =
      call_bridge("actor_kill", Py_BuildValue("(s)", actor_hex));
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

char *ray_tpu_put_buffer(const void *data, const char *dtype,
                         const long long *shape, int ndim) {
  if (data == nullptr || dtype == nullptr || shape == nullptr) {
    set_error("data/dtype/shape must not be NULL");
    return nullptr;
  }
  if (ndim < 0 || ndim > RAY_TPU_MAX_NDIM) {
    set_error("ndim out of range");
    return nullptr;
  }
  char shape_json[RAY_TPU_MAX_NDIM * 24 + 4];
  {
    size_t off = 0;
    shape_json[off++] = '[';
    for (int i = 0; i < ndim; i++) {
      int wrote = std::snprintf(shape_json + off, sizeof(shape_json) - off,
                                "%s%lld", i ? "," : "", shape[i]);
      if (wrote < 0 || off + wrote >= sizeof(shape_json) - 2) {
        set_error("shape too large");
        return nullptr;
      }
      off += wrote;
    }
    shape_json[off++] = ']';
    shape_json[off] = '\0';
  }
  Gil gil;
  // Resolve itemsize via numpy so the memoryview gets the exact length.
  PyObject *np = PyImport_ImportModule("numpy");
  if (np == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *dt = PyObject_CallMethod(np, "dtype", "(s)", dtype);
  Py_DECREF(np);
  if (dt == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *isz = PyObject_GetAttrString(dt, "itemsize");
  Py_DECREF(dt);
  if (isz == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  long long itemsize = PyLong_AsLongLong(isz);
  Py_DECREF(isz);
  long long nbytes = itemsize;
  for (int i = 0; i < ndim; i++) {
    if (shape[i] < 0) {
      set_error("negative dimension");
      return nullptr;
    }
    nbytes *= shape[i];
  }
  PyObject *view = PyMemoryView_FromMemory(
      const_cast<char *>(static_cast<const char *>(data)),
      static_cast<Py_ssize_t>(nbytes), PyBUF_READ);
  if (view == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  // call_bridge steals the args tuple; "N" steals view into it.
  return steal_string(call_bridge(
      "put_buffer", Py_BuildValue("(Nss)", view, dtype, shape_json)));
}

int ray_tpu_get_buffer(const char *ref_hex, double timeout_s,
                       ray_tpu_buffer *out) {
  if (ref_hex == nullptr || out == nullptr) {
    set_error("ref_hex/out must not be NULL");
    return -1;
  }
  std::memset(out, 0, sizeof(*out));
  Gil gil;
  PyObject *arr = call_bridge(
      "get_array", Py_BuildValue("(sd)", ref_hex, timeout_s));
  if (arr == nullptr) return -1;

  // dtype name
  PyObject *dt = PyObject_GetAttrString(arr, "dtype");
  PyObject *dtname = dt ? PyObject_GetAttrString(dt, "name") : nullptr;
  Py_XDECREF(dt);
  const char *dstr = dtname ? PyUnicode_AsUTF8(dtname) : nullptr;
  if (dstr == nullptr) {
    set_error_from_python();
    Py_XDECREF(dtname);
    Py_DECREF(arr);
    return -1;
  }
  std::snprintf(out->dtype, sizeof(out->dtype), "%s", dstr);
  Py_DECREF(dtname);

  // shape
  PyObject *shp = PyObject_GetAttrString(arr, "shape");
  if (shp == nullptr || !PyTuple_Check(shp) ||
      PyTuple_Size(shp) > RAY_TPU_MAX_NDIM) {
    set_error(shp ? "array rank exceeds RAY_TPU_MAX_NDIM"
                  : "array has no shape");
    Py_XDECREF(shp);
    Py_DECREF(arr);
    return -1;
  }
  out->ndim = static_cast<int>(PyTuple_Size(shp));
  for (int i = 0; i < out->ndim; i++) {
    out->shape[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
  }
  Py_DECREF(shp);

  // buffer view: holds a reference to arr until released.
  Py_buffer *view = static_cast<Py_buffer *>(std::malloc(sizeof(Py_buffer)));
  if (view == nullptr) {
    set_error("out of memory");
    Py_DECREF(arr);
    return -1;
  }
  if (PyObject_GetBuffer(arr, view, PyBUF_SIMPLE) != 0) {
    set_error_from_python();
    std::free(view);
    Py_DECREF(arr);
    return -1;
  }
  Py_DECREF(arr);  // the Py_buffer keeps its own reference (view->obj)
  out->data = view->buf;
  out->nbytes = static_cast<long long>(view->len);
  out->opaque = view;
  return 0;
}

void ray_tpu_buffer_release(ray_tpu_buffer *buf) {
  if (buf == nullptr || buf->opaque == nullptr) return;
  Gil gil;
  Py_buffer *view = static_cast<Py_buffer *>(buf->opaque);
  PyBuffer_Release(view);
  std::free(view);
  std::memset(buf, 0, sizeof(*buf));
}

int ray_tpu_wait(const char **ref_hexes, int n, int num_returns,
                 double timeout_s) {
  if (ref_hexes == nullptr || n < 0) {
    set_error("bad ref list");
    return -1;
  }
  Gil gil;
  PyObject *list = PyList_New(n);
  if (list == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < n; i++) {
    if (ref_hexes[i] == nullptr) {
      Py_DECREF(list);
      set_error("ref list contains NULL");
      return -1;
    }
    PyObject *item = PyUnicode_FromString(ref_hexes[i]);
    if (item == nullptr) {  // non-UTF-8 input
      set_error_from_python();
      Py_DECREF(list);
      return -1;
    }
    PyList_SetItem(list, i, item);
  }
  PyObject *jmod = PyImport_ImportModule("json");
  if (jmod == nullptr) {
    Py_DECREF(list);
    set_error_from_python();
    return -1;
  }
  PyObject *refs_json = PyObject_CallMethod(jmod, "dumps", "(O)", list);
  Py_DECREF(jmod);
  Py_DECREF(list);
  if (refs_json == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *out = call_bridge(
      "wait",
      Py_BuildValue("(Oid)", refs_json, num_returns, timeout_s));
  Py_DECREF(refs_json);
  if (out == nullptr) return -1;
  long ready = PyLong_AsLong(out);
  Py_DECREF(out);
  if (ready < 0 && PyErr_Occurred()) {
    set_error_from_python();
    return -1;
  }
  return static_cast<int>(ready);
}

}  // extern "C"
