// Shared-memory immutable object store — the plasma equivalent.
//
// Reference counterpart: src/ray/object_manager/plasma/ (store.cc, client.h,
// dlmalloc arena, eviction_policy.cc). Re-designed for the TPU runtime:
// one POSIX shm segment per node that the node controller creates and every
// worker process on the host maps. Objects are immutable byte blobs keyed by
// a 24-byte ObjectID. The create/seal protocol matches plasma's (create an
// unsealed buffer, write into it, seal; gets only see sealed objects), but
// there is no socket protocol at all: all operations are direct calls into
// this library under a process-shared robust mutex, and readers get offsets
// into their own mapping of the segment (zero-copy).
//
// Layout:  [StoreHeader][slot table][data arena]
// Allocator: sorted-by-offset free list with split on allocate and
// coalesce on free. Eviction: LRU over sealed, unreferenced objects.

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5450555354523143ULL;  // "TPUSTR1C"
constexpr uint64_t kAlign = 64;                     // cache-line data alignment
constexpr uint32_t kIdLen = 24;  // matches ray_tpu ObjectID.SIZE

enum SlotState : uint8_t { kEmpty = 0, kUsed = 1, kTombstone = 2 };

enum ReturnCode : int {
  kOk = 0,
  kNotFound = -1,
  kOutOfMemory = -2,
  kNotSealed = -3,
  kAlreadyExists = -4,
  kInUse = -5,
  kBadHandle = -6,
};

struct StoreHeader {
  uint64_t magic;
  uint64_t capacity;     // whole-segment bytes
  uint64_t table_off;
  uint32_t table_cap;    // power of two
  uint32_t ready;        // set to 1 once fully initialized
  uint64_t arena_off;
  uint64_t arena_size;
  uint64_t used_bytes;   // payload bytes of live objects
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t lru_clock;
  uint64_t free_head;    // offset of first free block, 0 = none
  pthread_mutex_t mutex;
};

struct Slot {
  uint8_t id[kIdLen];
  uint8_t state;
  uint8_t sealed;
  uint8_t pending_delete;
  uint8_t pad[5];
  uint32_t refcount;
  uint64_t block_off;    // BlockHeader offset in segment
  uint64_t size;         // payload bytes
  uint64_t lru;
};

// Every arena block (free or allocated) starts with this header.
struct BlockHeader {
  uint64_t size;       // payload capacity, excluding this header
  uint64_t next_free;  // next free block offset (valid when free), 0 = end
  uint32_t is_free;
  uint32_t pad;
};

struct Handle {
  uint8_t* base;
  uint64_t mapped_size;
  StoreHeader* hdr;
  bool owner;
  char name[256];
};

inline Slot* slot_table(Handle* h) {
  return reinterpret_cast<Slot*>(h->base + h->hdr->table_off);
}

inline BlockHeader* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(h->base + off);
}

uint64_t hash_id(const uint8_t* id) {
  uint64_t hash = 14695981039346656037ULL;  // FNV-1a
  for (uint32_t i = 0; i < kIdLen; ++i) {
    hash ^= id[i];
    hash *= 1099511628211ULL;
  }
  return hash;
}

// Locks the store mutex, recovering the lock state if a holder died.
void lock(Handle* h) {
  int rc = pthread_mutex_lock(&h->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // Previous owner died mid-operation. The index/free list are only
    // mutated under the lock in short critical sections; mark consistent
    // and continue — worst case a block leaks until eviction pressure.
    pthread_mutex_consistent(&h->hdr->mutex);
  }
}

void unlock(Handle* h) { pthread_mutex_unlock(&h->hdr->mutex); }

// Finds the slot for id, or an insertion slot if insert=true. Linear probing.
Slot* find_slot(Handle* h, const uint8_t* id, bool insert) {
  Slot* table = slot_table(h);
  uint32_t mask = h->hdr->table_cap - 1;
  uint32_t idx = static_cast<uint32_t>(hash_id(id)) & mask;
  Slot* first_tomb = nullptr;
  for (uint32_t probe = 0; probe <= mask; ++probe, idx = (idx + 1) & mask) {
    Slot* s = &table[idx];
    if (s->state == kEmpty) {
      if (!insert) return nullptr;
      return first_tomb ? first_tomb : s;
    }
    if (s->state == kTombstone) {
      if (insert && !first_tomb) first_tomb = s;
      continue;
    }
    if (std::memcmp(s->id, id, kIdLen) == 0) return s;
  }
  return insert ? first_tomb : nullptr;
}

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

// Allocates a block with >= size payload bytes. First fit over the sorted
// free list; splits when the remainder can hold a minimal block.
uint64_t alloc_block(Handle* h, uint64_t size) {
  size = align_up(size, kAlign);
  uint64_t prev_off = 0;
  uint64_t off = h->hdr->free_head;
  while (off != 0) {
    BlockHeader* b = block_at(h, off);
    if (b->size >= size) {
      uint64_t remainder = b->size - size;
      if (remainder >= sizeof(BlockHeader) + kAlign) {
        // Split: tail becomes a new free block.
        uint64_t tail_off = off + sizeof(BlockHeader) + size;
        BlockHeader* tail = block_at(h, tail_off);
        tail->size = remainder - sizeof(BlockHeader);
        tail->next_free = b->next_free;
        tail->is_free = 1;
        b->size = size;
        if (prev_off == 0) {
          h->hdr->free_head = tail_off;
        } else {
          block_at(h, prev_off)->next_free = tail_off;
        }
      } else {
        if (prev_off == 0) {
          h->hdr->free_head = b->next_free;
        } else {
          block_at(h, prev_off)->next_free = b->next_free;
        }
      }
      b->is_free = 0;
      b->next_free = 0;
      return off;
    }
    prev_off = off;
    off = b->next_free;
  }
  return 0;
}

// Returns a block to the free list (kept sorted by offset) and coalesces
// with adjacent free blocks.
void free_block(Handle* h, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  b->is_free = 1;
  uint64_t prev_off = 0;
  uint64_t cur = h->hdr->free_head;
  while (cur != 0 && cur < off) {
    prev_off = cur;
    cur = block_at(h, cur)->next_free;
  }
  b->next_free = cur;
  if (prev_off == 0) {
    h->hdr->free_head = off;
  } else {
    block_at(h, prev_off)->next_free = off;
  }
  // Coalesce with successor.
  if (cur != 0 && off + sizeof(BlockHeader) + b->size == cur) {
    BlockHeader* next = block_at(h, cur);
    b->size += sizeof(BlockHeader) + next->size;
    b->next_free = next->next_free;
  }
  // Coalesce with predecessor.
  if (prev_off != 0) {
    BlockHeader* prev = block_at(h, prev_off);
    if (prev_off + sizeof(BlockHeader) + prev->size == off) {
      prev->size += sizeof(BlockHeader) + b->size;
      prev->next_free = b->next_free;
    }
  }
}

void release_slot(Handle* h, Slot* s) {
  free_block(h, s->block_off);
  h->hdr->used_bytes -= s->size;
  h->hdr->num_objects -= 1;
  s->state = kTombstone;
  s->sealed = 0;
  s->pending_delete = 0;
}

// Evicts the least-recently-used sealed, unreferenced object.
// Returns true if something was evicted.
// O(table_cap) scan under the lock: fine at the common 1K-64K slot sizes;
// a sustained slot-full small-object workload would want a clock-hand
// cursor in the header to amortize this (plasma uses an LRU list).
bool evict_one(Handle* h) {
  Slot* table = slot_table(h);
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < h->hdr->table_cap; ++i) {
    Slot* s = &table[i];
    if (s->state == kUsed && s->sealed && s->refcount == 0) {
      if (victim == nullptr || s->lru < victim->lru) victim = s;
    }
  }
  if (victim == nullptr) return false;
  release_slot(h, victim);
  h->hdr->num_evictions += 1;
  return true;
}

uint32_t table_capacity_for(uint64_t capacity) {
  // One slot per 16KB of arena, clamped to [1024, 1<<20], power of two.
  uint64_t want = capacity / 16384;
  uint32_t cap = 1024;
  while (cap < want && cap < (1u << 20)) cap <<= 1;
  return cap;
}

}  // namespace

extern "C" {

// Creates a fresh store segment. Fails if one with this name already exists.
void* tps_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = static_cast<StoreHeader*>(base);
  std::memset(hdr, 0, sizeof(StoreHeader));
  hdr->capacity = capacity;
  hdr->table_cap = table_capacity_for(capacity);
  hdr->table_off = align_up(sizeof(StoreHeader), kAlign);
  uint64_t table_bytes = static_cast<uint64_t>(hdr->table_cap) * sizeof(Slot);
  hdr->arena_off = align_up(hdr->table_off + table_bytes, kAlign);
  if (hdr->arena_off + sizeof(BlockHeader) + kAlign > capacity) {
    munmap(base, capacity);
    shm_unlink(name);
    return nullptr;  // capacity too small for metadata
  }
  hdr->arena_size = capacity - hdr->arena_off;
  std::memset(static_cast<uint8_t*>(base) + hdr->table_off, 0, table_bytes);
  // Whole arena = one free block.
  auto* first = reinterpret_cast<BlockHeader*>(
      static_cast<uint8_t*>(base) + hdr->arena_off);
  first->size = hdr->arena_size - sizeof(BlockHeader);
  first->next_free = 0;
  first->is_free = 1;
  hdr->free_head = hdr->arena_off;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  hdr->magic = kMagic;
  __sync_synchronize();
  hdr->ready = 1;

  auto* h = new Handle();
  h->base = static_cast<uint8_t*>(base);
  h->mapped_size = capacity;
  h->hdr = hdr;
  h->owner = true;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

// Attaches to an existing store segment.
void* tps_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(StoreHeader)) {
    close(fd);
    return nullptr;
  }
  uint64_t capacity = static_cast<uint64_t>(st.st_size);
  void* base =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic || !hdr->ready || hdr->capacity != capacity) {
    munmap(base, capacity);
    return nullptr;
  }
  auto* h = new Handle();
  h->base = static_cast<uint8_t*>(base);
  h->mapped_size = capacity;
  h->hdr = hdr;
  h->owner = false;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

void tps_close(void* handle) {
  if (handle == nullptr) return;
  auto* h = static_cast<Handle*>(handle);
  munmap(h->base, h->mapped_size);
  delete h;
}

int tps_unlink(const char* name) { return shm_unlink(name); }

// Creates an unsealed object and returns the data offset for direct writes.
// The creator holds an implicit reference until seal/abort.
int tps_create_obj(void* handle, const uint8_t* id, uint64_t size,
                   uint64_t* data_off) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* existing = find_slot(h, id, false);
  if (existing != nullptr) {
    unlock(h);
    return kAlreadyExists;
  }
  // An object that can never fit must not trigger the eviction loop below —
  // it would destroy every idle object before failing anyway.
  if (align_up(size, kAlign) + sizeof(BlockHeader) > h->hdr->arena_size) {
    unlock(h);
    return kOutOfMemory;
  }
  uint64_t block = alloc_block(h, size);
  while (block == 0) {
    if (!evict_one(h)) {
      unlock(h);
      return kOutOfMemory;
    }
    block = alloc_block(h, size);
  }
  Slot* s = find_slot(h, id, true);
  while (s == nullptr) {
    // Slot table full (all kUsed, no tombstone): evict an idle object to
    // reclaim a slot, like plasma does for arena pressure. Dense
    // small-object workloads hit this before the arena fills.
    if (!evict_one(h)) {
      free_block(h, block);
      unlock(h);
      return kOutOfMemory;
    }
    s = find_slot(h, id, true);
  }
  std::memcpy(s->id, id, kIdLen);
  s->state = kUsed;
  s->sealed = 0;
  s->pending_delete = 0;
  s->refcount = 1;  // creator's reference
  s->block_off = block;
  s->size = size;
  s->lru = ++h->hdr->lru_clock;
  h->hdr->used_bytes += size;
  h->hdr->num_objects += 1;
  *data_off = block + sizeof(BlockHeader);
  unlock(h);
  return kOk;
}

// Seals an object (making it visible to gets) and drops the creator's ref.
int tps_seal(void* handle, const uint8_t* id) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  if (s == nullptr) {
    unlock(h);
    return kNotFound;
  }
  if (s->sealed) {  // idempotent: never steal a reader's pin on re-seal
    unlock(h);
    return kAlreadyExists;
  }
  s->sealed = 1;
  if (s->refcount > 0) s->refcount -= 1;
  unlock(h);
  return kOk;
}

// Aborts an unsealed create, freeing its space.
int tps_abort(void* handle, const uint8_t* id) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  if (s == nullptr) {
    unlock(h);
    return kNotFound;
  }
  if (s->sealed) {
    unlock(h);
    return kAlreadyExists;
  }
  release_slot(h, s);
  unlock(h);
  return kOk;
}

// One-shot put: create + copy + seal.
int tps_put(void* handle, const uint8_t* id, const uint8_t* data,
            uint64_t size) {
  uint64_t off = 0;
  int rc = tps_create_obj(handle, id, size, &off);
  if (rc != kOk) return rc;
  auto* h = static_cast<Handle*>(handle);
  std::memcpy(h->base + off, data, size);
  return tps_seal(handle, id);
}

// Gets a sealed object: returns its data offset + size and pins it
// (refcount++). Caller must tps_release when done with the buffer.
int tps_get(void* handle, const uint8_t* id, uint64_t* data_off,
            uint64_t* size) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  if (s == nullptr) {
    unlock(h);
    return kNotFound;
  }
  if (!s->sealed) {
    unlock(h);
    return kNotSealed;
  }
  s->refcount += 1;
  s->lru = ++h->hdr->lru_clock;
  *data_off = s->block_off + sizeof(BlockHeader);
  *size = s->size;
  unlock(h);
  return kOk;
}

// Drops a pin taken by tps_get. Completes a deferred delete at zero refs.
int tps_release(void* handle, const uint8_t* id) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  if (s == nullptr) {
    unlock(h);
    return kNotFound;
  }
  if (s->refcount > 0) s->refcount -= 1;
  if (s->refcount == 0 && s->pending_delete) release_slot(h, s);
  unlock(h);
  return kOk;
}

int tps_contains(void* handle, const uint8_t* id) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  int present = (s != nullptr && s->sealed) ? 1 : 0;
  unlock(h);
  return present;
}

// Deletes an object. If pinned, deletion is deferred to the last release.
int tps_delete(void* handle, const uint8_t* id) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* s = find_slot(h, id, false);
  if (s == nullptr) {
    unlock(h);
    return kNotFound;
  }
  if (s->refcount > 0) {
    s->pending_delete = 1;
    unlock(h);
    return kInUse;
  }
  release_slot(h, s);
  unlock(h);
  return kOk;
}

// stats[0]=num_objects stats[1]=used_bytes stats[2]=arena_size
// stats[3]=num_evictions stats[4]=table_cap stats[5]=capacity
int tps_stats(void* handle, uint64_t* stats) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  stats[0] = h->hdr->num_objects;
  stats[1] = h->hdr->used_bytes;
  stats[2] = h->hdr->arena_size;
  stats[3] = h->hdr->num_evictions;
  stats[4] = h->hdr->table_cap;
  stats[5] = h->hdr->capacity;
  unlock(h);
  return kOk;
}

// Lists up to max_ids object ids (sealed only) into out (kIdLen bytes each).
// Returns the number written.
int tps_list(void* handle, uint8_t* out, int max_ids) {
  auto* h = static_cast<Handle*>(handle);
  if (h == nullptr) return kBadHandle;
  lock(h);
  Slot* table = slot_table(h);
  int n = 0;
  for (uint32_t i = 0; i < h->hdr->table_cap && n < max_ids; ++i) {
    Slot* s = &table[i];
    if (s->state == kUsed && s->sealed) {
      std::memcpy(out + static_cast<uint64_t>(n) * kIdLen, s->id, kIdLen);
      ++n;
    }
  }
  unlock(h);
  return n;
}

}  // extern "C"
