// Object-transfer data plane — the ObjectManager equivalent.
//
// Reference counterpart: src/ray/object_manager/ (object_manager.cc chunked
// Push/Pull over a dedicated gRPC service, object_buffer_pool). Re-designed
// for this runtime: a per-node TCP server thread that streams object bytes
// STRAIGHT OUT OF the shared-memory arena (no copy into Python, no pickle
// framing), and a client that receives STRAIGHT INTO a newly created arena
// slot on the destination node. The Python control plane only exchanges
// object locations; bulk bytes never cross the GIL.
//
// Wire protocol (all little-endian):
//   GET : c->s [op=1:1][id:24]            s->c [status:1][size:8][payload]
//   PUT : c->s [op=2:1][id:24][size:8][payload]   s->c [status:1]
//   GETR: c->s [op=3:1][id:24][offset:8][length:8]
//         s->c [status:1][total:8][n:8][payload n bytes]
// A connection handles sequential requests until EOF.
//
// GETR is the chunked data plane (reference: object_buffer_pool chunked
// Push): n = min(length, total - offset), so a receiver pulls an object as
// a pipeline of fixed-size ranges, writing each into its (unsealed) arena
// slot as it lands. length=0 is a pure size probe. Because every response
// carries the authoritative total, a pull broken by sender death resumes
// at the next un-landed offset against ANY other holder — the per-chunk
// offset IS the resume cursor.

#include "shm_store.cc"  // same TU: Handle layout + tps_* internals

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>

namespace {

constexpr uint8_t kOpGet = 1;
constexpr uint8_t kOpPut = 2;
constexpr uint8_t kOpGetRange = 3;
constexpr int kChunk = 1 << 20;  // 1MB send granularity (ref ray_config_def.h:242)

bool send_all(int fd, const uint8_t* buf, uint64_t n) {
  while (n > 0) {
    ssize_t w = send(fd, buf, n > kChunk ? kChunk : n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    buf += w;
    n -= static_cast<uint64_t>(w);
  }
  return true;
}

bool recv_all(int fd, uint8_t* buf, uint64_t n) {
  while (n > 0) {
    ssize_t r = recv(fd, buf, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    buf += r;
    n -= static_cast<uint64_t>(r);
  }
  return true;
}

struct ServerCtx {
  void* store;
  int listen_fd;
  int port;
  pthread_t thread;
  std::atomic<bool> stop{false};
  // Data-plane accounting: bytes served out of the arena and request
  // count, read by the Python side for transfer_bytes_out. Relaxed is
  // fine — these are monotonic gauges, not synchronization.
  std::atomic<uint64_t> bytes_out{0};
  std::atomic<uint64_t> requests{0};
  // Live-connection registry: conn threads are detached, so stop must
  // shut their sockets down and wait for the last one to leave before
  // the ctx can be freed (a detached thread touching a deleted ctx is a
  // use-after-free — caught by the TSAN stress harness).
  pthread_mutex_t conn_mu = PTHREAD_MUTEX_INITIALIZER;
  pthread_cond_t conn_cv = PTHREAD_COND_INITIALIZER;
  std::vector<int> conn_fds;
  int live_conns = 0;
};

struct ConnArgs {
  ServerCtx* ctx;
  int fd;
};

void handle_get(ServerCtx* ctx, int fd, const uint8_t* id) {
  uint64_t off = 0, size = 0;
  int rc = tps_get(ctx->store, id, &off, &size);
  uint8_t status = rc == kOk ? 0 : 1;
  uint8_t head[9];
  head[0] = status;
  uint64_t sz = rc == kOk ? size : 0;
  std::memcpy(head + 1, &sz, 8);
  if (!send_all(fd, head, 9)) {
    if (rc == kOk) tps_release(ctx->store, id);
    return;
  }
  if (rc == kOk) {
    auto* h = static_cast<Handle*>(ctx->store);
    if (send_all(fd, h->base + off, size)) {  // zero-copy out of the arena
      ctx->bytes_out.fetch_add(size, std::memory_order_relaxed);
    }
    tps_release(ctx->store, id);
  }
}

// One range of a sealed object: [status:1][total:8][n:8][payload].
// status 0 = ok, 1 = miss, 2 = offset past end. length 0 probes the size.
void handle_get_range(ServerCtx* ctx, int fd, const uint8_t* id) {
  uint8_t operands[16];
  if (!recv_all(fd, operands, sizeof(operands))) return;
  uint64_t offset, length;
  std::memcpy(&offset, operands, 8);
  std::memcpy(&length, operands + 8, 8);
  uint64_t off = 0, size = 0;
  int rc = tps_get(ctx->store, id, &off, &size);
  uint8_t status = rc == kOk ? 0 : 1;
  uint64_t total = rc == kOk ? size : 0;
  uint64_t n = 0;
  if (rc == kOk) {
    if (offset > total) {
      status = 2;
    } else {
      uint64_t avail = total - offset;
      n = length < avail ? length : avail;
    }
  }
  uint8_t head[17];
  head[0] = status;
  std::memcpy(head + 1, &total, 8);
  std::memcpy(head + 9, &n, 8);
  if (send_all(fd, head, sizeof(head)) && n > 0) {
    auto* h = static_cast<Handle*>(ctx->store);
    if (send_all(fd, h->base + off + offset, n)) {
      ctx->bytes_out.fetch_add(n, std::memory_order_relaxed);
    }
  }
  if (rc == kOk) tps_release(ctx->store, id);
}


// Drain `size` payload bytes so the connection stays request-aligned when a
// body cannot be stored (duplicate object, OOM, raced fetcher).
static bool drain_payload(int fd, uint64_t size) {
  uint8_t sink[65536];
  uint64_t left = size;
  while (left > 0) {
    uint64_t take = left > sizeof(sink) ? sizeof(sink) : left;
    if (!recv_all(fd, sink, take)) return false;
    left -= take;
  }
  return true;
}

void handle_put(ServerCtx* ctx, int fd, const uint8_t* id) {
  uint64_t size = 0;
  if (!recv_all(fd, reinterpret_cast<uint8_t*>(&size), 8)) return;
  uint64_t off = 0;
  int rc = tps_create_obj(ctx->store, id, size, &off);
  uint8_t status;
  if (rc == kOk) {
    auto* h = static_cast<Handle*>(ctx->store);
    if (recv_all(fd, h->base + off, size)) {  // straight into the arena
      tps_seal(ctx->store, id);
      status = 0;
    } else {
      tps_abort(ctx->store, id);
      return;  // connection broken anyway
    }
  } else if (rc == kAlreadyExists) {
    // Idempotent: drain payload, report success (objects are immutable).
    if (!drain_payload(fd, size)) return;
    status = 0;
  } else {
    // OOM etc: drain the payload so a persistent connection stays framed
    // (the next bytes must be a request header, not leftover payload).
    if (!drain_payload(fd, size)) return;
    status = 2;  // sender sees failure
  }
  send_all(fd, &status, 1);
}

void* conn_loop(void* arg) {
  auto* ca = static_cast<ConnArgs*>(arg);
  int one = 1;
  setsockopt(ca->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t req[1 + kIdLen];
  while (!ca->ctx->stop.load(std::memory_order_relaxed)) {
    if (!recv_all(ca->fd, req, sizeof(req))) break;
    ca->ctx->requests.fetch_add(1, std::memory_order_relaxed);
    if (req[0] == kOpGet) {
      handle_get(ca->ctx, ca->fd, req + 1);
    } else if (req[0] == kOpPut) {
      handle_put(ca->ctx, ca->fd, req + 1);
    } else if (req[0] == kOpGetRange) {
      handle_get_range(ca->ctx, ca->fd, req + 1);
    } else {
      break;
    }
  }
  close(ca->fd);
  // deregister LAST: after the count drops, stop may free the ctx
  ServerCtx* ctx = ca->ctx;
  int fd = ca->fd;
  delete ca;
  pthread_mutex_lock(&ctx->conn_mu);
  for (size_t i = 0; i < ctx->conn_fds.size(); ++i) {
    if (ctx->conn_fds[i] == fd) {
      ctx->conn_fds[i] = ctx->conn_fds.back();
      ctx->conn_fds.pop_back();
      break;
    }
  }
  ctx->live_conns--;
  pthread_cond_broadcast(&ctx->conn_cv);
  pthread_mutex_unlock(&ctx->conn_mu);
  return nullptr;
}

void* accept_loop(void* arg) {
  auto* ctx = static_cast<ServerCtx*>(arg);
  while (!ctx->stop.load(std::memory_order_relaxed)) {
    int fd = accept(ctx->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listen socket closed by tts_serve_stop
    }
    auto* ca = new ConnArgs{ctx, fd};
    pthread_mutex_lock(&ctx->conn_mu);
    ctx->conn_fds.push_back(fd);
    ctx->live_conns++;
    pthread_mutex_unlock(&ctx->conn_mu);
    pthread_t t;
    if (pthread_create(&t, nullptr, conn_loop, ca) == 0) {
      pthread_detach(t);
    } else {
      close(fd);
      delete ca;
      pthread_mutex_lock(&ctx->conn_mu);
      for (size_t i = 0; i < ctx->conn_fds.size(); ++i) {
        if (ctx->conn_fds[i] == fd) {
          ctx->conn_fds[i] = ctx->conn_fds.back();
          ctx->conn_fds.pop_back();
          break;
        }
      }
      ctx->live_conns--;
      pthread_cond_broadcast(&ctx->conn_cv);
      pthread_mutex_unlock(&ctx->conn_mu);
    }
  }
  return nullptr;
}

int connect_to(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

}  // namespace

extern "C" {

// Starts the transfer server for an open store handle. port=0 picks a free
// port. Returns a ServerCtx* (opaque) or null.
void* tts_serve_start(void* store_handle, int port) {
  if (store_handle == nullptr) return nullptr;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 128) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* ctx = new ServerCtx();
  ctx->store = store_handle;
  ctx->listen_fd = fd;
  ctx->port = ntohs(addr.sin_port);
  if (pthread_create(&ctx->thread, nullptr, accept_loop, ctx) != 0) {
    close(fd);
    delete ctx;
    return nullptr;
  }
  return ctx;
}

int tts_serve_port(void* sctx) {
  return sctx ? static_cast<ServerCtx*>(sctx)->port : -1;
}

// Cumulative bytes served / requests handled by this server (the
// transfer_bytes_out source of truth; payload bytes only, no headers).
void tts_serve_stats(void* sctx, uint64_t* bytes_out, uint64_t* requests) {
  auto* ctx = static_cast<ServerCtx*>(sctx);
  if (ctx == nullptr) {
    if (bytes_out) *bytes_out = 0;
    if (requests) *requests = 0;
    return;
  }
  if (bytes_out) *bytes_out = ctx->bytes_out.load(std::memory_order_relaxed);
  if (requests) *requests = ctx->requests.load(std::memory_order_relaxed);
}

void tts_serve_stop(void* sctx) {
  if (sctx == nullptr) return;
  auto* ctx = static_cast<ServerCtx*>(sctx);
  ctx->stop.store(true);
  shutdown(ctx->listen_fd, SHUT_RDWR);
  close(ctx->listen_fd);
  pthread_join(ctx->thread, nullptr);
  // Kick every live connection out of its blocking recv/send, then wait
  // for the detached handlers to deregister — only then is the ctx free
  // (use-after-free otherwise; see the ServerCtx registry comment).
  pthread_mutex_lock(&ctx->conn_mu);
  for (int fd : ctx->conn_fds) shutdown(fd, SHUT_RDWR);
  while (ctx->live_conns > 0) {
    pthread_cond_wait(&ctx->conn_cv, &ctx->conn_mu);
  }
  pthread_mutex_unlock(&ctx->conn_mu);
  delete ctx;
}

// Opens a persistent data-plane connection (the server handles sequential
// requests per connection). Returns fd >= 0 or -1.
int tts_connect(const char* host, int port) { return connect_to(host, port); }

void tts_disconnect(int fd) {
  if (fd >= 0) close(fd);
}

// Fetch over an existing connection. Same return codes as tts_fetch, plus
// -5 = connection broken (caller should reconnect).
int tts_fetch_fd(int fd, const uint8_t* id, void* store_handle) {
  if (tps_contains(store_handle, id) == 1) return 0;
  uint8_t req[1 + kIdLen];
  req[0] = kOpGet;
  std::memcpy(req + 1, id, kIdLen);
  uint8_t head[9];
  if (!send_all(fd, req, sizeof(req)) || !recv_all(fd, head, 9)) return -5;
  uint64_t size;
  std::memcpy(&size, head + 1, 8);
  if (head[0] != 0) return -1;
  uint64_t off = 0;
  int rc = tps_create_obj(store_handle, id, size, &off);
  if (rc == kAlreadyExists || rc != kOk) {
    // raced another fetcher / local store full: must still drain the stream
    // to keep the connection request-aligned.
    if (!drain_payload(fd, size)) return -5;
    return rc == kAlreadyExists ? 0 : -3;
  }
  auto* h = static_cast<Handle*>(store_handle);
  if (!recv_all(fd, h->base + off, size)) {
    tps_abort(store_handle, id);
    return -5;
  }
  tps_seal(store_handle, id);
  return 0;
}

// Fetches ONE range of object `id` over an existing connection, receiving
// straight into caller memory `dst` (an unsealed arena slot on the pull
// path). length=0 probes the size without moving payload. Returns the
// number of payload bytes landed (>= 0) with *total_out set to the
// object's full size, or negative: -1 remote miss, -4 protocol error
// (offset past end / malformed), -5 connection broken mid-stream — the
// caller's already-landed prefix stays valid, so a retry against another
// holder resumes at offset + <bytes landed so far>.
int64_t tts_fetch_range_fd(int fd, const uint8_t* id, uint64_t offset,
                           uint64_t length, uint8_t* dst,
                           uint64_t* total_out) {
  if (total_out) *total_out = 0;
  uint8_t req[1 + kIdLen + 16];
  req[0] = kOpGetRange;
  std::memcpy(req + 1, id, kIdLen);
  std::memcpy(req + 1 + kIdLen, &offset, 8);
  std::memcpy(req + 1 + kIdLen + 8, &length, 8);
  uint8_t head[17];
  if (!send_all(fd, req, sizeof(req)) || !recv_all(fd, head, sizeof(head)))
    return -5;
  uint64_t total, n;
  std::memcpy(&total, head + 1, 8);
  std::memcpy(&n, head + 9, 8);
  if (total_out) *total_out = total;
  if (head[0] == 1) return -1;
  if (head[0] != 0 || n > length) return -4;
  if (n > 0 && !recv_all(fd, dst, n)) return -5;
  return static_cast<int64_t>(n);
}

// Fetches object `id` from host:port directly into the local arena.
// Returns 0 on success, -1 remote miss, -2 connect failure, -3 local store
// full, -4 protocol error, -5 connection broken. Safe to call concurrently.
int tts_fetch(const char* host, int port, const uint8_t* id,
              void* store_handle) {
  if (tps_contains(store_handle, id) == 1) return 0;
  int fd = connect_to(host, port);
  if (fd < 0) return -2;
  int result = tts_fetch_fd(fd, id, store_handle);
  close(fd);
  return result;
}

// Fetches into a malloc'd buffer (for processes with no local arena).
// On success returns size (>=0) and sets *out (caller frees via
// tts_buf_free); negative = error codes as tts_fetch.
int64_t tts_fetch_buf(const char* host, int port, const uint8_t* id,
                      uint8_t** out) {
  *out = nullptr;
  int fd = connect_to(host, port);
  if (fd < 0) return -2;
  uint8_t req[1 + kIdLen];
  req[0] = kOpGet;
  std::memcpy(req + 1, id, kIdLen);
  uint8_t head[9];
  int64_t result = -4;
  if (send_all(fd, req, sizeof(req)) && recv_all(fd, head, 9)) {
    uint64_t size;
    std::memcpy(&size, head + 1, 8);
    if (head[0] != 0) {
      result = -1;
    } else {
      auto* buf = static_cast<uint8_t*>(malloc(size ? size : 1));
      if (buf == nullptr) {
        result = -3;
      } else if (recv_all(fd, buf, size)) {
        *out = buf;
        result = static_cast<int64_t>(size);
      } else {
        free(buf);
        result = -4;
      }
    }
  }
  close(fd);
  return result;
}

void tts_buf_free(uint8_t* p) { free(p); }

// Pushes a local arena object to a remote node (the reference's Push path).
// Returns 0 ok, -1 not local, -2 connect failure, -4 protocol/remote error.
int tts_push(const char* host, int port, const uint8_t* id,
             void* store_handle) {
  uint64_t off = 0, size = 0;
  if (tps_get(store_handle, id, &off, &size) != kOk) return -1;
  int result = -4;
  int fd = connect_to(host, port);
  if (fd >= 0) {
    uint8_t req[1 + kIdLen + 8];
    req[0] = kOpPut;
    std::memcpy(req + 1, id, kIdLen);
    std::memcpy(req + 1 + kIdLen, &size, 8);
    auto* h = static_cast<Handle*>(store_handle);
    uint8_t status = 1;
    if (send_all(fd, req, sizeof(req)) &&
        send_all(fd, h->base + off, size) && recv_all(fd, &status, 1) &&
        status == 0) {
      result = 0;
    }
    close(fd);
  } else {
    result = -2;
  }
  tps_release(store_handle, id);
  return result;
}

}  // extern "C"
