// Native frame pump: socket recv + length-prefix framing + scatter-gather
// send, off the GIL (reference counterpart: the C++ core worker / raylet
// keep exactly these loops native — arXiv:1712.05889 §4.3; the Python
// byte-shuffling in protocol.py:_recv_exact was 14% of head and 60% of
// worker self-time in the PR 6 live profile).
//
// One pump per connection, used from ONE thread at a time (the client's
// reader thread or the server's event loop). Two modes share the
// ring/splitter:
//
//   * fd mode   (fd >= 0)  — the pump owns the read side of the socket:
//     fp_pump() blocks in recv(2) with the GIL released (ctypes releases
//     it around the foreign call), appends to a growable ring, splits
//     length-prefixed frames, and batches them for one fp_take() per
//     wakeup — N frames per Python call instead of 2+ recv syscalls and
//     a bytearray dance per frame.
//   * feed mode (fd < 0)   — the caller supplies bytes (the asyncio
//     server's bulk reader.read() chunks); fp_feed() splits the same way.
//
// Frame layout is protocol.py's: [8-byte LE length][body]. The pump
// enforces the same MAX_MESSAGE bound (oversize => hard error, the
// connection is dropped, matching the Python path's behavior). Bodies are
// delivered verbatim: magic-byte dispatch, pickle fallback, chaos hooks
// and every decode stay in Python.
//
// Thread-safety contract: fp_pump/fp_feed/fp_take on one handle are
// called from a single thread; fp_destroy only after the pumping thread
// has exited (the Python wrapper destroys from the reader loop's exit
// path). fp_sendv is stateless per call and safe from any thread.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>

#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr size_t kRecvChunk = 256 * 1024;
// Stay well under IOV_MAX per sendmsg (EMSGSIZE otherwise); same cap the
// Python _send_buffers used.
constexpr size_t kIovCap = 512;

struct Frame {
  size_t off;
  size_t len;
};

struct FramePump {
  int fd = -1;                    // -1: feed mode
  uint64_t max_message = 0;
  std::vector<uint8_t> buf;       // contiguous ring: [frames)[partial tail)
  size_t parse = 0;               // split cursor (start of the partial tail)
  std::deque<Frame> frames;       // complete, undelivered frame bodies
  uint64_t body_bytes = 0;        // sum of undelivered body lengths
  std::vector<uint8_t> rx;        // fd-mode recv staging chunk
  bool error = false;
};

uint64_t read_le64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // host is little-endian on every deploy target
  return v;
}

// Split complete frames out of [parse, buf.size()). Returns false on an
// oversize frame (protocol violation: latch the error, drop the conn).
bool split_frames(FramePump* p) {
  size_t end = p->buf.size();
  while (end - p->parse >= 8) {
    uint64_t length = read_le64(p->buf.data() + p->parse);
    if (length > p->max_message) {
      p->error = true;
      return false;
    }
    if (end - p->parse - 8 < length) break;  // partial body: wait for more
    p->frames.push_back({p->parse + 8, static_cast<size_t>(length)});
    p->body_bytes += length;
    p->parse += 8 + length;
  }
  return true;
}

// Reclaim delivered bytes once nothing references them: memmove the
// partial tail to the front so the buffer never grows past one frame +
// one recv chunk in steady state.
void compact(FramePump* p) {
  if (!p->frames.empty() || p->parse == 0) return;
  size_t tail = p->buf.size() - p->parse;
  if (tail > 0) std::memmove(p->buf.data(), p->buf.data() + p->parse, tail);
  p->buf.resize(tail);
  p->parse = 0;
}

// Copy out up to max_frames bodies into dst, then write the number of
// frames STILL buffered into sizes[taken] (the array must hold
// max_frames + 1 entries). Returns taken, or -3 when the first pending
// frame's body exceeds dst_cap (nothing consumed; the caller grows dst
// and drains with fp_take). The batched single-call path: Python pays
// ONE foreign call per wakeup instead of pending/bytes/take round-trips
// (each ctypes crossing costs ~1 µs — four per frame erased the win).
int64_t take_batch(FramePump* p, uint8_t* dst, uint64_t dst_cap,
                   uint64_t* sizes, uint64_t max_frames) {
  if (!p->frames.empty() && p->frames.front().len > dst_cap) return -3;
  uint64_t taken = 0;
  uint64_t written = 0;
  while (taken < max_frames && !p->frames.empty()) {
    const Frame& f = p->frames.front();
    if (written + f.len > dst_cap) break;
    if (f.len > 0) std::memcpy(dst + written, p->buf.data() + f.off, f.len);
    sizes[taken] = f.len;
    written += f.len;
    p->body_bytes -= f.len;
    p->frames.pop_front();
    ++taken;
  }
  sizes[taken] = p->frames.size();  // leftovers (cap overflow): rare drain
  compact(p);
  return static_cast<int64_t>(taken);
}

}  // namespace

extern "C" {

void* fp_create(int fd, uint64_t max_message) {
  FramePump* p = new (std::nothrow) FramePump();
  if (p == nullptr) return nullptr;
  p->fd = fd;
  p->max_message = max_message;
  return p;
}

void fp_destroy(void* h) { delete static_cast<FramePump*>(h); }

// fd mode: block in recv until at least one complete frame is buffered
// (or EOF/error). Returns the number of complete frames ready, -1 on
// EOF/socket error, -2 on an oversize frame.
int64_t fp_pump(void* h) {
  FramePump* p = static_cast<FramePump*>(h);
  if (p->error) return -2;
  if (p->fd < 0) return -1;
  if (p->rx.size() < kRecvChunk) p->rx.resize(kRecvChunk);
  uint8_t* chunk = p->rx.data();
  while (p->frames.empty()) {
    ssize_t n = recv(p->fd, chunk, kRecvChunk, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (n == 0) return -1;  // orderly EOF
    p->buf.insert(p->buf.end(), chunk, chunk + n);
    if (!split_frames(p)) return -2;
  }
  return static_cast<int64_t>(p->frames.size());
}

// feed mode: append caller bytes + split. Returns frames ready, -2 on an
// oversize frame.
int64_t fp_feed(void* h, const uint8_t* data, uint64_t len) {
  FramePump* p = static_cast<FramePump*>(h);
  if (p->error) return -2;
  if (len > 0) p->buf.insert(p->buf.end(), data, data + len);
  if (!split_frames(p)) return -2;
  return static_cast<int64_t>(p->frames.size());
}

// fd mode, one foreign call per wakeup: block until >=1 frame, then copy
// a batch straight into the caller's reusable dst. Returns frames taken,
// -1 EOF/socket error, -2 oversize frame, -3 dst too small for the first
// frame (nothing consumed; grow + fp_take). sizes needs max_frames + 1
// entries — sizes[taken] reports frames still buffered.
int64_t fp_pump_take(void* h, uint8_t* dst, uint64_t dst_cap,
                     uint64_t* sizes, uint64_t max_frames) {
  FramePump* p = static_cast<FramePump*>(h);
  if (p->error) return -2;
  if (p->fd < 0) return -1;
  if (p->rx.size() < kRecvChunk) p->rx.resize(kRecvChunk);
  uint8_t* chunk = p->rx.data();
  while (p->frames.empty()) {
    ssize_t n = recv(p->fd, chunk, kRecvChunk, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (n == 0) return -1;  // orderly EOF
    p->buf.insert(p->buf.end(), chunk, chunk + n);
    if (!split_frames(p)) return -2;
  }
  return take_batch(p, dst, dst_cap, sizes, max_frames);
}

// feed mode, one foreign call per chunk: append + split + copy out.
// Returns frames taken (0: no complete frame yet), -2 oversize, -3 dst
// too small for the first frame (bytes consumed into the ring; grow +
// fp_take, do NOT refeed). Same sizes contract as fp_pump_take.
int64_t fp_feed_take(void* h, const uint8_t* data, uint64_t len,
                     uint8_t* dst, uint64_t dst_cap,
                     uint64_t* sizes, uint64_t max_frames) {
  FramePump* p = static_cast<FramePump*>(h);
  if (p->error) return -2;
  if (len > 0) p->buf.insert(p->buf.end(), data, data + len);
  if (!split_frames(p)) return -2;
  if (p->frames.empty()) {
    sizes[0] = 0;
    return 0;
  }
  return take_batch(p, dst, dst_cap, sizes, max_frames);
}

uint64_t fp_pending_frames(void* h) {
  return static_cast<FramePump*>(h)->frames.size();
}

uint64_t fp_pending_bytes(void* h) {
  return static_cast<FramePump*>(h)->body_bytes;
}

// Copy out up to max_frames frame bodies, concatenated into dst; each
// body's length lands in sizes[]. Returns the number of frames taken
// (they are consumed), or -1 if dst_cap cannot hold them.
int64_t fp_take(void* h, uint8_t* dst, uint64_t dst_cap,
                uint64_t* sizes, uint64_t max_frames) {
  FramePump* p = static_cast<FramePump*>(h);
  uint64_t taken = 0;
  uint64_t written = 0;
  while (taken < max_frames && !p->frames.empty()) {
    const Frame& f = p->frames.front();
    if (written + f.len > dst_cap) {
      if (taken == 0) return -1;  // caller's buffer cannot hold even one
      break;
    }
    if (f.len > 0) std::memcpy(dst + written, p->buf.data() + f.off, f.len);
    sizes[taken] = f.len;
    written += f.len;
    p->body_bytes -= f.len;
    p->frames.pop_front();
    ++taken;
  }
  compact(p);
  return static_cast<int64_t>(taken);
}

// Scatter-gather send of n buffers over a BLOCKING fd: one sendmsg per
// <=kIovCap iovecs, partial-send continuation, EINTR retry. Returns 0 on
// success, -1 on error (errno left for the caller).
int fp_sendv(int fd, const uint8_t** bufs, const uint64_t* lens, uint64_t n) {
  std::vector<iovec> iov(n);
  for (uint64_t i = 0; i < n; ++i) {
    iov[i].iov_base = const_cast<uint8_t*>(bufs[i]);
    iov[i].iov_len = static_cast<size_t>(lens[i]);
  }
  size_t idx = 0;
  while (idx < n) {
    // Skip fully-sent / empty entries so msg_iovlen never counts them.
    if (iov[idx].iov_len == 0) {
      ++idx;
      continue;
    }
    msghdr mh;
    std::memset(&mh, 0, sizeof(mh));
    mh.msg_iov = &iov[idx];
    mh.msg_iovlen = std::min<size_t>(n - idx, kIovCap);
    ssize_t sent = sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (sent < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    size_t s = static_cast<size_t>(sent);
    while (idx < n && s >= iov[idx].iov_len) {
      s -= iov[idx].iov_len;
      ++idx;
    }
    if (s > 0) {
      iov[idx].iov_base = static_cast<uint8_t*>(iov[idx].iov_base) + s;
      iov[idx].iov_len -= s;
    }
  }
  return 0;
}

}  // extern "C"
