// Shared-memory SPSC streaming channel — the native data plane for
// streaming edges between co-located operator instances.
//
// Reference counterpart: streaming/src/channel.h + data_writer.cc /
// data_reader.cc + ring_buffer.cc: a bounded queue on shared memory with
// flow control by capacity, seq-ordered messages, and EOF propagation.
// Re-designed for this runtime: one POSIX shm segment per edge, a
// single-producer/single-consumer byte ring with atomic head/tail (no
// locks on the data path), message framing [u32 len][bytes], and a wrap
// marker so messages stay contiguous for zero-copy reads on the consumer
// side. Backpressure IS the ring: a writer with no room spins with
// backoff until the reader drains (the reference's credit exhaustion).
//
// Single-writer/single-reader is a hard precondition (one channel per
// graph edge instance, like the reference's per-queue writer/reader).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kChanMagic = 0x5450554348414e31ULL;  // "TPUCHAN1"
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;
constexpr uint32_t kFrame = sizeof(uint32_t);

struct ChanHeader {
  uint64_t magic;
  uint64_t capacity;                    // ring data bytes
  std::atomic<uint64_t> head;           // read offset  (consumer-owned)
  std::atomic<uint64_t> tail;           // write offset (producer-owned)
  std::atomic<uint32_t> closed;         // writer finished
  std::atomic<uint32_t> reader_dead;    // consumer gave up (error path)
  std::atomic<uint64_t> messages;       // total messages written (stats)
  uint8_t pad[16];
};

struct ChanHandle {
  uint8_t* base;
  uint64_t mapped;
  ChanHeader* hdr;
  uint8_t* data;
  bool owner;
  char name[256];
};

inline uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

inline void backoff(unsigned& spins) {
  if (spins < 64) {
    ++spins;
  } else {
    usleep(spins < 1024 ? 50 : 500);
    spins = spins < 1024 ? spins * 2 : spins;
  }
}

// Bytes available to read (contiguity handled by wrap markers).
inline uint64_t used(const ChanHeader* h) {
  return h->tail.load(std::memory_order_acquire) -
         h->head.load(std::memory_order_acquire);
}

}  // namespace

extern "C" {

void* tch_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  uint64_t total = sizeof(ChanHeader) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (base) ChanHeader();
  hdr->capacity = capacity;
  hdr->head.store(0);
  hdr->tail.store(0);
  hdr->closed.store(0);
  hdr->reader_dead.store(0);
  hdr->messages.store(0);
  __sync_synchronize();
  hdr->magic = kChanMagic;

  auto* h = new ChanHandle();
  h->base = static_cast<uint8_t*>(base);
  h->mapped = total;
  h->hdr = hdr;
  h->data = h->base + sizeof(ChanHeader);
  h->owner = true;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

void* tch_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      st.st_size < static_cast<off_t>(sizeof(ChanHeader))) {
    close(fd);
    return nullptr;
  }
  uint64_t total = static_cast<uint64_t>(st.st_size);
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<ChanHeader*>(base);
  if (hdr->magic != kChanMagic) {
    munmap(base, total);
    return nullptr;
  }
  auto* h = new ChanHandle();
  h->base = static_cast<uint8_t*>(base);
  h->mapped = total;
  h->hdr = hdr;
  h->data = h->base + sizeof(ChanHeader);
  h->owner = false;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

// 0 = ok, -1 = timeout (ring full), -2 = closed, -3 = message too large.
int tch_write(void* handle, const uint8_t* payload, uint64_t len,
              uint64_t timeout_ms) {
  auto* h = static_cast<ChanHandle*>(handle);
  ChanHeader* hdr = h->hdr;
  uint64_t cap = hdr->capacity;
  uint64_t need = kFrame + len;
  if (need + kFrame > cap) return -3;  // must fit with room for a marker
  if (hdr->closed.load(std::memory_order_acquire)) return -2;

  uint64_t deadline = timeout_ms ? now_ms() + timeout_ms : 0;
  unsigned spins = 0;
  for (;;) {
    uint64_t tail = hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = hdr->head.load(std::memory_order_acquire);
    uint64_t pos = tail % cap;
    uint64_t to_end = cap - pos;
    if (to_end < need) {
      // Frame would straddle the end: emit a wrap marker as its OWN step
      // once it fits, so the reader can consume it and free the burned
      // bytes before the message is attempted. (Checking marker+message
      // together can deadlock: burned-bytes + message may exceed the
      // capacity outright for messages > cap/2 at unlucky positions.)
      if (tail + to_end - head <= cap) {
        if (to_end >= kFrame) {
          uint32_t marker = kWrapMarker;
          std::memcpy(h->data + pos, &marker, kFrame);
        }
        hdr->tail.store(tail + to_end, std::memory_order_release);
        continue;  // progress made; retry from offset 0
      }
    } else if (tail + need - head <= cap) {
      std::memcpy(h->data + pos, &len, kFrame);
      std::memcpy(h->data + pos + kFrame, payload, len);
      hdr->tail.store(tail + need, std::memory_order_release);
      hdr->messages.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
    if (deadline && now_ms() > deadline) return -1;
    backoff(spins);
  }
}

// >= 0: message length copied into buf; -1 timeout; -2 closed + drained;
// -3 buf too small (message length returned via *needed).
int64_t tch_read(void* handle, uint8_t* buf, uint64_t buf_len,
                 uint64_t timeout_ms, uint64_t* needed) {
  auto* h = static_cast<ChanHandle*>(handle);
  ChanHeader* hdr = h->hdr;
  uint64_t cap = hdr->capacity;
  uint64_t deadline = timeout_ms ? now_ms() + timeout_ms : 0;
  unsigned spins = 0;
  for (;;) {
    uint64_t head = hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = hdr->tail.load(std::memory_order_acquire);
    if (tail != head) {
      uint64_t pos = head % cap;
      uint64_t to_end = cap - pos;
      uint32_t len;
      if (to_end < kFrame) {
        // unreadable tail sliver: writer wrapped without a marker
        hdr->head.store(head + to_end, std::memory_order_release);
        continue;
      }
      std::memcpy(&len, h->data + pos, kFrame);
      if (len == kWrapMarker) {
        hdr->head.store(head + to_end, std::memory_order_release);
        continue;
      }
      if (len > buf_len) {
        if (needed) *needed = len;
        return -3;
      }
      std::memcpy(buf, h->data + pos + kFrame, len);
      hdr->head.store(head + kFrame + len, std::memory_order_release);
      return static_cast<int64_t>(len);
    }
    if (hdr->closed.load(std::memory_order_acquire)) return -2;
    if (deadline && now_ms() > deadline) return -1;
    backoff(spins);
  }
}

uint64_t tch_pending_bytes(void* handle) {
  return used(static_cast<ChanHandle*>(handle)->hdr);
}

uint64_t tch_total_messages(void* handle) {
  return static_cast<ChanHandle*>(handle)->hdr->messages.load();
}

void tch_close_write(void* handle) {
  static_cast<ChanHandle*>(handle)
      ->hdr->closed.store(1, std::memory_order_release);
}

// Consumer error path: tells the (possibly blocked) writer that no one
// will ever drain this ring again.
void tch_mark_reader_dead(void* handle) {
  static_cast<ChanHandle*>(handle)
      ->hdr->reader_dead.store(1, std::memory_order_release);
}

int tch_reader_dead(void* handle) {
  return static_cast<int>(
      static_cast<ChanHandle*>(handle)
          ->hdr->reader_dead.load(std::memory_order_acquire));
}

// Unmap; the reader side unlinks the segment (it outlives the writer).
void tch_close(void* handle, int unlink_segment) {
  auto* h = static_cast<ChanHandle*>(handle);
  munmap(h->base, h->mapped);
  if (unlink_segment) shm_unlink(h->name);
  delete h;
}

}  // extern "C"
