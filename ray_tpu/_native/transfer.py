"""ctypes wrapper for the native object-transfer plane (src/transfer.cc).

Reference counterpart: the ObjectManager's Push/Pull service
(object_manager.h:213). The server streams object bytes straight from the
shm arena; fetches land straight into the destination arena. All socket I/O
runs in C with the GIL released — Python only initiates transfers.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Tuple

from .build import load_native_library
from .shm_store import _pad_id


def _lib() -> Optional[ctypes.CDLL]:
    lib = load_native_library("transfer")
    if lib is None or getattr(lib, "_tts_bound", False):
        return lib
    lib.tps_open.restype = ctypes.c_void_p
    lib.tps_open.argtypes = [ctypes.c_char_p]
    lib.tps_close.argtypes = [ctypes.c_void_p]
    lib.tts_serve_start.restype = ctypes.c_void_p
    lib.tts_serve_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tts_serve_port.restype = ctypes.c_int
    lib.tts_serve_port.argtypes = [ctypes.c_void_p]
    lib.tts_serve_stop.argtypes = [ctypes.c_void_p]
    lib.tts_fetch.restype = ctypes.c_int
    lib.tts_fetch.argtypes = [ctypes.c_char_p, ctypes.c_int,
                              ctypes.c_char_p, ctypes.c_void_p]
    lib.tts_connect.restype = ctypes.c_int
    lib.tts_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tts_disconnect.argtypes = [ctypes.c_int]
    lib.tts_fetch_fd.restype = ctypes.c_int
    lib.tts_fetch_fd.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_void_p]
    lib.tts_push.restype = ctypes.c_int
    lib.tts_push.argtypes = [ctypes.c_char_p, ctypes.c_int,
                             ctypes.c_char_p, ctypes.c_void_p]
    lib.tts_fetch_buf.restype = ctypes.c_int64
    lib.tts_fetch_buf.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.tts_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.tts_fetch_range_fd.restype = ctypes.c_int64
    lib.tts_fetch_range_fd.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.tts_serve_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib._tts_bound = True
    return lib


class TransferBrokenError(Exception):
    """The sender died (or the stream broke) mid-pull. ``offset`` is the
    number of bytes already landed in the destination buffer — a retry
    against another holder resumes from exactly there."""

    def __init__(self, offset: int, reason: str = "connection broken"):
        super().__init__(f"{reason} at offset {offset}")
        self.offset = offset


class RemoteMissError(Exception):
    """The remote node does not hold (a sealed copy of) the object."""


class TransferServer:
    """Per-node data-plane server bound to the node's shm arena."""

    def __init__(self, store_name: str, port: int = 0):
        lib = _lib()
        if lib is None:
            raise RuntimeError("native transfer library unavailable")
        self._lib = lib
        self._handle = lib.tps_open(store_name.encode())
        if not self._handle:
            raise RuntimeError(f"cannot open store {store_name!r}")
        self._ctx = lib.tts_serve_start(self._handle, port)
        if not self._ctx:
            lib.tps_close(self._handle)
            raise RuntimeError("transfer server failed to start")
        self.port = lib.tts_serve_port(self._ctx)

    def stats(self) -> Tuple[int, int]:
        """(payload bytes served, requests handled) since start — the
        node's authoritative ``transfer_bytes_out`` source."""
        if not self._ctx:
            return (0, 0)
        bytes_out = ctypes.c_uint64(0)
        requests = ctypes.c_uint64(0)
        self._lib.tts_serve_stats(self._ctx, ctypes.byref(bytes_out),
                                  ctypes.byref(requests))
        return (bytes_out.value, requests.value)

    def stop(self) -> None:
        if self._ctx:
            self._lib.tts_serve_stop(self._ctx)
            self._ctx = None
        if self._handle:
            self._lib.tps_close(self._handle)
            self._handle = None


class TransferClient:
    """Fetch/push objects between this host's arena and remote nodes."""

    def __init__(self, store_name: Optional[str] = None):
        lib = _lib()
        if lib is None:
            raise RuntimeError("native transfer library unavailable")
        self._lib = lib
        self._handle = None
        self._conns: dict = {}  # (host, port) -> fd, persistent
        # One request/response in flight per connection: concurrent fetches
        # to the same peer must not interleave on one socket.
        self._conn_locks: dict = {}
        self._meta_lock = threading.Lock()
        if store_name:
            self._handle = lib.tps_open(store_name.encode())
            if not self._handle:
                raise RuntimeError(f"cannot open store {store_name!r}")

    def _conn_lock(self, host: str, port: int):
        with self._meta_lock:
            lock = self._conn_locks.get((host, port))
            if lock is None:
                lock = self._conn_locks[(host, port)] = threading.Lock()
            return lock

    def _conn(self, host: str, port: int) -> int:
        key = (host, port)
        fd = self._conns.get(key, -1)
        if fd < 0:
            fd = self._lib.tts_connect(host.encode(), port)
            if fd >= 0:
                self._conns[key] = fd
        return fd

    def _drop_conn(self, host: str, port: int) -> None:
        fd = self._conns.pop((host, port), -1)
        if fd >= 0:
            self._lib.tts_disconnect(fd)

    def fetch_into_store(self, host: str, port: int, object_id: bytes) -> bool:
        """Pull a remote object into the local arena (sealed on arrival).
        Reuses a persistent connection (serialized per peer); reconnects once
        on a broken one."""
        if self._handle is None:
            raise RuntimeError("client has no local store")
        oid = _pad_id(object_id)
        with self._conn_lock(host, port):
            for _ in range(2):
                fd = self._conn(host, port)
                if fd < 0:
                    return False
                rc = self._lib.tts_fetch_fd(fd, oid, self._handle)
                if rc == -5:
                    self._drop_conn(host, port)
                    continue
                return rc == 0
        return False

    def probe_size(self, host: str, port: int,
                   object_id: bytes) -> Optional[int]:
        """Ask a holder for an object's total size (a zero-length range
        request — no payload moves). None on miss; TransferBrokenError when
        the holder is unreachable."""
        fd = self._lib.tts_connect(host.encode(), port)
        if fd < 0:
            raise TransferBrokenError(0, "connect failed")
        try:
            total = ctypes.c_uint64(0)
            n = self._lib.tts_fetch_range_fd(fd, _pad_id(object_id), 0, 0,
                                             None, ctypes.byref(total))
            if n == -1:
                return None
            if n < 0:
                raise TransferBrokenError(0)
            return total.value
        finally:
            self._lib.tts_disconnect(fd)

    def fetch_chunks(self, host: str, port: int, object_id: bytes,
                     view, offset: int = 0,
                     chunk_size: int = 1 << 20) -> int:
        """Pull ``view[offset:]`` as a pipeline of fixed-size ranges over a
        dedicated connection, writing each chunk into ``view`` (the
        destination's unsealed arena slot) as it lands. Returns the chunk
        count on completion; raises TransferBrokenError carrying the resume
        offset when the sender dies mid-stream, RemoteMissError when the
        holder no longer has the object.

        A dedicated (non-pooled) connection per pull keeps concurrent
        admitted pulls from the same source streaming in parallel instead
        of serializing on the shared request/response socket."""
        total = len(view)
        oid = _pad_id(object_id)
        fd = self._lib.tts_connect(host.encode(), port)
        if fd < 0:
            raise TransferBrokenError(offset, "connect failed")
        chunks = 0
        try:
            while offset < total:
                want = min(chunk_size, total - offset)
                dst = (ctypes.c_ubyte * want).from_buffer(view, offset)
                remote_total = ctypes.c_uint64(0)
                n = self._lib.tts_fetch_range_fd(
                    fd, oid, offset, want, dst, ctypes.byref(remote_total))
                # Release the buffer export before any raise: a traceback
                # pins this frame, and a pinned export blocks arena close.
                del dst
                if n == -1:
                    raise RemoteMissError(object_id.hex())
                if n < 0 or remote_total.value != total:
                    # Broken stream, or the holder's copy disagrees on size
                    # (a different object under the same id would corrupt
                    # the slot — treat as a bad source and resume elsewhere)
                    raise TransferBrokenError(offset)
                if n == 0:
                    raise TransferBrokenError(offset, "empty range response")
                offset += n
                chunks += 1
            return chunks
        finally:
            self._lib.tts_disconnect(fd)

    def fetch_bytes(self, host: str, port: int,
                    object_id: bytes) -> Optional[bytes]:
        """Pull a remote object into process memory (no arena needed)."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tts_fetch_buf(host.encode(), port, _pad_id(object_id),
                                    ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.tts_buf_free(out)

    def push(self, host: str, port: int, object_id: bytes) -> bool:
        """Push a local arena object to a remote node's arena."""
        if self._handle is None:
            raise RuntimeError("client has no local store")
        rc = self._lib.tts_push(host.encode(), port, _pad_id(object_id),
                                self._handle)
        return rc == 0

    def close(self) -> None:
        for (host, port) in list(self._conns):
            self._drop_conn(host, port)
        if self._handle:
            self._lib.tps_close(self._handle)
            self._handle = None


def available() -> bool:
    return _lib() is not None
