/* C frontend for the ray_tpu cluster (layer-7 native-language API).
 *
 * Reference counterpart: cpp/include/ray/api.h (Ray::Init / Ray::Put /
 * Ray::Get / Ray::Task(...).Remote()). The execution substrate here is the
 * Python+jax worker, so remote calls name an importable Python entrypoint
 * ("module:function") and values cross the boundary as JSON — a C program
 * can orchestrate cluster compute without any Python in its own source.
 *
 * Thread-safe: every call acquires the embedded interpreter's GIL.
 * Error handling: functions return NULL / -1 on failure;
 * ray_tpu_last_error() returns a description (thread-shared, read soon).
 *
 * Strings returned by ray_tpu_* are malloc'd; free with ray_tpu_free().
 */

#ifndef RAY_TPU_C_H
#define RAY_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

/* Connect to a cluster ("host:port") or start a local runtime (NULL/"").
 * Returns 0 on success. */
int ray_tpu_init(const char *address);

/* Disconnect and tear down the runtime. Returns 0 on success. */
int ray_tpu_shutdown(void);

/* Store a JSON-encoded value; returns the object ref as a hex string. */
char *ray_tpu_put_json(const char *json);

/* Fetch an object as JSON. timeout_s <= 0 waits forever. */
char *ray_tpu_get_json(const char *ref_hex, double timeout_s);

/* Submit entrypoint("module:function") with JSON-array args; returns the
 * result's object ref. num_cpus <= 0 uses the default (1). */
char *ray_tpu_submit_json(const char *entrypoint, const char *args_json,
                          double num_cpus);

/* Wait until >= num_returns of the given refs are ready (or timeout).
 * Returns the number ready, or -1 on error. */
int ray_tpu_wait(const char **ref_hexes, int n, int num_returns,
                 double timeout_s);

/* Drop this process's handle on an object ref. Long-running clients MUST
 * release refs they are done with, or the distributed refcount pins every
 * result until shutdown. (ray_tpu_free only frees the string.)
 * Returns 0 on success. */
int ray_tpu_release(const char *ref_hex);

/* ---- actors (reference: the actor templates of cpp/include/ray/api.h,
 * Ray::Actor(Counter::FactoryCreate).Remote() / actor.Task(...)) ---- */

/* Create an actor from an importable Python class ("module:Class") with
 * JSON-array constructor args; returns the actor handle id (hex string).
 * num_cpus <= 0 uses the default (1). */
char *ray_tpu_actor_create(const char *entrypoint, const char *args_json,
                           double num_cpus);

/* Invoke a method on an actor; returns the result's object ref. Method
 * calls on one actor execute in submission order. JSON args may embed
 * {"__ref__": "<hex>"} markers anywhere; each resolves to the value of
 * that object ref at execution time (also honored by
 * ray_tpu_submit_json). */
char *ray_tpu_actor_call_json(const char *actor_hex, const char *method,
                              const char *args_json);

/* Destroy the actor process and drop the handle. Returns 0 on success. */
int ray_tpu_actor_kill(const char *actor_hex);

/* ---- zero-copy array buffers (the payload a TPU framework serves;
 * dlpack-shaped: pointer + dtype + shape) ---- */

#define RAY_TPU_MAX_NDIM 8

typedef struct {
  const void *data;   /* contiguous, C-order; read-only view */
  long long nbytes;
  char dtype[16];     /* numpy dtype name, e.g. "float32" */
  long long shape[RAY_TPU_MAX_NDIM];
  int ndim;
  void *opaque;       /* internal owner; free via ray_tpu_buffer_release */
} ray_tpu_buffer;

/* Store an n-d array from host memory (one copy into the object store;
 * the caller's buffer is not retained). dtype is a numpy dtype name.
 * Returns the object ref as a hex string. */
char *ray_tpu_put_buffer(const void *data, const char *dtype,
                         const long long *shape, int ndim);

/* Fetch an object as a contiguous array view. Fills *out; the view stays
 * valid until ray_tpu_buffer_release(out). timeout_s <= 0 waits forever.
 * Returns 0 on success. */
int ray_tpu_get_buffer(const char *ref_hex, double timeout_s,
                       ray_tpu_buffer *out);

/* Release the array view obtained from ray_tpu_get_buffer. */
void ray_tpu_buffer_release(ray_tpu_buffer *buf);

const char *ray_tpu_last_error(void);

void ray_tpu_free(char *s);

#ifdef __cplusplus
}
#endif

#endif /* RAY_TPU_C_H */
