/* C frontend for the ray_tpu cluster (layer-7 native-language API).
 *
 * Reference counterpart: cpp/include/ray/api.h (Ray::Init / Ray::Put /
 * Ray::Get / Ray::Task(...).Remote()). The execution substrate here is the
 * Python+jax worker, so remote calls name an importable Python entrypoint
 * ("module:function") and values cross the boundary as JSON — a C program
 * can orchestrate cluster compute without any Python in its own source.
 *
 * Thread-safe: every call acquires the embedded interpreter's GIL.
 * Error handling: functions return NULL / -1 on failure;
 * ray_tpu_last_error() returns a description (thread-shared, read soon).
 *
 * Strings returned by ray_tpu_* are malloc'd; free with ray_tpu_free().
 */

#ifndef RAY_TPU_C_H
#define RAY_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

/* Connect to a cluster ("host:port") or start a local runtime (NULL/"").
 * Returns 0 on success. */
int ray_tpu_init(const char *address);

/* Disconnect and tear down the runtime. Returns 0 on success. */
int ray_tpu_shutdown(void);

/* Store a JSON-encoded value; returns the object ref as a hex string. */
char *ray_tpu_put_json(const char *json);

/* Fetch an object as JSON. timeout_s <= 0 waits forever. */
char *ray_tpu_get_json(const char *ref_hex, double timeout_s);

/* Submit entrypoint("module:function") with JSON-array args; returns the
 * result's object ref. num_cpus <= 0 uses the default (1). */
char *ray_tpu_submit_json(const char *entrypoint, const char *args_json,
                          double num_cpus);

/* Wait until >= num_returns of the given refs are ready (or timeout).
 * Returns the number ready, or -1 on error. */
int ray_tpu_wait(const char **ref_hexes, int n, int num_returns,
                 double timeout_s);

/* Drop this process's handle on an object ref. Long-running clients MUST
 * release refs they are done with, or the distributed refcount pins every
 * result until shutdown. (ray_tpu_free only frees the string.)
 * Returns 0 on success. */
int ray_tpu_release(const char *ref_hex);

const char *ray_tpu_last_error(void);

void ray_tpu_free(char *s);

#ifdef __cplusplus
}
#endif

#endif /* RAY_TPU_C_H */
