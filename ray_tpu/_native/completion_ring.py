"""Per-owner shared-memory completion ring (the result data plane).

Reference counterpart: plasma's notification socket — the owner learns
*which* objects sealed without scanning the store or polling the directory
(src/ray/object_manager/plasma/store.cc, NotificationListener). Here the
notification carries slightly more: a fixed-size completion record
``(oid, flags, size)`` and, for results at or under
``RAY_TPU_INLINE_RESULT_MAX`` bytes, the serialized result itself — so the
owner's ``get()`` becomes O(completions-this-wave) ring pops instead of an
O(arena) rescan per wake plus a directory long-poll round trip, and small
results never touch an arena slot at all.

Topology: ONE ring per owner (driver or worker core), created by the owner
and named from its 4-byte job id (``rtcr-<jobhex>``), which every return
ObjectID embeds at bytes [12:16] — an executing worker derives the ring
name from the oid alone, no task-spec change. The consumer side is single
(the owner); the publisher side may be several worker processes, serialized
by an ``flock`` on the ring file (microseconds per publish; the kernel
releases the lock if a publisher dies, so a crash can never wedge the
ring). Mirrors the ``_native/channel`` ring discipline: monotonic u64
head/tail counters over a byte ring, records may straddle the wrap point.

Commit protocol (crash safety): a publisher writes the record body first —
CRC-32 commit word over everything after itself — and only then advances
``head``. A publisher dying mid-write leaves ``head`` unmoved (the partial
bytes are invisible and get overwritten); a record that IS visible but
fails its CRC (torn commit word — in practice only reachable through the
``_debug_publish_torn`` test hook or memory corruption) marks the ring
*degraded*: the consumer stops harvesting and the owner falls back to the
RPC/directory path for everything, while a header flag tells publishers to
stop appending. Delivery through the ring is an optimization layered over
the normal registration flow (``task_done_batch`` still carries every
registration), so a degraded or full ring costs latency, never results.

Backpressure: a publisher that cannot fit a record returns False and moves
on — it NEVER blocks the worker; the result still reaches the owner through
the directory.

Kill switch: ``RAY_TPU_COMPLETION_RING=0`` disables creation and publishing
(A/B and degraded-arena escape hatch).
"""

from __future__ import annotations

import atexit
import fcntl
import mmap
import os
import struct
import threading
import zlib
from typing import List, Optional, Tuple

ID_LEN = 24  # == ObjectID.SIZE

_MAGIC = 0x52435254  # "TRCR"
_VERSION = 1
_HDR = struct.Struct("<IIQQQB")  # magic, version, capacity, head, tail, degraded
_HDR_SIZE = 64                   # header padded to a cache line
_OFF_HEAD = 16
_OFF_TAIL = 24
_OFF_DEGRADED = 32
_U64 = struct.Struct("<Q")

# Record: commit (crc32 of everything after it), total record length,
# oid, flags, object size, inline payload length; inline bytes follow.
_REC = struct.Struct("<II24sBQI")

FLAG_INLINE = 1

_DEFAULT_CAPACITY = 1 << 20


def ring_enabled() -> bool:
    """Kill switch (``RAY_TPU_COMPLETION_RING=0`` pins the old path)."""
    return os.environ.get("RAY_TPU_COMPLETION_RING", "") not in ("0",)


_inline_cache = ("\0unset", 4096)


def inline_result_max() -> int:
    """Results at or under this many serialized bytes ride inside the
    completion record / ``task_done_batch`` item instead of an arena slot
    (``RAY_TPU_INLINE_RESULT_MAX``; 0 disables inlining). Re-read per call
    (tests monkeypatch it) but parsed once per distinct value — this sits
    on the per-result store path."""
    global _inline_cache
    raw = os.environ.get("RAY_TPU_INLINE_RESULT_MAX", "")
    cached = _inline_cache
    if cached[0] == raw:
        return cached[1]
    try:
        val = int(raw) if raw else 4096
    except ValueError:
        val = 4096
    _inline_cache = (raw, max(0, val))
    return _inline_cache[1]


def ring_name(job_bytes: bytes) -> str:
    """Ring name for an owner's 4-byte job id (pass ``oid[12:16]`` to
    resolve the owner of a return object)."""
    return f"rtcr-{job_bytes.hex()}"


def _ring_dir() -> str:
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    import tempfile

    return tempfile.gettempdir()


def ring_path(name: str) -> str:
    return os.path.join(_ring_dir(), name)


class _RingBase:
    """Shared mmap plumbing: wrapped reads/writes over the data region."""

    def __init__(self, fd: int, size: int):
        self._mmap = mmap.mmap(fd, size)
        self.capacity = size - _HDR_SIZE
        self._closed = False

    # -- header cells -------------------------------------------------------
    def _u64(self, off: int) -> int:
        return _U64.unpack_from(self._mmap, off)[0]

    def _set_u64(self, off: int, val: int) -> None:
        _U64.pack_into(self._mmap, off, val)

    @property
    def degraded(self) -> bool:
        return self._closed or self._mmap[_OFF_DEGRADED] != 0

    def has_pending(self) -> bool:
        """Unpopped records exist (racy peek — one mmap read, no lock;
        what the owner's ring-first wait loop watches instead of parking
        on the directory long-poll)."""
        if self._closed:
            return False
        return self._u64(_OFF_HEAD) != self._u64(_OFF_TAIL)

    def _mark_degraded(self) -> None:
        if not self._closed:
            self._mmap[_OFF_DEGRADED] = 1

    # -- wrapped data access ------------------------------------------------
    def _write_at(self, pos: int, data: bytes) -> None:
        """Write into the data region at ring position ``pos`` (monotonic
        counter), wrapping across the capacity boundary."""
        off = pos % self.capacity
        first = min(len(data), self.capacity - off)
        base = _HDR_SIZE + off
        self._mmap[base:base + first] = data[:first]
        if first < len(data):
            self._mmap[_HDR_SIZE:_HDR_SIZE + len(data) - first] = data[first:]

    def _read_at(self, pos: int, n: int) -> bytes:
        off = pos % self.capacity
        first = min(n, self.capacity - off)
        base = _HDR_SIZE + off
        out = self._mmap[base:base + first]
        if first < n:
            out += self._mmap[_HDR_SIZE:_HDR_SIZE + n - first]
        return out

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._mmap.close()
            except (BufferError, ValueError):
                pass


class CompletionRing(_RingBase):
    """Owner (consumer) side: creates the segment, pops records.

    Single consumer by contract; ``pop_all`` is additionally guarded by an
    in-process lock so concurrent ``get()``/``wait()``/resolver threads in
    the owner can share one ring safely.
    """

    def __init__(self, name: str, capacity: int = 0, create: bool = True):
        capacity = capacity or int(os.environ.get(
            "RAY_TPU_COMPLETION_RING_BYTES", _DEFAULT_CAPACITY))
        self.name = name
        self.path = ring_path(name)
        self._owner = create
        self._lock_fd = -1
        size = _HDR_SIZE + capacity
        if create:
            # Liveness sidecar: the owner holds an flock on <ring>.lock
            # for its lifetime (kernel-released even on SIGKILL), so
            # sweep_stale_rings can tell a crashed owner's leftover ring
            # from a live one. Taken BEFORE the ring exists: a ring is
            # never visible without its lock held.
            self._lock_fd = os.open(self.path + ".lock",
                                    os.O_RDWR | os.O_CREAT, 0o600)
            fcntl.flock(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            # A stale segment (4-byte job-id collision with a crashed
            # owner) must not feed us someone else's records.
            try:
                os.unlink(self.path)
            except OSError:
                pass
            fd = os.open(self.path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, size)
                super().__init__(fd, size)
            finally:
                os.close(fd)
            _HDR.pack_into(self._mmap, 0, _MAGIC, _VERSION, capacity, 0, 0, 0)
        else:
            fd = os.open(self.path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                super().__init__(fd, size)
            finally:
                os.close(fd)
            self._check_header()
        self._lock = threading.Lock()
        self.torn_records = 0
        if create:
            atexit.register(self.close)

    def _check_header(self) -> None:
        magic, version, capacity = _HDR.unpack_from(self._mmap, 0)[:3]
        if magic != _MAGIC or version != _VERSION \
                or capacity != self.capacity:
            raise OSError(f"bad completion ring header: {self.path}")

    def pop_all(self, limit: int = 1 << 16
                ) -> List[Tuple[bytes, int, int, Optional[bytes]]]:
        """Drain committed records: [(oid, flags, size, inline|None)].
        A CRC mismatch marks the ring degraded and stops the harvest —
        the caller falls back to the RPC/directory path."""
        out: List[Tuple[bytes, int, int, Optional[bytes]]] = []
        if self._closed:
            return out
        with self._lock:
            if self.degraded:
                return out
            head = self._u64(_OFF_HEAD)
            tail = self._u64(_OFF_TAIL)
            while tail < head and len(out) < limit:
                hdr = self._read_at(tail, _REC.size)
                commit, total, oid, flags, size, inline_len = \
                    _REC.unpack(hdr)
                if (total < _REC.size or total > self.capacity
                        or inline_len != total - _REC.size
                        or tail + total > head):
                    self.torn_records += 1
                    self._mark_degraded()
                    break
                body = self._read_at(tail + 4, total - 4)
                if zlib.crc32(body) != commit:
                    self.torn_records += 1
                    self._mark_degraded()
                    break
                inline = body[_REC.size - 4:] if flags & FLAG_INLINE else None
                out.append((oid, flags, size, inline))
                tail += total
            self._set_u64(_OFF_TAIL, tail)
        return out

    # -- test hook ----------------------------------------------------------
    def _debug_publish_torn(self) -> None:
        """Inject a committed-looking record with a corrupt commit word —
        what a worker dying between the head bump and the body write of a
        hypothetical reserve-first protocol would leave behind. Drives the
        crash-safety test for the degraded-ring fallback."""
        with self._lock:
            head = self._u64(_OFF_HEAD)
            rec = _REC.pack(0xDEADBEEF, _REC.size, b"\0" * ID_LEN, 0, 0, 0)
            self._write_at(head, rec)
            self._set_u64(_OFF_HEAD, head + _REC.size)

    def close(self) -> None:
        if self._closed:
            return
        super().close()
        if self._owner:
            for p in (self.path, self.path + ".lock"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        if self._lock_fd >= 0:
            try:
                os.close(self._lock_fd)
            except OSError:
                pass
            self._lock_fd = -1


class RingPublisher(_RingBase):
    """Worker (producer) side: opens an owner's ring by name and appends
    completion records. Multiple publisher processes are serialized by an
    flock on the ring file (auto-released by the kernel on death)."""

    def __init__(self, name: str):
        self.name = name
        self.path = ring_path(name)
        self._fd = os.open(self.path, os.O_RDWR)
        try:
            size = os.fstat(self._fd).st_size
            super().__init__(self._fd, size)
        except BaseException:
            os.close(self._fd)
            raise
        magic, version, capacity = _HDR.unpack_from(self._mmap, 0)[:3]
        if magic != _MAGIC or version != _VERSION \
                or capacity != self.capacity:
            self.close()
            raise OSError(f"bad completion ring header: {self.path}")
        self._tlock = threading.Lock()  # flock is per-fd, not per-thread

    def publish(self, oid: bytes, size: int,
                inline: Optional[bytes] = None) -> bool:
        """Append one completion record. Returns False — never blocks on
        ring space — when the ring is full/degraded/closed; the caller's
        result still reaches the owner through the directory path."""
        if self._closed:
            return False
        flags = FLAG_INLINE if inline is not None else 0
        payload = inline or b""
        total = _REC.size + len(payload)
        if total > self.capacity // 4:
            return False  # oversized record: directory path serves it
        body = _REC.pack(0, total, oid, flags, size, len(payload))[4:] \
            + payload
        rec = struct.pack("<I", zlib.crc32(body)) + body
        with self._tlock:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX)
            except OSError:
                return False
            try:
                if self.degraded:
                    return False
                head = self._u64(_OFF_HEAD)
                tail = self._u64(_OFF_TAIL)
                if total > self.capacity - (head - tail):
                    return False  # full: backpressure == fall back, not block
                self._write_at(head, rec)
                # Publish AFTER the full body (incl. commit word) is in
                # place: a crash before this line leaves head unmoved and
                # the partial record invisible.
                self._set_u64(_OFF_HEAD, head + total)
                return True
            finally:
                try:
                    fcntl.flock(self._fd, fcntl.LOCK_UN)
                except OSError:
                    pass

    def close(self) -> None:
        if not self._closed:
            super().close()
            try:
                os.close(self._fd)
            except OSError:
                pass


def open_publisher(name: str) -> Optional[RingPublisher]:
    """Open an owner's ring for publishing; None when it doesn't exist on
    this host (cross-host owner, ring disabled, or owner gone)."""
    try:
        return RingPublisher(name)
    except OSError:
        return None


def scan_stale_rings() -> int:
    """Non-destructive twin of :func:`sweep_stale_rings`: count rings whose
    owner's liveness flock has lapsed (dead owner, ~1 MiB tmpfs leaked
    each) WITHOUT unlinking anything. The consistency auditor reports the
    count (an ``audit_stale_ring`` finding); the janitor sweep on the next
    controller start — or an operator running it by hand — reclaims them."""
    stale = 0
    try:
        names = os.listdir(_ring_dir())
    except OSError:
        return 0
    for fn in names:
        if not fn.startswith("rtcr-") or fn.endswith(".lock"):
            continue
        lock_path = ring_path(fn) + ".lock"
        try:
            lfd = os.open(lock_path, os.O_RDWR)
        except OSError:
            stale += 1  # no liveness lock at all: pre-lock leftover
            continue
        try:
            try:
                fcntl.flock(lfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # owner alive
            fcntl.flock(lfd, fcntl.LOCK_UN)
            stale += 1
        finally:
            os.close(lfd)
    return stale


def sweep_stale_rings() -> int:
    """Janitor: unlink rings whose owner died without close() (SIGKILLed
    worker, crashed driver) — each leaks ~1 MiB of tmpfs otherwise. An
    owner holds an flock on ``<ring>.lock`` for its whole lifetime, so
    winning a non-blocking flock proves the owner is gone; a ring with no
    lock file at all predates its owner's lock (impossible in this
    protocol) or lost it — stale either way. Called on node-controller
    start; safe to run concurrently with live rings and with other
    sweepers (unlink is idempotent, the flock serializes the verdict)."""
    removed = 0
    try:
        names = os.listdir(_ring_dir())
    except OSError:
        return 0
    for fn in names:
        if not fn.startswith("rtcr-") or fn.endswith(".lock"):
            continue
        path = ring_path(fn)
        lock_path = path + ".lock"
        try:
            lfd = os.open(lock_path, os.O_RDWR)
        except OSError:
            # No liveness lock: pre-lock leftover. Unlink.
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
            continue
        try:
            try:
                fcntl.flock(lfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # owner alive
            for p in (path, lock_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            removed += 1
        finally:
            os.close(lfd)
    return removed
