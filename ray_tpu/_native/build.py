"""Lazy native build: compile C++ sources to shared libraries with g++.

No pip/apt at runtime, so the toolchain contract is just "g++ exists". The
built .so is cached next to the sources and rebuilt when the source is newer
(mtime). Import never raises: callers get None on failure and are expected
to fall back to a Python implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_lock = threading.Lock()
_cache: dict = {}


def _san_mode() -> str:
    """Sanitizer build mode (reference: ci/asan_tests): RAY_TPU_NATIVE_SAN
    = "asan" compiles the native libraries with ASAN+UBSAN, "tsan" with
    ThreadSanitizer (-O1 -g either way, own .so names so sanitized and
    plain builds never share a cache slot). dlopen'ing a sanitized .so
    requires the matching runtime preloaded — the harness for both modes
    is scripts/native_san.py."""
    return os.environ.get("RAY_TPU_NATIVE_SAN", "").lower()


def _san_flags():
    mode = _san_mode()
    if mode == "asan":
        return ["-fsanitize=address,undefined", "-g", "-O1"]
    if mode == "tsan":
        return ["-fsanitize=thread", "-g", "-O1"]
    return ["-O2"]


def _san_suffix() -> str:
    mode = _san_mode()
    return f".{mode}" if mode in ("asan", "tsan") else ""


def _needs_build(src: str, out: str) -> bool:
    if not os.path.exists(out):
        return True
    # Sources #include each other (transfer.cc pulls in shm_store.cc), so
    # any newer .cc in the dir invalidates the build.
    newest = max(
        os.path.getmtime(os.path.join(_SRC_DIR, f))
        for f in os.listdir(_SRC_DIR) if f.endswith(".cc"))
    return newest > os.path.getmtime(out)


def build_c_api() -> Optional[str]:
    """Build the embeddable C frontend (src/capi.cc + CPython) into
    _build/libray_tpu_c.so; returns the path, or None on failure.

    Not dlopen'd here — the consumer is a C/C++ program linking
    -lray_tpu_c against include/ray_tpu_c.h (see tests/native/test_capi.c).
    """
    import sysconfig

    src = os.path.join(_SRC_DIR, "capi.cc")
    out = os.path.join(_BUILD_DIR, f"libray_tpu_c{_san_suffix()}.so")
    try:
        if _needs_build(src, out):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            inc = sysconfig.get_paths()["include"]
            own_inc = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "include")
            libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
            # LDVERSION carries ABI flags (e.g. "3.13t"); VERSION alone
            # fails to link on abiflagged builds.
            pylib = "python" + (sysconfig.get_config_var("LDVERSION")
                                or sysconfig.get_config_var("VERSION")
                                or "3")
            tmp = out + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", *_san_flags(), "-shared", "-fPIC", "-std=c++17",
                 "-Wall", f"-I{inc}", f"-I{own_inc}", "-o", tmp, src,
                 f"-L{libdir}", f"-Wl,-rpath,{libdir}", f"-l{pylib}",
                 "-lpthread"],
                check=True, capture_output=True, timeout=180,
            )
            os.replace(tmp, out)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def build_native_library(name: str) -> Optional[str]:
    """Compile src/<name>.cc -> _build/lib<name>[.asan].so (honoring the
    RAY_TPU_NATIVE_SAN sanitizer mode) without dlopen'ing it; returns the
    .so path or None on failure. Split out of load_native_library so the
    sanitizer harness can verify a clean ASAN+UBSAN compile of every
    library even though a sanitized .so cannot be dlopen'd into a plain
    python process."""
    src = os.path.join(_SRC_DIR, f"{name}.cc")
    out = os.path.join(_BUILD_DIR, f"lib{name}{_san_suffix()}.so")
    try:
        if _needs_build(src, out):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = out + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", *_san_flags(), "-shared", "-fPIC", "-std=c++17",
                 "-Wall", "-o", tmp, src, "-lpthread", "-lrt"],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, out)  # atomic under concurrent builders
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def load_native_library(name: str) -> Optional[ctypes.CDLL]:
    """Builds (if stale) and dlopens src/<name>.cc -> _build/lib<name>.so."""
    with _lock:
        if name in _cache:
            return _cache[name]
        out = build_native_library(name)
        lib: Optional[ctypes.CDLL] = None
        if out is not None:
            try:
                lib = ctypes.CDLL(out)
            except OSError:
                lib = None
        _cache[name] = lib
        return lib
