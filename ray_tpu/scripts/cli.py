"""CLI (reference: python/ray/scripts/scripts.py — ray start/stop/status/
memory/timeline/microbenchmark/kill_random_node).

argparse instead of click (not baked into the image). Session state (head
process pid, GCS address, worker pids) lives in a JSON session file so
``stop``/``status`` can find the cluster started by ``start``.

    python -m ray_tpu.scripts.cli start --head --num-workers 4
    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli stop
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

SESSION_FILE = os.environ.get(
    "RAY_TPU_SESSION_FILE", "/tmp/ray_tpu_session.json")


def _load_session() -> Dict:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _save_session(state: Dict) -> None:
    with open(SESSION_FILE, "w") as f:
        json.dump(state, f)


def _gcs_client(address: Optional[str]):
    from ray_tpu.cluster.protocol import RpcClient

    if address is None:
        address = _load_session().get("address")
    if address is None:
        raise SystemExit("no running cluster (and no --address given)")
    host, port = address.rsplit(":", 1)
    return RpcClient(host, int(port))


# ---------------------------------------------------------------- commands

def _launch_env() -> Dict[str, str]:
    """Env for spawned cluster processes: importable package, no TPU-tunnel
    claim at interpreter startup (same scrubbing as cluster.testing)."""
    import ray_tpu

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _launch_head(resources: Dict, num_workers: int, port: int = 0):
    """Start a head process; returns (address, pid, log_path)."""
    cmd = [sys.executable, "-m", "ray_tpu.cluster.launch", "head",
           "--port", str(port),
           "--resources", json.dumps(resources),
           "--num-workers", str(num_workers)]
    # Output goes to LOG FILES, never a pipe: the head outlives this CLI
    # process, and an unread pipe fills after ~64KB of worker logs and
    # then blocks the controller's event loop on print() — wedging the
    # whole node (observed: register_worker RPCs timing out).
    log_path = f"/tmp/ray_tpu_head_{os.getpid()}.log"
    out = open(log_path, "w")
    proc = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT,
                            env=_launch_env())
    # wait for the gcs_started event line to appear in the log
    deadline = time.monotonic() + 60
    gcs_port = None
    with open(log_path) as tail:
        while time.monotonic() < deadline and gcs_port is None:
            line = tail.readline()
            if not line:
                if proc.poll() is not None:
                    raise SystemExit(
                        f"head process died during startup; see {log_path}")
                time.sleep(0.05)
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if event.get("event") == "gcs_started":
                gcs_port = event["port"]
    if gcs_port is None:
        proc.kill()
        raise SystemExit("timed out waiting for GCS startup")
    return f"127.0.0.1:{gcs_port}", proc.pid, log_path


def _launch_worker_node(address: str, resources: Dict, num_workers: int,
                        label: str = "") -> int:
    """Start a worker node joined to ``address``; returns its pid."""
    cmd = [sys.executable, "-m", "ray_tpu.cluster.launch", "node",
           "--gcs", address,
           "--resources", json.dumps(resources),
           "--num-workers", str(num_workers)]
    if label:
        cmd += ["--label", label]
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=_launch_env())
    return proc.pid


def cmd_start(args) -> None:
    resources = json.loads(args.resources) if args.resources else {"CPU": 4}
    if args.head:
        address, pid, log_path = _launch_head(
            resources, args.num_workers, args.port)
        _save_session({"address": address, "head_pid": pid,
                       "worker_pids": [], "head_log": log_path})
        print(f"started head: address={address} pid={pid}")
        print(f"logs: {log_path}")
        print(f"connect with ray_tpu.init(address={address!r})")
        return

    if not args.address:
        raise SystemExit("--address required to start a worker node")
    pid = _launch_worker_node(args.address, resources, args.num_workers)
    state = _load_session()
    state.setdefault("worker_pids", []).append(pid)
    _save_session(state)
    print(f"started worker node pid={pid} -> {args.address}")


def _read_cluster_config(path: str) -> Dict:
    with open(path) as f:
        text = f.read()
    cfg = None
    try:
        cfg = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # noqa: PLC0415 - optional, like the reference's

            cfg = yaml.safe_load(text)
        except ImportError:
            raise SystemExit(
                f"{path} is not valid JSON and pyyaml is unavailable")
        except Exception as e:  # noqa: BLE001 - yaml syntax errors
            raise SystemExit(f"{path} is not valid JSON or YAML: {e}")
    if not isinstance(cfg, dict):
        raise SystemExit(
            f"{path} must parse to a mapping with 'head'/'worker_nodes' "
            f"keys, got {type(cfg).__name__}")
    return cfg


def cmd_up(args) -> None:
    """Bring up a whole cluster from a config file (reference: ray up,
    scripts.py:659 — minus cloud provisioning: node groups become local
    ``launch node`` processes, the same substrate the autoscaler's
    SubprocessProvider scales).

    Config (JSON or YAML):
        {"head": {"resources": {"CPU": 4}, "num_workers": 2},
         "worker_nodes": [
             {"resources": {"CPU": 4}, "count": 2, "num_workers": 2}]}
    """
    cfg = _read_cluster_config(args.config)
    head = cfg.get("head", {})
    address, head_pid, log_path = _launch_head(
        head.get("resources", {"CPU": 4}), head.get("num_workers", 2))
    worker_pids = []
    provider_nodes = []
    n_nodes = 0
    provider_cfg = cfg.get("provider")
    if provider_cfg:
        # Cloud path (reference: ray up provisioning via NodeProvider —
        # autoscaler/commands.py): e.g. {"type": "gce_tpu", "project": ...,
        # "zone": ..., "accelerator_type": ..., "runtime_version": ...}.
        # TPU VMs join the head via their startup script.
        from ray_tpu.autoscaler.gce import make_provider
        from ray_tpu.autoscaler.node_provider import (
            STATUS_UP_TO_DATE, TAG_NODE_KIND, TAG_NODE_STATUS,
        )

        provider_cfg = dict(provider_cfg, gcs_address=address)
        # Scope cloud nodes to THIS cluster (cluster-name label) so stop/
        # down can never touch another cluster's VMs in the same zone.
        provider_cfg.setdefault("cluster_name", cfg.get(
            "cluster_name",
            os.path.splitext(os.path.basename(args.config))[0]))
        provider = make_provider(provider_cfg)
        tags = {TAG_NODE_KIND: "worker", TAG_NODE_STATUS: STATUS_UP_TO_DATE}
        for group in cfg.get("worker_nodes", [{}]):
            provider.create_node(group, tags, group.get("count", 1))
        provider_nodes = provider.non_terminated_nodes({})
        n_nodes = len(provider_nodes)
        # Subprocess nodes are owned by THIS process; record pids so
        # `cli down` (a different process) can stop them. Cloud nodes are
        # API-addressable and torn down through the provider instead.
        if hasattr(provider, "_procs"):
            worker_pids = [p.pid for p in provider._procs.values()]
    else:
        for group_idx, group in enumerate(cfg.get("worker_nodes", [])):
            for i in range(group.get("count", 1)):
                worker_pids.append(_launch_worker_node(
                    address, group.get("resources", {"CPU": 4}),
                    group.get("num_workers", 2),
                    label=f"group{group_idx}-{i}"))
                n_nodes += 1
    _save_session({"address": address, "head_pid": head_pid,
                   "worker_pids": worker_pids, "head_log": log_path,
                   "provider": provider_cfg, "provider_nodes": provider_nodes,
                   "config": os.path.abspath(args.config)})
    print(f"cluster up: address={address} head_pid={head_pid} "
          f"worker_nodes={n_nodes}")
    print(f"connect with ray_tpu.init(address={address!r})")


def cmd_down(args) -> None:
    """Tear down the session's cluster (reference: ray down,
    scripts.py:703)."""
    cmd_stop(args)


def cmd_stop(args) -> None:
    state = _load_session()
    stopped = 0
    # Cloud provider nodes (TPU VMs) are released through the provider API;
    # local subprocess-provider nodes were recorded by pid at `up` time.
    if (state.get("provider") or {}).get("type") == "gce_tpu":
        try:
            from ray_tpu.autoscaler.gce import make_provider

            provider = make_provider(state["provider"])
            # Union of the nodes recorded at `up` time and a live API query:
            # the autoscaler may have launched more since (a TPU VM missed
            # here keeps running AND billing).
            nodes = set(state.get("provider_nodes") or [])
            try:
                nodes |= set(provider.non_terminated_nodes({}))
            except Exception:  # noqa: BLE001 - API hiccup: use saved list
                pass
            for nid in nodes:
                provider.terminate_node(nid)
                stopped += 1
        except Exception as e:  # noqa: BLE001 - still stop local processes
            print(f"provider teardown failed: {e}")
    for pid in state.get("worker_pids", []) + (
            [state["head_pid"]] if "head_pid" in state else []):
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    # grace period, then hard kill
    time.sleep(1.0)
    for pid in state.get("worker_pids", []) + (
            [state["head_pid"]] if "head_pid" in state else []):
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    try:
        os.unlink(SESSION_FILE)
    except OSError:
        pass
    print(f"stopped {stopped} processes")


def cmd_status(args) -> None:
    gcs = _gcs_client(args.address)
    try:
        try:
            ha = gcs.call({"type": "ha_status"})
            line = (f"leadership: {ha.get('role', '?')} "
                    f"epoch={ha.get('epoch', 0)} "
                    f"failovers={ha.get('failover_count', 0)}")
            if ha.get("role") == "standby":
                line += f" lag_bytes={ha.get('standby_lag_bytes', 0)}"
            if ha.get("failover_count"):
                line += (f" last_recovery="
                         f"{ha.get('time_to_recover_s', 0.0):.2f}s")
            print(line)
        except RuntimeError:
            pass  # pre-HA GCS without the ha_status handler
        nodes = gcs.call({"type": "list_nodes"})["nodes"]
        res = gcs.call({"type": "cluster_resources"})
        print(f"nodes: {sum(n['Alive'] for n in nodes)} alive / {len(nodes)}")
        for n in nodes:
            state = ("DRAINING" if n["Alive"] and n.get("Draining")
                     else "ALIVE" if n["Alive"] else "DEAD")
            print(f"  {n['NodeID'][:12]} {state:<8} {n['Resources']}")
        print(f"total resources:     {res['total']}")
        print(f"available resources: {res['available']}")
        groups = gcs.call({"type": "list_placement_groups"})["groups"]
        if groups:
            by_state: Dict[str, int] = {}
            for g in groups.values():
                by_state[g["state"]] = by_state.get(g["state"], 0) + 1
            detail = " ".join(f"{k.lower()}={v}"
                              for k, v in sorted(by_state.items()))
            print(f"placement groups:    {len(groups)} ({detail})")
        # Per-phase latency table from the GCS handler stats (the same
        # cells scripts/cluster_lat.py harvests): avg wall per item for the
        # server-side phases of the 7-phase profiler.
        handlers = gcs.call({"type": "debug_stats"})["handlers"]
        phase_cells = [(k[len("phase:"):], h) for k, h in handlers.items()
                       if k.startswith("phase:")]
        if phase_cells:
            print("control-plane phases (GCS-side, cumulative):")
            print(f"  {'PHASE':<18} {'ITEMS':>10} {'TOTAL_S':>10} "
                  f"{'AVG_US':>9}")
            for name, h in phase_cells:
                avg_us = (h["total_s"] / h["count"] * 1e6
                          if h["count"] else 0.0)
                print(f"  {name:<18} {h['count']:>10} "
                      f"{h['total_s']:>10.4f} {avg_us:>9.1f}")
            relay = {k: handlers[k]["count"]
                     for k in ("relay:opaque", "relay:pickled")
                     if k in handlers}
            if relay:
                print(f"  dispatch relay: {relay}")
        if getattr(args, "verbose", False):
            # Per-RPC handler timings (bg:<type> = detached completion
            # time): the cProfile-free view of where GCS cycles go.
            stats = gcs.call({"type": "debug_stats"})["handlers"]
            print("GCS handlers (busiest first):")
            for mtype, h in stats.items():
                print(f"  {mtype:<24} {h['count']:>8} calls "
                      f"{h['total_s']:>10.4f} s")
    finally:
        gcs.close()


def cmd_memory(args) -> None:
    gcs = _gcs_client(args.address)
    try:
        if getattr(args, "refs", False):
            # Reference accounting view (reference: `ray memory` backed by
            # the dashboard memory.py ref table).
            refs = gcs.call({"type": "ref_table",
                             "limit": args.limit})["refs"]
            print(f"{len(refs)} tracked objects")
            print(f"{'OBJECT_ID':<44} {'SIZE':>10} {'PINS':>5} "
                  f"{'NESTED':>6}  HOLDERS")
            for oid, info in sorted(refs.items(),
                                    key=lambda kv: -kv[1]["size"]):
                holders = ",".join(h[:14] for h in info["holders"]) or "-"
                print(f"{oid:<44} {info['size']:>10} "
                      f"{info['task_pins']:>5} "
                      f"{info['contained_children']:>6}  {holders}")
            return
        objs = gcs.call({"type": "list_objects", "limit": args.limit})["objects"]
        print(f"{len(objs)} objects in the cluster object table")
        print(f"{'OBJECT_ID':<44} {'SIZE':>12}  LOCATIONS")
        for oid, info in sorted(objs.items(), key=lambda kv: -kv[1]["size"]):
            locs = ",".join(str(l)[:12] for l in info["locations"])
            print(f"{oid:<44} {info['size']:>12}  {locs}")
    finally:
        gcs.close()


# Human explanations for the scheduler's pending-reason attribution
# (`cli task <id>` why-pending line; cli tasks legend).
_WHY_PENDING = {
    "waiting-for-deps": "an argument object has no live copy yet — its "
                        "producer is still running, failed, or the copy "
                        "is being recovered from lineage/spill",
    "waiting-for-capacity": "the task fits the fleet's total resources "
                            "but every node is busy; it will place when "
                            "running work releases capacity",
    "infeasible": "the demand fits NO node even when idle — the cluster "
                  "needs bigger/different nodes (this feeds the "
                  "autoscaler's pending-demand view)",
    "waiting-for-pg": "the task targets a placement group whose gang "
                      "reservation is not CREATED yet (check `cli pgs` "
                      "for the group's own pending reason)",
    "quota-throttled": "held back by an admission quota/weight policy",
    "unclassified": "submitted but not yet seen by a placement tick",
}


def _fmt_age(now: float, ts: float) -> str:
    if not ts:
        return "-"
    d = max(now - ts, 0.0)
    if d < 120:
        return f"{d:.1f}s"
    if d < 7200:
        return f"{d / 60:.1f}m"
    return f"{d / 3600:.1f}h"


def cmd_tasks(args) -> None:
    """State API v2 task table: per-state summary plus a filtered,
    paginated row listing with lifecycle ages and pending reasons."""
    gcs = _gcs_client(args.address)
    try:
        summ = gcs.call({"type": "task_summary"})
        states = " ".join(f"{k.lower()}={v}"
                          for k, v in sorted(summ["states"].items()))
        print(f"{summ['total']} tasks in table  {states or '-'}")
        reasons = summ.get("pending_reasons") or {}
        if reasons:
            print("pending by reason: " + "  ".join(
                f"{k}={v}" for k, v in sorted(reasons.items())))
        msg = {"type": "list_tasks", "limit": args.limit,
               "offset": args.offset}
        for key, val in (("state", args.state), ("kind", args.kind),
                         ("reason", args.reason),
                         ("name_contains", args.name)):
            if val:
                msg[key] = val
        resp = gcs.call(msg)
        rows = resp["tasks"]
        now = time.time()
        print(f"showing {len(rows)} of {resp['total']} matching "
              f"(offset {args.offset})"
              + (" — truncated, page with --offset"
                 if resp.get("truncated") else ""))
        if not rows:
            return
        print(f"{'TASK_ID':<18} {'KIND':<6} {'STATE':<11} {'AGE':>7} "
              f"{'RUN':>7} {'NODE':<10} {'REASON':<21} NAME")
        for t in rows:
            run = (_fmt_age(t["ts_finish"] or now, t["ts_dispatch"])
                   if t["ts_dispatch"] else "-")
            print(f"{t['task_id'][:16]:<18} {t['kind']:<6} "
                  f"{t['state']:<11} {_fmt_age(now, t['ts_submit']):>7} "
                  f"{run:>7} {(t['node_id'] or '-')[:8]:<10} "
                  f"{(t['pending_reason'] or '-'):<21} "
                  f"{t['name'][:32]}")
    finally:
        gcs.close()


def cmd_task(args) -> None:
    """One task in full detail, with a human 'why pending' line for
    PENDING tasks (the scheduler's per-tick reason attribution)."""
    gcs = _gcs_client(args.address)
    try:
        try:
            resp = gcs.call({"type": "get_task", "task_id": args.id})
        except RuntimeError as e:
            # ok:False responses (unknown id, ambiguous prefix) surface
            # as RuntimeError from the RPC client.
            raise SystemExit(f"task lookup failed: {e}")
        t = resp["task"]
        now = time.time()
        print(f"task   {t['task_id']}")
        print(f"kind   {t['kind']}   state {t['state']}"
              + (f"   node {t['node_id'][:12]}" if t["node_id"] else "")
              + ("   CANCELLED" if t["cancelled"] else ""))
        if t.get("name"):
            print(f"name   {t['name']}")
        print(f"submitted {_fmt_age(now, t['ts_submit'])} ago"
              + (f"; dispatched {_fmt_age(now, t['ts_dispatch'])} ago"
                 if t["ts_dispatch"] else "")
              + (f"; finished {_fmt_age(now, t['ts_finish'])} ago"
                 if t["ts_finish"] else ""))
        if t.get("resources"):
            print(f"resources {t['resources']}")
        print(f"retries_left {t['retries_left']} / "
              f"max {t.get('max_retries', 0)}")
        if t.get("deps"):
            missing = set(t.get("deps_missing") or ())
            print(f"deps ({len(t['deps'])}, {len(missing)} missing):")
            for d in t["deps"][:16]:
                print(f"  {d[:32]}{'  MISSING' if d in missing else ''}")
        if t["state"] == "PENDING":
            reason = t["pending_reason"] or "unclassified"
            why = _WHY_PENDING.get(reason, "")
            print(f"why pending: {reason} — {why}")
        if t.get("timeout_s"):
            print(f"deadline {t['timeout_s']}s")
        if t.get("failure_cause"):
            line = f"failure cause: {t['failure_cause']}"
            if t.get("failure_error"):
                line += f" — {t['failure_error']}"
            print(line)
        q = t.get("quarantined_fn")
        if q:
            print(f"function QUARANTINED after {q.get('strikes', 0)} "
                  f"worker-fatal strikes "
                  f"(clear: cli quarantine --clear {q.get('fn_id', '')[:16]})")
    finally:
        gcs.close()


def cmd_jobs(args) -> None:
    """Per-job rollup over the task table: counts, wall-clock bounds,
    and — for jobs the GCS profiler already analyzed — the critical
    path length and scheduler-efficiency ratio."""
    gcs = _gcs_client(args.address)
    try:
        rows = gcs.call({"type": "list_jobs"})["jobs"]
        if not rows:
            print("no jobs in the task table")
            return
        now = time.time()
        print(f"{'JOB_ID':<10} {'TASKS':>6} {'ACTIVE':<7} {'AGE':>7} "
              f"{'SPAN':>8} {'EFF':>6} {'CP':>5}  STATES")
        for j in rows:
            span = "-"
            if j.get("makespan_s"):
                span = f"{j['makespan_s']:.2f}s"
            elif j["ts_last_finish"] and j["ts_first_submit"]:
                span = f"{j['ts_last_finish'] - j['ts_first_submit']:.2f}s"
            eff = (f"{j['efficiency']:.2f}"
                   if j.get("efficiency") is not None else "-")
            cp = str(j.get("critical_len", "-"))
            states = " ".join(f"{k.lower()}={v}"
                              for k, v in sorted(j["states"].items()))
            print(f"{j['job_id']:<10} {j['tasks']:>6} "
                  f"{('yes' if j.get('active') else 'no'):<7} "
                  f"{_fmt_age(now, j['ts_first_submit']):>7} {span:>8} "
                  f"{eff:>6} {cp:>5}  {states}")
    finally:
        gcs.close()


def cmd_job(args) -> None:
    """One job's critical-path profile: the longest duration-weighted
    path to sink, each hop's blocked-time decomposition, the blocked
    rollup by pending reason, per-node skew, and the
    scheduler-efficiency ratio (critical-path exec lower bound over
    actual makespan — 1.0 means no scheduler could have run this DAG's
    recorded exec times any faster)."""
    gcs = _gcs_client(args.address)
    try:
        msg = {"type": "job_profile",
               "include_rows": bool(args.timeline)}
        if args.id:
            msg["job_id"] = args.id
        try:
            resp = gcs.call(msg, timeout=180.0)
        except RuntimeError as e:
            raise SystemExit(f"job lookup failed: {e}")
        prof = resp["profile"]
        states = " ".join(f"{k.lower()}={v}"
                          for k, v in sorted(prof["states"].items()))
        print(f"job      {prof['job_id']}  ({prof['num_tasks']} tasks: "
              f"{states})")
        print(f"makespan {prof['makespan_s']:.3f}s   critical path "
              f"{prof['critical_len']} hops / "
              f"{prof['critical_exec_s']:.3f}s exec")
        print(f"scheduler efficiency {prof['efficiency']:.3f}  "
              f"(critical-path lower bound / actual makespan; "
              f"1.0 = unimprovable)")
        blocked = prof.get("blocked_s") or {}
        if blocked:
            print("blocked time on the critical path "
                  f"({prof['blocked_total_s']:.3f}s total):")
            for name, secs in sorted(blocked.items(),
                                     key=lambda kv: -kv[1]):
                print(f"  {name:<28} {secs:>9.3f}s")
        nodes = prof.get("nodes") or {}
        if len(nodes) > 1:
            print(f"node skew {prof['node_skew']:.2f}x "
                  f"(max node exec / mean):")
            for node, agg in sorted(nodes.items(),
                                    key=lambda kv: -kv[1]["exec_s"]):
                print(f"  {node[:12]:<14} {agg['tasks']:>6} tasks "
                      f"{agg['exec_s']:>9.3f}s exec")
        hops = prof.get("critical_path") or []
        if hops:
            print(f"critical path ({len(hops)} hops, longest "
                  f"duration-weighted path to sink):")
            print(f"  {'TASK_ID':<18} {'NODE':<10} {'EXEC':>8} "
                  f"{'GAP':>8}  GAP BREAKDOWN / NAME")
            for h in hops[: args.limit]:
                parts = " ".join(
                    f"{k}={v:.3f}s"
                    for k, v in sorted((h.get("buckets") or {}).items(),
                                       key=lambda kv: -kv[1]))
                tail = (parts + "  " if parts else "") + (h["name"] or "")
                print(f"  {h['task_id'][:16]:<18} "
                      f"{(h['node_id'] or '-')[:8]:<10} "
                      f"{h['exec_s']:>7.3f}s {h['gap_s']:>7.3f}s  "
                      f"{tail}")
            if len(hops) > args.limit:
                print(f"  ... {len(hops) - args.limit} more hops "
                      f"(--limit to see them)")
        if args.timeline:
            from ..scheduler.critical_path import chrome_trace

            trace = chrome_trace(resp.get("rows", []),
                                 job_id=prof["job_id"])
            with open(args.timeline, "w") as f:
                json.dump(trace, f)
            print(f"timeline written to {args.timeline} "
                  f"(load in Perfetto / chrome://tracing)")
    finally:
        gcs.close()


def cmd_doctor(args) -> None:
    """Cross-process consistency audit + postmortem bundle. Runs the GCS
    reconciliation pass (object directory vs controller arenas, spill
    dirs, completion rings, task table, inline budget), prints the
    findings, and writes one directory with everything a postmortem
    needs: findings, task table, events, time-series snapshot, node
    stats, handler stats, and collapsed flight-recorder profiles.
    Exit status: 0 when every invariant holds, 1 when anything is
    flagged."""
    gcs = _gcs_client(args.address)
    try:
        resp = gcs.call({"type": "run_audit",
                         "verify": not args.no_verify}, timeout=180.0)
        findings = resp.get("findings", [])
        summary = resp.get("summary", {})
        bundle = args.out or (
            f"/tmp/ray_tpu_postmortem_{time.strftime('%Y%m%d_%H%M%S')}")
        os.makedirs(bundle, exist_ok=True)
        os.makedirs(os.path.join(bundle, "profiles"), exist_ok=True)

        def dump(name: str, payload) -> None:
            with open(os.path.join(bundle, name), "w") as f:
                json.dump(payload, f, indent=2, default=repr)

        dump("findings.json", {"findings": findings, "summary": summary})
        dump("tasks.json", {
            "summary": gcs.call({"type": "task_summary"}),
            "tasks": gcs.call({"type": "list_tasks",
                               "limit": 10_000})["tasks"]})
        dump("events.json", gcs.call({"type": "get_events",
                                      "limit": 2000}))
        dump("timeseries.json", gcs.call({"type": "get_timeseries"}))
        dump("nodes.json", {
            "nodes": gcs.call({"type": "list_nodes"})["nodes"],
            "node_stats": gcs.call({"type": "get_node_stats"})["stats"],
            "resources": gcs.call({"type": "cluster_resources"})})
        dump("handlers.json", gcs.call({"type": "debug_stats"}))
        # Owner-shard directory: which driver owns each job's objects,
        # its liveness, and the shard layout — the audit's
        # dual_tracked_object / dead_owner_orphan findings read against
        # this table.
        try:
            owners = gcs.call({"type": "list_owners"})
        except Exception:  # noqa: BLE001 - pre-ownership head
            owners = {"owners": [], "shards": 0}
        dump("owners.json", owners)
        comps = gcs.call({"type": "get_profile_stacks"})["components"]
        for comp, info in comps.items():
            path = os.path.join(bundle, "profiles", f"{comp}.folded")
            with open(path, "w") as f:
                for stack, n in sorted(info["stacks"].items(),
                                       key=lambda kv: -kv[1]):
                    f.write(f"{stack} {n}\n")
        checked = (f"{summary.get('objects_checked', 0)} objects, "
                   f"{summary.get('tasks_checked', 0)} tasks, "
                   f"{summary.get('nodes_checked', 0)} node inventories")
        own_rows = owners.get("owners") or []
        if own_rows:
            live = sum(1 for o in own_rows if o.get("alive"))
            print(f"owner directory: {len(own_rows)} owner(s), "
                  f"{live} alive, {owners.get('shards', 0)} shards")
        if not findings:
            print(f"doctor: all consistency checks passed ({checked})")
            print(f"postmortem bundle: {bundle}")
            return
        print(f"doctor: {len(findings)} finding(s) across {checked}:")
        by_kind: Dict[str, int] = {}
        for f_ in findings:
            by_kind[f_["kind"]] = by_kind.get(f_["kind"], 0) + 1
        for kind, n in sorted(by_kind.items(), key=lambda kv: -kv[1]):
            print(f"  {kind:<20} {n}")
        for f_ in findings[:args.limit]:
            detail = " ".join(f"{k}={v}" for k, v in f_.items()
                              if k != "kind")
            print(f"  {f_['kind']:<20} {detail}")
        if len(findings) > args.limit:
            print(f"  ... {len(findings) - args.limit} more "
                  f"(see findings.json)")
        print(f"postmortem bundle: {bundle}")
        raise SystemExit(1)
    finally:
        gcs.close()


def cmd_transfers(args) -> None:
    """Data-plane view: per-node transfer counters (bytes in/out, inflight
    streams, admission-queue depth, chunk retries, sender deaths) from the
    latest heartbeat snapshot, plus every inflight/queued pull from the
    controllers' audit inventories when ``--inventory`` is set."""
    gcs = _gcs_client(args.address)
    try:
        stats = gcs.call({"type": "get_node_stats"})["stats"]
        rows = [(nid, s.get("transfer")) for nid, s in sorted(stats.items())
                if isinstance(s, dict)]
        rows = [(nid, t) for nid, t in rows if t]
        if not rows:
            print("no transfer stats yet (no node heartbeat carried them)")
            return
        print(f"{'NODE':<18} {'BYTES_IN':>12} {'BYTES_OUT':>12} "
              f"{'INFLIGHT':>8} {'QUEUED':>6} {'RETRIES':>7} "
              f"{'DEATHS':>6} {'OK':>6} {'FAIL':>5}")
        tot = dict.fromkeys(("bytes_in", "bytes_out", "inflight",
                             "queue_depth", "chunk_retries",
                             "sender_deaths", "pulls_ok", "pulls_failed"), 0)
        for nid, t in rows:
            for k in tot:
                tot[k] += int(t.get(k, 0))
            print(f"{nid[:16]:<18} {t.get('bytes_in', 0):>12} "
                  f"{t.get('bytes_out', 0):>12} {t.get('inflight', 0):>8} "
                  f"{t.get('queue_depth', 0):>6} "
                  f"{t.get('chunk_retries', 0):>7} "
                  f"{t.get('sender_deaths', 0):>6} "
                  f"{t.get('pulls_ok', 0):>6} {t.get('pulls_failed', 0):>5}")
        print(f"{'TOTAL':<18} {tot['bytes_in']:>12} {tot['bytes_out']:>12} "
              f"{tot['inflight']:>8} {tot['queue_depth']:>6} "
              f"{tot['chunk_retries']:>7} {tot['sender_deaths']:>6} "
              f"{tot['pulls_ok']:>6} {tot['pulls_failed']:>5}")
        caps = {t.get("max_inflight") for _, t in rows} - {None}
        if caps:
            sched = all(t.get("sched_enabled", True) for _, t in rows)
            print(f"admission: max_inflight/source="
                  f"{','.join(str(c) for c in sorted(caps))} "
                  f"scheduler={'on' if sched else 'OFF'}")
        if getattr(args, "inventory", False):
            resp = gcs.call({"type": "run_audit", "verify": False},
                            timeout=180.0)
            invs = resp.get("transfer_inventories") or {}
            shown = 0
            for nid, tr in sorted(invs.items()):
                for state in ("inflight", "queued"):
                    for e in (tr or {}).get(state, []):
                        print(f"  {state:<8} {e.get('object_id', '?')[:16]} "
                              f"on {nid[:12]} <- {str(e.get('source'))[:12]} "
                              f"age={e.get('age_s', 0):.1f}s "
                              f"size={e.get('size', 0)}")
                        shown += 1
            if not shown:
                print("no inflight or queued pulls")
    finally:
        gcs.close()


def cmd_trace(args) -> None:
    """Per-task straggler report: top-k slowest sampled tasks with latency
    attributed to the 7 control-plane phases (needs tracing enabled —
    default 1/64 sampling). ``--sample N`` broadcasts a new 1-in-N rate
    through the GCS kv (0 disables, -1 reverts to env/default): every
    driver/node picks it up on its next stats poll, no restarts."""
    from ray_tpu._private.tracing import TRACE_SAMPLE_KV_KEY, straggler_report

    gcs = _gcs_client(args.address)
    try:
        if args.sample is not None:
            if args.sample < 0:
                gcs.call({"type": "kv_put", "key": TRACE_SAMPLE_KV_KEY,
                          "value": None})
                print("trace sampling reverted to env/default "
                      "(override cleared)")
            else:
                gcs.call({"type": "kv_put", "key": TRACE_SAMPLE_KV_KEY,
                          "value": str(args.sample).encode()})
                print(f"trace sampling set to 1/{args.sample}"
                      if args.sample else "trace sampling disabled")
            print("(applies cluster-wide within ~2s, the stats-poll "
                  "cadence)")
            return
        spans = gcs.call({"type": "get_trace_data",
                          "limit": args.limit})["spans"]
        print(straggler_report(spans, top_k=args.top))
    finally:
        gcs.close()


def cmd_profile(args) -> None:
    """Flight-recorder report: top-N frames by wall samples from the GCS
    profile-stacks table, with the on-CPU column alongside so a thread
    blocked in ``recv`` reads ~0 on-CPU instead of masquerading as hot
    self-time. With ``--seconds N`` the table is snapshot-diffed around a
    live window (profile what's running NOW); 0 uses the cumulative
    counts. Also writes the window as a collapsed-stack file flamegraph
    tools consume directly (flamegraph.pl / speedscope)."""
    from ray_tpu._private.flight_recorder import attribution_table

    component = {"head": "gcs"}.get(args.component, args.component)
    gcs = _gcs_client(args.address)

    def snap() -> Dict[str, Dict]:
        msg: Dict = {"type": "get_profile_stacks"}
        if component != "all":
            msg["component"] = component
        return gcs.call(msg)["components"]

    try:
        before = snap() if args.seconds > 0 else {}
        if args.seconds > 0:
            print(f"recording {args.seconds:.0f}s window "
                  f"(component={args.component})...")
            time.sleep(args.seconds)
        after = snap()
    finally:
        gcs.close()
    # Window = after - before, merged across the selected components —
    # both the wall-sample counts and the fractional on-CPU weights.
    window: Dict[str, int] = {}
    window_cpu: Dict[str, float] = {}
    have_cpu = False
    for comp, info in after.items():
        base = before.get(comp, {})
        base_stacks = base.get("stacks", {})
        base_cpu = base.get("stacks_oncpu") or {}
        comp_cpu = info.get("stacks_oncpu")
        if comp_cpu is not None:
            have_cpu = True
        for stack, n in info["stacks"].items():
            d = n - base_stacks.get(stack, 0)
            if d > 0:
                window[stack] = window.get(stack, 0) + d
                if comp_cpu is not None:
                    dc = comp_cpu.get(stack, 0.0) - base_cpu.get(stack, 0.0)
                    window_cpu[stack] = (window_cpu.get(stack, 0.0)
                                         + max(0.0, dc))
    total = sum(window.values())
    if not total:
        print("no stack samples in the window — is the flight recorder "
              "on (RAY_TPU_FLIGHT_RECORDER) and the cluster busy?")
        return
    comps = ",".join(sorted(after)) or args.component
    print(f"{total} stack samples ({comps}); top {args.top} frames "
          f"by wall samples (WALL = samples the frame was on a stack, "
          f"ONCPU = schedstat-weighted share actually running):")
    print(f"{'WALL%':>7} {'WALL':>8} {'ONCPU':>8} {'CUM':>8}  FRAME")
    rows = attribution_table(window, window_cpu if have_cpu else None,
                             top=args.top)
    for frame, wall_n, oncpu_n, cum_n, pct in rows:
        oncpu_txt = (f"{oncpu_n:>8.1f}" if oncpu_n is not None
                     else f"{'-':>8}")
        print(f"{pct:>6.1f}% {wall_n:>8} {oncpu_txt} {cum_n:>8}  {frame}")
    if not have_cpu:
        print("(no on-CPU tagging in this window — loopmon disabled or "
              "procfs unavailable; WALL==ONCPU would be a lie, so it is "
              "shown as '-')")
    out_path = args.out or f"/tmp/ray_tpu_profile_{args.component}.folded"
    with open(out_path, "w") as f:
        for stack, n in sorted(window.items(), key=lambda kv: -kv[1]):
            f.write(f"{stack} {n}\n")
    print(f"collapsed stacks written to {out_path} "
          f"(feed to flamegraph.pl / speedscope)")


def _render_top_frame(gcs) -> str:
    """One `cli top` frame: live cluster view from the time-series
    rollups + handler stats."""
    from ray_tpu._private.timeseries import sparkline, window_rate

    ts = gcs.call({"type": "get_timeseries", "last": 60})
    nodes = gcs.call({"type": "list_nodes"})["nodes"]
    handlers = gcs.call({"type": "debug_stats"})["handlers"]
    series = ts["series"]
    bucket_s = ts.get("bucket_s", 10)
    now = time.time()
    lines = [f"ray_tpu top — {time.strftime('%H:%M:%S')}  "
             f"nodes {sum(n['Alive'] for n in nodes)}/{len(nodes)} alive  "
             f"bucket {bucket_s:.0f}s"]

    def pts(name):
        return (series.get(name) or {}).get("points", [])

    def rates(name):
        return [c["sum"] / bucket_s for _, c in pts(name)]

    tp = pts("tasks_finished")
    lines.append(
        f"tasks/s    {window_rate(tp, now - 60, now):>9.1f} (1m)  "
        f"{window_rate(tp, now - 300, now):>9.1f} (5m)   "
        f"{sparkline(rates('tasks_finished'))}")
    # Per-phase µs/task over the last minute (the 7-phase profiler view,
    # trended): seconds-delta / count-delta.
    phase_rows = []
    for name in sorted(series):
        if not name.startswith("phase_seconds:"):
            continue
        phase = name[len("phase_seconds:"):]
        sec = sum(c["sum"] for t, c in pts(name) if t >= now - 60)
        cnt = sum(c["sum"] for t, c in
                  pts(f"phase_count:{phase}") if t >= now - 60)
        if cnt > 0:
            phase_rows.append((phase, sec / cnt * 1e6, int(cnt)))
    if phase_rows:
        lines.append(f"  {'PHASE':<18} {'US/TASK':>10} {'ITEMS(1m)':>10}")
        for phase, us, cnt in phase_rows:
            lines.append(f"  {phase:<18} {us:>10.1f} {cnt:>10}")
    # Result-path mix: how results reached their owners (driver totals).
    totals = ts.get("driver_totals") or {}
    mix = {k[len("result:"):]: int(v) for k, v in totals.items()
           if k.startswith("result:")}
    if mix:
        total_n = sum(mix.values()) or 1
        lines.append("result path " + "  ".join(
            f"{k}={v} ({100 * v / total_n:.0f}%)"
            for k, v in sorted(mix.items(), key=lambda kv: -kv[1])))
    # Gauges worth trending.
    for label, name in (("cpu%", "node_cpu_percent_mean"),
                        ("mem%", "node_mem_percent_mean"),
                        ("objects", "objects_in_directory")):
        p = pts(name)
        if p:
            lines.append(f"{label:<10} {p[-1][1]['last']:>10.1f}   "
                         f"{sparkline([c['last'] for _, c in p])}")
    # Event-loop observatory rows: head loop lag p50/p99 (the queueing
    # delay every GCS callback inherits) and the per-component on/off-CPU
    # split (cores actually running vs loop wall split dwell/callbacks).
    from ray_tpu._private.timeseries import (latest_value, merge_hist,
                                             quantile_from_hist)

    lag_cells = [c for t, c in pts("loop_lag_ms:gcs") if t >= now - 60]
    if lag_cells:
        hist = merge_hist(lag_cells)
        p50 = quantile_from_hist(hist, 0.50)
        p99 = quantile_from_hist(hist, 0.99)
        lag_max = max((c["max"] for _, c in pts("loop_lag_max_ms:gcs")),
                      default=0.0)
        lines.append(
            f"head lag   p50<={p50:.0f}ms p99<={p99:.0f}ms "
            f"max={lag_max:.1f}ms (loop-lag heartbeat, 1m)")
    cpu_comps = sorted(n[len("proc_cpu_cores:"):]
                       for n in series if n.startswith("proc_cpu_cores:"))
    split_rows = []
    for comp in cpu_comps:
        cores = latest_value(pts(f"proc_cpu_cores:{comp}"))
        if cores is None:
            continue
        dwell = sum(c["sum"] for t, c in
                    pts(f"loop_dwell_s:{comp}") if t >= now - 60)
        cb = sum(c["sum"] for t, c in
                 pts(f"loop_cb_s:{comp}") if t >= now - 60)
        loop_txt = (f" loop: cb {cb / 60 * 100:>4.1f}% "
                    f"dwell {dwell / 60 * 100:>4.1f}%"
                    if (dwell or cb) else "")
        split_rows.append(f"  {comp:<11} on-CPU {cores:>5.2f} cores"
                          f"{loop_txt}")
    if split_rows:
        lines.append("on/off-CPU (2s window; off-CPU = wall - on-CPU)")
        lines.extend(split_rows)
    # Pending-by-reason gauges (the scheduling-explainability stream):
    # shown whenever anything is pending, so a stuck fan-out explains
    # itself in the first `cli top` frame.
    from ray_tpu._private.timeseries import latest_value

    reasons = {n[len("pending_reason:"):]: latest_value(pts(n))
               for n in series if n.startswith("pending_reason:")}
    reasons = {k: int(v) for k, v in reasons.items() if v}
    if reasons:
        lines.append("pending    " + "  ".join(
            f"{k}={v}" for k, v in sorted(reasons.items(),
                                          key=lambda kv: -kv[1])))
    audit = latest_value(pts("audit_findings"))
    if audit:
        lines.append(f"AUDIT      {int(audit)} consistency finding(s) — "
                     f"run `cli doctor` for the reconciliation report")
    pg_states = {n[len('pg_state:'):]: pts(n)[-1][1]["last"]
                 for n in series if n.startswith("pg_state:") and pts(n)}
    if pg_states:
        lines.append("pgs        " + "  ".join(
            f"{k.lower()}={int(v)}" for k, v in sorted(pg_states.items())))
    dropped = ts.get("events_dropped", 0)
    if dropped:
        lines.append(f"event log  {dropped} events dropped (ring full — "
                     f"raise RAY_TPU_EVENT_LOG_SIZE)")
    # Firing SLO rules (slo_fired without a later slo_resolved).
    events = gcs.call({"type": "get_events", "limit": 200})["events"]
    firing: Dict[str, Dict] = {}
    for ev in events:
        if ev.get("kind") == "slo_fired":
            firing[ev.get("rule", "?")] = ev
        elif ev.get("kind") == "slo_resolved":
            firing.pop(ev.get("rule", "?"), None)
    for rule, ev in firing.items():
        lines.append(f"SLO FIRING {rule}: value={ev.get('value')} "
                     f"threshold={ev.get('threshold')}")
    relay = {k: handlers[k]["count"]
             for k in ("relay:opaque", "relay:pickled") if k in handlers}
    if relay:
        lines.append(f"relay      {relay}")
    return "\n".join(lines)


def cmd_top(args) -> None:
    """Live cluster view (reference: `ray top` never shipped; this is
    htop-for-the-control-plane over the GCS time-series): tasks/s with
    sparkline, per-phase latency, result-path mix, pg states, SLO alerts.
    Refreshes in place; ``--once`` prints a single frame (scripts/CI)."""
    gcs = _gcs_client(args.address)
    try:
        if args.once:
            print(_render_top_frame(gcs))
            return
        while True:
            frame = _render_top_frame(gcs)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        gcs.close()


def build_ledger_window(gcs, since_s: float = 60.0) -> Dict:
    """Observatory aggregates over the last ``since_s`` of time-series
    data, shaped for :func:`tracing.conservation_ledger`. Shared by
    ``cli loops`` and the bench harness's ``--ledger`` mode."""
    from ray_tpu._private.timeseries import window_sum

    ts = gcs.call({"type": "get_timeseries", "last": int(since_s) + 10})
    series = ts["series"]
    now = time.time()
    # Points are keyed by BUCKET START (10 s bins): the partially-filled
    # current bucket's timestamp precedes a short window's `since`, so an
    # exact cut would drop the freshest — often the only — cell. Pad by
    # one bucket; conservation_ledger caps buckets at the measured gap,
    # so the over-inclusion can shift attribution but never invent wall.
    since = now - since_s - 10.0

    def wsum(name):
        return window_sum((series.get(name) or {}).get("points", []),
                          since)

    # Head handler seconds already counted inside the traced phases —
    # gcs_place and result_register run as GCS loop callbacks, so they
    # are subtracted from callback_run to keep the buckets disjoint.
    handler_s = (wsum("phase_seconds:gcs_place")
                 + wsum("phase_seconds:result_register"))
    lag_cells = [c for t, c in
                 (series.get("loop_lag_ms:gcs") or {}).get("points", [])
                 if t >= since]
    lag_s = sum(float(c.get("sum", 0.0)) for c in lag_cells) / 1000.0
    return {
        "tasks": wsum("tasks_finished"),
        "lag_s": lag_s,
        "cb_s": wsum("loop_cb_s:gcs"),
        "handler_s": handler_s,
        "dwell_s": wsum("loop_dwell_s:gcs"),
        "socket_dwell_s": wsum("socket_dwell_s:driver"),
        "ctx": wsum("ctx_vol:gcs") + wsum("ctx_invol:gcs"),
    }


def cmd_loops(args) -> None:
    """Event-loop observatory report: per-loop lag/dwell/callback split,
    per-process on/off-CPU truth, the slow-callback ledger, and the
    wall-clock conservation ledger (phases + gap buckets vs e2e)."""
    from ray_tpu._private.timeseries import quantile_from_hist
    from ray_tpu._private.tracing import (conservation_ledger,
                                          group_traces, ledger_table)

    gcs = _gcs_client(args.address)
    try:
        stats = gcs.call({"type": "get_loop_stats"})
        comps = stats.get("components", {})
        if not comps:
            print("no loop windows yet — loopmon disabled "
                  "(RAY_TPU_LOOPMON=0) or cluster just started")
        else:
            print(f"{'LOOP':<11} {'WALL':>6} {'DWELL%':>7} {'CB%':>6} "
                  f"{'CBS':>7} {'LAGp99':>7} {'LAGmax':>7} {'QMAX':>5} "
                  f"{'CPU':>5} {'CTXv/i':>11}")
            for comp in sorted(comps):
                w = comps[comp]
                wall = max(float(w.get("wall_s", 0.0)), 1e-9)
                lag = w.get("lag") or {}
                hist = {"buckets": lag.get("buckets", {}),
                        "sum": lag.get("sum_ms", 0.0),
                        "count": lag.get("count", 0)}
                p99 = quantile_from_hist(hist, 0.99)
                tc = w.get("thread_cpu") or {}
                cpu_cores = (float(tc["cpu_s"]) /
                             max(float(tc.get("wall_s", wall)), 1e-9)
                             if tc.get("cpu_s") is not None else None)
                print(f"{comp:<11} {wall:>5.1f}s "
                      f"{100 * w.get('dwell_s', 0) / wall:>6.1f}% "
                      f"{100 * w.get('cb_s', 0) / wall:>5.1f}% "
                      f"{w.get('cb_count', 0):>7} "
                      f"{(f'{p99:.0f}ms' if p99 is not None else '-'):>7} "
                      f"{lag.get('max_ms', 0.0):>5.1f}ms "
                      f"{w.get('queue_max', 0):>5} "
                      f"{(f'{cpu_cores:.2f}' if cpu_cores is not None else '-'):>5} "
                      f"{int(tc.get('vol', 0)):>5}/{int(tc.get('invol', 0)):<5}")
        slow = stats.get("slow", {})
        rows = [(comp, r) for comp, lst in slow.items() for r in lst]
        rows.sort(key=lambda cr: -cr[1][3])
        if rows:
            print(f"\nslow callbacks (>= threshold; worst first):")
            print(f"{'LOOP':<11} {'N':>5} {'TOTAL':>9} {'MAX':>9}  CALLBACK")
            for comp, (name, n, tot, mx) in rows[:args.top]:
                print(f"{comp:<11} {int(n):>5} {tot * 1e3:>7.1f}ms "
                      f"{mx * 1e3:>7.1f}ms  {name}")
        spans = gcs.call({"type": "get_trace_data",
                          "limit": 50_000})["spans"]
        traces = group_traces(spans)
        window = build_ledger_window(gcs)
        print()
        print(ledger_table(conservation_ledger(traces, window)))
    finally:
        gcs.close()


def _print_event(ev: Dict) -> None:
    stamp = time.strftime("%H:%M:%S", time.localtime(ev["ts"]))
    detail = " ".join(f"{k}={v}" for k, v in ev.items()
                      if k not in ("ts", "kind", "seq"))
    print(f"  {stamp} {ev['kind']:<22} {detail}")


def cmd_events(args) -> None:
    """Cluster event log: structured lifecycle events (node up/down, task
    retries, actor restarts, spill/restore, backpressure). ``--follow``
    tails it live with a sequence cursor: each poll asks only for events
    newer than the last seen seq, and a cursor that falls behind the
    ring's oldest surviving event (eviction outran the poll, or events
    were dropped) is reported, never silent."""
    gcs = _gcs_client(args.address)
    try:
        msg = {"type": "get_events", "limit": args.limit}
        if args.kind:
            msg["kind"] = args.kind
        resp = gcs.call(msg)
        events = resp["events"]
        dropped = resp.get("dropped", 0)
        print(f"{len(events)} events"
              + (f" (kind={args.kind})" if args.kind else "")
              + (f"; {dropped} dropped from the "
                 f"{resp.get('capacity', '?')}-slot ring "
                 f"(raise RAY_TPU_EVENT_LOG_SIZE)" if dropped else ""))
        for ev in events:
            _print_event(ev)
        if not getattr(args, "follow", False):
            return
        cursor = resp.get("last_seq", 0)
        last_dropped = dropped
        last_epoch = resp.get("epoch", 0)
        print("-- following (Ctrl-C to stop) --")
        while True:
            time.sleep(args.interval)
            msg = {"type": "get_events", "limit": args.limit,
                   "after_seq": cursor}
            if args.kind:
                msg["kind"] = args.kind
            try:
                resp = gcs.call(msg)
            except (ConnectionError, OSError, RuntimeError):
                # Re-dial rather than spin on the dead socket: the head may
                # have restarted or failed over to the standby (a standby
                # mid-promotion also answers NOT_LEADER, a RuntimeError).
                print("  (GCS unreachable; re-dialing)")
                try:
                    gcs.close()
                except Exception:  # noqa: BLE001
                    pass
                try:
                    gcs = _gcs_client(args.address)
                except (ConnectionError, OSError):
                    pass
                continue
            epoch = resp.get("epoch", last_epoch)
            if epoch != last_epoch:
                # The event ring is not replicated: the new leader starts a
                # fresh ring with fresh seqs. Reset the cursor and say so —
                # never silently splice two leaders' histories together.
                print(f"  !! leader changed (epoch {last_epoch} -> {epoch});"
                      f" events recorded before the failover are gone — "
                      f"resuming from the new leader's ring")
                last_epoch = epoch
                cursor = 0
                last_dropped = 0
                continue
            oldest = resp.get("oldest_seq")
            if oldest is not None and oldest > cursor + 1:
                # The ring evicted past our cursor between polls: those
                # events are unrecoverable — honor the drop accounting.
                print(f"  !! missed {oldest - cursor - 1} events "
                      f"(ring evicted past cursor; raise "
                      f"RAY_TPU_EVENT_LOG_SIZE or poll faster)")
            new_dropped = resp.get("dropped", 0)
            if new_dropped > last_dropped:
                print(f"  !! {new_dropped - last_dropped} events dropped "
                      f"from the full ring since last poll")
                last_dropped = new_dropped
            for ev in resp["events"]:
                _print_event(ev)
            cursor = max(cursor, resp.get("last_seq", cursor))
    except KeyboardInterrupt:
        pass
    finally:
        gcs.close()


def cmd_pgs(args) -> None:
    """Placement-group table: lifecycle state, strategy, bundles, the
    nodes holding each bundle, and — for stuck gangs — the pending reason
    (infeasible vs waiting-for-capacity)."""
    gcs = _gcs_client(args.address)
    try:
        groups = gcs.call({"type": "list_placement_groups"})["groups"]
        print(f"{len(groups)} placement groups")
        if not groups:
            return
        print(f"{'GROUP':<18} {'STATE':<13} {'STRATEGY':<14} "
              f"{'BUNDLES':<8} {'NODES':<26} REASON")
        for pg_hex, g in groups.items():
            nodes = ",".join(n[:8] for n in g.get("nodes", [])) or "-"
            name = f" name={g['name']}" if g.get("name") else ""
            print(f"{pg_hex[:16]:<18} {g['state']:<13} "
                  f"{g['strategy']:<14} {len(g['bundles']):<8} "
                  f"{nodes:<26} {g.get('reason') or '-'}{name}")
            if getattr(args, "verbose", False):
                for i, b in enumerate(g["bundles"]):
                    print(f"    bundle[{i}] {b}")
    finally:
        gcs.close()


def cmd_kill_random_node(args) -> None:
    if getattr(args, "head", False):
        # The head-failover drill: SIGKILL the head process recorded by
        # `cli start`/`cli up`. A running standby (RAY_TPU_GCS_ADDRS /
        # --standby head) should take over within the lease TTL.
        from ray_tpu._private import chaos

        pid = _load_session().get("head_pid")
        if not pid:
            raise SystemExit("no head_pid in the session file — "
                             "`kill_random_node --head` only works on a "
                             "cluster started by `cli start`/`cli up`")
        if not chaos.kill_process(int(pid)):
            raise SystemExit(f"could not kill head pid={pid} (already dead?)")
        print(f"killed head pid={pid}")
        return
    gcs = _gcs_client(args.address)
    try:
        nodes = [n for n in gcs.call({"type": "list_nodes"})["nodes"]
                 if n["Alive"]]
        if getattr(args, "worker", False):
            # Worker-scoped chaos: SIGKILL one worker process on a random
            # node via its controller — exercises blame attribution and
            # the collateral re-drive path instead of whole-node death.
            from ray_tpu.cluster.protocol import RpcClient

            if not nodes:
                raise SystemExit("no alive node to pick a worker from")
            victim = random.choice(nodes)
            host, port = victim["Address"]
            ctrl = RpcClient(host, int(port))
            try:
                resp = ctrl.call({"type": "kill_worker"})
            finally:
                ctrl.close()
            print(f"killed worker pid={resp.get('pid')} "
                  f"on node {victim['NodeID'][:12]}")
            return
        if len(nodes) <= 1:
            raise SystemExit("refusing: would kill the only alive node")
        victim = random.choice(nodes[1:])  # never the head's first node
        gcs.call({"type": "report_node_dead", "node_id": victim["NodeID"]})
        print(f"marked node dead: {victim['NodeID'][:12]}")
    finally:
        gcs.close()


def cmd_drain(args) -> None:
    """Gracefully retire a node: mask it out of placement, wait for its
    running tasks, re-home sole-copy objects, then remove it — a planned
    scale-down with zero task failures (vs kill_random_node's crash)."""
    gcs = _gcs_client(args.address)
    try:
        msg = {"type": "drain_node", "node_id": args.node}
        if args.timeout is not None:
            msg["timeout_s"] = args.timeout
        resp = gcs.call(msg)
        node_id = resp["node_id"]
        if resp.get("already_draining"):
            print(f"node {node_id[:12]} is already draining")
        else:
            print(f"draining node {node_id[:12]} ...")
        if args.no_wait:
            return
        # The GCS drains in the background; poll until the node retires.
        deadline = time.monotonic() + (args.timeout or 60.0) + 30.0
        while time.monotonic() < deadline:
            rows = gcs.call({"type": "list_nodes"})["nodes"]
            row = next((n for n in rows if n["NodeID"] == node_id), None)
            if row is None or not row["Alive"]:
                print(f"node {node_id[:12]} drained and removed")
                return
            time.sleep(0.5)
        raise SystemExit(f"timed out waiting for {node_id[:12]} to drain")
    finally:
        gcs.close()


def cmd_quarantine(args) -> None:
    """Show (or clear) the poison-task quarantine: functions whose workers
    died fatally RAY_TPU_POISON_THRESHOLD times; their submissions fail
    fast with TaskPoisonedError instead of crashing more workers."""
    gcs = _gcs_client(args.address)
    try:
        if args.clear is not None:
            msg = {"type": "clear_quarantine"}
            if args.clear:  # empty string = clear everything
                msg["fn_id"] = args.clear
            cleared = gcs.call(msg)["cleared"]
            if not cleared:
                print("nothing matched — no quarantine entries cleared")
                return
            for ent in cleared:
                print(f"cleared {ent.get('fn_id', '')[:16]} "
                      f"{ent.get('name', '')}")
            return
        resp = gcs.call({"type": "list_quarantine"})
        rows = resp.get("quarantined", [])
        print(f"{len(rows)} quarantined function(s) "
              f"(threshold {resp.get('threshold')})")
        for ent in rows:
            print(f"  {ent.get('fn_id', '')[:16]:<18} "
                  f"strikes={ent.get('strikes', 0)} "
                  f"{ent.get('name', '')}")
            if ent.get("last_error"):
                print(f"      last: {ent['last_error'][:120]}")
        strikes = [srow for srow in resp.get("strikes", [])
                   if not any(q.get("fn_id") == srow["fn_id"]
                              for q in rows)]
        if strikes:
            print(f"{len(strikes)} function(s) with sub-threshold strikes:")
            for srow in strikes:
                print(f"  {srow['fn_id'][:16]:<18} "
                      f"count={srow['count']} {srow.get('name', '')}")
    finally:
        gcs.close()


def _driver_env(address: Optional[str]) -> Dict[str, str]:
    """Environment for a driver process pointed at the running cluster."""
    if address is None:
        address = _load_session().get("address")
    if address is None:
        raise SystemExit("no running cluster (and no --address given)")
    import ray_tpu

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = address
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def cmd_submit(args) -> None:
    """Run a python script as a driver against the running cluster
    (reference: ray submit, scripts.py:781 — minus the cloud rsync: the
    cluster is local/multi-process, so the script path is already here).
    The script's plain ray_tpu.init() connects via RAY_TPU_ADDRESS."""
    if not os.path.exists(args.script):
        raise SystemExit(f"script not found: {args.script}")
    proc = subprocess.run(
        [sys.executable, args.script, *args.script_args],
        env=_driver_env(args.address),
    )
    raise SystemExit(proc.returncode)


def cmd_exec(args) -> None:
    """Run a shell command with the cluster env exported (reference:
    ray exec, scripts.py:863)."""
    proc = subprocess.run(
        args.command, shell=True, env=_driver_env(args.address),
    )
    raise SystemExit(proc.returncode)


def cmd_attach(args) -> None:
    """Open an interactive shell wired to the running cluster (reference:
    ray attach, scripts.py:781 — ssh to the head; locally, a subshell with
    RAY_TPU_ADDRESS exported so ray_tpu.init() connects)."""
    env = _driver_env(args.address)
    shell = os.environ.get("SHELL", "/bin/bash")
    print(f"attached to {env['RAY_TPU_ADDRESS']} — ray_tpu.init() connects; "
          f"exit the shell to detach")
    proc = subprocess.run([shell, "-i"], env=env)
    raise SystemExit(proc.returncode)


def _descendants(pid: int) -> List[int]:
    out = [pid]
    try:
        kids = subprocess.run(
            ["pgrep", "-P", str(pid)], capture_output=True, text=True
        ).stdout.split()
    except OSError:
        return out
    for kid in kids:
        out.extend(_descendants(int(kid)))
    return out


def cmd_stack(args) -> None:
    """Dump python stacks of every process in the session's cluster tree
    (reference: ray stack, scripts.py:1000 — py-spy replaced by the
    faulthandler SIGUSR1 dumps every cluster process registers)."""
    from ray_tpu._private.stack_dump import STACK_DIR

    state = _load_session()
    roots = state.get("worker_pids", []) + (
        [state["head_pid"]] if "head_pid" in state else [])
    if not roots:
        raise SystemExit("no running cluster session")
    pids = []
    for root in roots:
        pids.extend(_descendants(root))
    pids = sorted(set(pids))
    dumped = []
    for pid in pids:
        try:
            os.kill(pid, signal.SIGUSR1)
            dumped.append(pid)
        except (ProcessLookupError, PermissionError):
            pass
    time.sleep(0.8)  # give handlers time to write
    for pid in dumped:
        path = os.path.join(STACK_DIR, f"{pid}.txt")
        print(f"{'=' * 30} pid {pid} {'=' * 30}")
        try:
            with open(path) as f:
                content = f.read()
            # faulthandler appends; show only the most recent dump.
            print(content[-6000:] if len(content) > 6000 else content)
        except OSError:
            print("(no dump: process has no stack handler registered)")


def cmd_timeline(args) -> None:
    print("timeline export runs in the driver process:\n"
          "  import ray_tpu; ray_tpu.init(); ...\n"
          f"  ray_tpu.timeline(filename={args.output!r})\n"
          "then open the JSON in chrome://tracing or perfetto.")


def cmd_dashboard(args) -> None:
    """Serve the browsable HTML dashboard against the session's cluster
    (reference: ray dashboard / the aiohttp dashboard started by ray
    start). Blocks until Ctrl-C."""
    import ray_tpu
    from ray_tpu.dashboard import start_dashboard

    address = args.address or _load_session().get("address")
    if address:
        ray_tpu.init(address=address)
        print(f"connected to cluster at {address}")
    else:
        ray_tpu.init(num_cpus=os.cpu_count() or 4)
        print("no running cluster; serving a local-mode dashboard")
    dash = start_dashboard(port=args.port)
    print(f"dashboard at {dash.url} (Ctrl-C to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        dash.stop()
        ray_tpu.shutdown()


def cmd_serve(args) -> None:
    """Serving-fleet status: endpoints (routed/errors/latency), backends
    (replicas by up/down/draining state, inflight, queue depth, autoscale
    band) and the failover counters the self-healing loop maintains."""
    import ray_tpu
    from ray_tpu.serve.master import MASTER_NAME

    address = args.address or _load_session().get("address")
    if not address:
        raise SystemExit("no running cluster (pass --address or `cli up`); "
                         "serve status needs the cluster that runs the "
                         "serve control plane")
    ray_tpu.init(address=address)
    try:
        try:
            master = ray_tpu.get_actor(MASTER_NAME)
        except Exception:
            raise SystemExit("no serve control plane in this cluster "
                             "(serve.init() not called)")
        s = ray_tpu.get(master.stat.remote())
        eps = s.get("endpoints", {})
        print(f"{len(eps)} endpoints")
        if eps:
            print(f"{'ENDPOINT':<20} {'ROUTED':>8} {'ERRORS':>8} TRAFFIC")
            for ep, info in eps.items():
                traffic = " ".join(f"{t}={w:g}" for t, w in
                                   info.get("traffic", {}).items())
                print(f"{ep:<20} {info['routed']:>8} {info['errors']:>8} "
                      f"{traffic}")
        fleet = s.get("fleet", {})
        backends = s.get("backends", {})
        print(f"{len(backends)} backends")
        if backends:
            print(f"{'BACKEND':<20} {'TARGET':>6} {'UP':>4} {'DOWN':>5} "
                  f"{'DRAIN':>6} {'INFLIGHT':>9} {'QUEUED':>7} AUTOSCALE")
            for tag, b in backends.items():
                f = fleet.get(tag, {})
                auto = (f"{f['min_replicas']}..{f['max_replicas']}"
                        if f.get("autoscaling") else "off")
                print(f"{tag:<20} {f.get('target', '-'):>6} "
                      f"{b.get('up', 0):>4} {b.get('down', 0):>5} "
                      f"{b.get('draining', 0):>6} {b.get('inflight', 0):>9} "
                      f"{b.get('queued', 0):>7} {auto}")
        counters = {**s.get("counters", {}), **s.get("fleet_counters", {})}
        if counters:
            print("counters: " + " ".join(
                f"{k}={v}" for k, v in counters.items()))
        print(f"live streams: {s.get('streams', 0)}")
    finally:
        ray_tpu.shutdown()


def cmd_microbenchmark(args) -> None:
    """In-process perf microbenchmarks (reference: ray microbenchmark /
    ray_perf.py). Prints ops/s per pattern."""
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=os.cpu_count() or 4)

    def timeit(name, fn, n, unit="ops/s"):
        fn()  # warmup
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        print(f"{name:<40} {n / dt:>12,.0f} {unit}")

    @ray_tpu.remote
    def noop():
        return None

    timeit("tasks sync (1k serial round-trips)",
           lambda: [ray_tpu.get(noop.remote()) for _ in range(1000)], 1000)
    timeit("tasks async (10k batched)",
           lambda: ray_tpu.get([noop.remote() for _ in range(10000)]), 10000)

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    timeit("actor calls sync (1k serial)",
           lambda: [ray_tpu.get(a.m.remote()) for _ in range(1000)], 1000)
    timeit("actor calls async (10k pipelined)",
           lambda: ray_tpu.get([a.m.remote() for _ in range(10000)]), 10000)

    blob = np.zeros(1024 * 1024, dtype=np.uint8)
    timeit("put 1MiB x100 (GB/s)",
           lambda: [ray_tpu.put(blob) for _ in range(100)],
           100 / 1024, unit="GB/s")
    ray_tpu.shutdown()


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="ray-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="GCS address (worker mode)")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--resources", help='JSON, e.g. \'{"CPU": 8}\'')
    sp.add_argument("--num-workers", type=int, default=2,
                    help="worker processes per node")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the session's cluster")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("up", help="start a cluster from a config file")
    sp.add_argument("config")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down the session's cluster")
    sp.set_defaults(fn=cmd_down)

    for name, fn in [("status", cmd_status), ("memory", cmd_memory),
                     ("kill_random_node", cmd_kill_random_node)]:
        sp = sub.add_parser(name)
        sp.add_argument("--address")
        if name == "memory":
            sp.add_argument("--limit", type=int, default=1000)
            sp.add_argument("--refs", action="store_true",
                            help="reference-accounting view (holders/pins)")
        if name == "status":
            sp.add_argument("-v", "--verbose", action="store_true",
                            help="include per-RPC GCS handler timings")
        if name == "kill_random_node":
            sp.add_argument("--head", action="store_true",
                            help="SIGKILL the head process instead (the "
                                 "failover drill; needs a session started "
                                 "by `cli start`/`cli up`)")
            sp.add_argument("--worker", action="store_true",
                            help="SIGKILL one worker process on a random "
                                 "node instead of the whole node (the "
                                 "blame-attribution drill)")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("drain", help="gracefully retire a node: no new "
                                      "placements, wait for running tasks, "
                                      "re-home sole-copy objects, remove")
    sp.add_argument("node", help="node id (hex prefix accepted)")
    sp.add_argument("--address")
    sp.add_argument("--timeout", type=float, default=None,
                    help="seconds to wait for running tasks before "
                         "relocating them (default RAY_TPU_DRAIN_TIMEOUT_S "
                         "or 60)")
    sp.add_argument("--no-wait", action="store_true",
                    help="issue the drain and return without polling")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("quarantine",
                        help="poison-task quarantine table "
                             "(functions that keep killing workers)")
    sp.add_argument("--address")
    sp.add_argument("--clear", nargs="?", const="", default=None,
                    metavar="FN_ID",
                    help="lift quarantine: --clear <fn_id prefix>, or "
                         "--clear with no value for all entries")
    sp.set_defaults(fn=cmd_quarantine)

    sp = sub.add_parser("transfers", help="data-plane view: per-node "
                        "transfer counters (bytes in/out, inflight, queue "
                        "depth, retries) and optionally every live pull")
    sp.add_argument("--address")
    sp.add_argument("--inventory", action="store_true",
                    help="also list every inflight/queued pull from the "
                    "controllers' audit inventories")
    sp.set_defaults(fn=cmd_transfers)

    sp = sub.add_parser("trace", help="per-task straggler report "
                                      "(sampled trace table)")
    sp.add_argument("--address")
    sp.add_argument("--top", type=int, default=10)
    sp.add_argument("--limit", type=int, default=50_000,
                    help="newest spans to fetch from the GCS trace table")
    sp.add_argument("--sample", type=int, default=None,
                    help="broadcast a new 1-in-N sampling rate via the "
                         "GCS kv (0=off, -1=revert to env/default)")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("profile", help="flight-recorder self-time report "
                                        "(+ collapsed-stack file)")
    sp.add_argument("--address")
    sp.add_argument("--component", default="all",
                    choices=["all", "head", "gcs", "controller", "worker",
                             "driver"])
    sp.add_argument("--seconds", type=float, default=5.0,
                    help="live window to snapshot-diff (0 = cumulative)")
    sp.add_argument("--top", type=int, default=25)
    sp.add_argument("--out", help="collapsed-stack output path "
                                  "(default /tmp/ray_tpu_profile_*.folded)")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("top", help="live cluster view over the GCS "
                                    "time-series rollups")
    sp.add_argument("--address")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("loops", help="event-loop observatory: lag/dwell/"
                                      "callback split, slow-callback "
                                      "ledger, conservation ledger")
    sp.add_argument("--address")
    sp.add_argument("--top", type=int, default=10,
                    help="slow-callback rows to print")
    sp.set_defaults(fn=cmd_loops)

    sp = sub.add_parser("pgs", help="placement-group table (gang "
                                    "reservations and lifecycle state)")
    sp.add_argument("--address")
    sp.add_argument("-v", "--verbose", action="store_true",
                    help="print per-bundle resource dicts")
    sp.set_defaults(fn=cmd_pgs)

    sp = sub.add_parser("events", help="cluster lifecycle event log")
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--kind", help="filter by event kind "
                                   "(e.g. node_down, task_retry)")
    sp.add_argument("-f", "--follow", action="store_true",
                    help="tail the log live (cursor-based; reports "
                         "evicted/dropped gaps instead of hiding them)")
    sp.add_argument("--interval", type=float, default=1.0,
                    help="poll interval for --follow")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("tasks", help="state API v2: the cluster task "
                                      "table (filterable, paginated)")
    sp.add_argument("--address")
    sp.add_argument("--state", choices=["PENDING", "DISPATCHED",
                                        "FINISHED", "FAILED"])
    sp.add_argument("--kind", choices=["task", "actor"])
    sp.add_argument("--reason", help="filter PENDING rows by pending "
                                     "reason (e.g. infeasible)")
    sp.add_argument("--name", help="substring filter on task name")
    sp.add_argument("--limit", type=int, default=50)
    sp.add_argument("--offset", type=int, default=0)
    sp.set_defaults(fn=cmd_tasks)

    sp = sub.add_parser("task", help="one task in detail, with a "
                                     "why-pending explanation")
    sp.add_argument("id", help="task id (hex prefix accepted)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_task)

    sp = sub.add_parser("jobs", help="per-job rollup: task counts, "
                                     "makespan, scheduler efficiency")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("job", help="job critical-path profile: "
                                    "blocked-time buckets + efficiency")
    sp.add_argument("id", nargs="?", default="",
                    help="job id (hex prefix; omit when one job)")
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=40,
                    help="critical-path hops to print")
    sp.add_argument("--timeline", metavar="OUT",
                    help="also write the Chrome-trace/Perfetto JSON "
                         "timeline to this path")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("doctor", help="consistency audit + postmortem "
                                       "bundle (exit 1 on findings)")
    sp.add_argument("--address")
    sp.add_argument("--out", help="bundle directory (default "
                                  "/tmp/ray_tpu_postmortem_<ts>)")
    sp.add_argument("--limit", type=int, default=25,
                    help="findings to print (the bundle holds all)")
    sp.add_argument("--no-verify", action="store_true",
                    help="skip the per-object has_object confirmation "
                         "probes (faster, may over-report)")
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser("submit", help="run a driver script on the cluster")
    sp.add_argument("--address")
    sp.add_argument("script")
    sp.add_argument("script_args", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("exec", help="run a shell command with cluster env")
    sp.add_argument("--address")
    sp.add_argument("command")
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("stack", help="dump stacks of cluster processes")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("attach", help="interactive shell on the cluster")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("timeline")
    sp.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("dashboard",
                        help="serve the browsable HTML dashboard")
    sp.add_argument("--address")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("serve", help="serving-fleet status: replicas by "
                                      "state, inflight, retry/failover "
                                      "counters")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("microbenchmark")
    sp.set_defaults(fn=cmd_microbenchmark)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
