"""``# raylint:`` comment annotations.

Two forms, both parsed with :mod:`tokenize` so ``#`` inside string
literals can never masquerade as an annotation:

  * ``# raylint: disable=rule-a,rule-b`` — suppress those rules on this
    line; placed on a ``def``/``class`` header (or its decorator line) it
    covers the whole body. ``disable=all`` suppresses every rule.
  * ``# raylint: hotpath`` — marks the function defined on this line (or
    the line below the comment) as a hot-path function: the ``hot-path``
    checker then forbids pickle/json, INFO logging, and eager f-string
    log calls inside it.

Suppressions are deliberate, reviewed exceptions and should carry a
trailing justification: ``# raylint: disable=async-blocking — snapshot
must serialize on the loop thread for a consistent view``.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, Set, Tuple

_ANNOT = re.compile(r"#\s*raylint:\s*(.*)")
_DISABLE = re.compile(r"disable=([\w\-,]+)")


def _comment_lines(source: str) -> Dict[int, str]:
    """line -> raylint annotation text, for every `# raylint:` comment."""
    out: Dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = _ANNOT.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group(1).strip()
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def parse(source: str, tree: ast.Module
          ) -> Tuple[Dict[int, frozenset], frozenset]:
    """Return (disabled-rules-per-line, hotpath-def-lines).

    A ``disable=`` comment on a def/class header line (or any of its
    decorator lines) is expanded over the node's full line span. A
    ``hotpath`` comment attaches to the def on the same line, or the def
    starting on the next line.
    """
    annots = _comment_lines(source)
    src_lines = source.splitlines()
    disabled: Dict[int, Set[str]] = {}
    hotpath_comment_lines: Set[int] = set()

    for line, text in annots.items():
        m = _DISABLE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            disabled.setdefault(line, set()).update(rules)
            # A standalone comment line (no code before the `#`) covers
            # the line BELOW it — the natural place to write a disable
            # that would not fit at the end of the offending line.
            raw = src_lines[line - 1] if line - 1 < len(src_lines) else ""
            if raw.split("#", 1)[0].strip() == "":
                disabled.setdefault(line + 1, set()).update(rules)
        if re.search(r"\bhotpath\b", text):
            hotpath_comment_lines.add(line)

    hotpath_defs: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        header_lines = {node.lineno}
        header_lines.update(d.lineno for d in node.decorator_list)
        first = min(header_lines)
        span_rules: Set[str] = set()
        for hl in header_lines:
            span_rules.update(disabled.get(hl, ()))
        if span_rules:
            end = getattr(node, "end_lineno", node.lineno)
            for ln in range(first, end + 1):
                disabled.setdefault(ln, set()).update(span_rules)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # `# raylint: hotpath` on the def line or the line above it
            # (or above the first decorator).
            if (node.lineno in hotpath_comment_lines
                    or first in hotpath_comment_lines
                    or (first - 1) in hotpath_comment_lines):
                hotpath_defs.add(node.lineno)

    return ({ln: frozenset(rules) for ln, rules in disabled.items()},
            frozenset(hotpath_defs))
