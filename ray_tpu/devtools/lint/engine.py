"""raylint engine: file discovery, parsing, checker dispatch, suppression.

One :func:`run_lint` call loads the WHOLE project (cross-file rules like
wire-discipline need the full picture even when only one file changed),
runs the enabled checkers, drops ``# raylint: disable=`` suppressed
findings, and splits the rest against the committed baseline. The
``paths`` filter only restricts which findings are *reported* — never
what the checkers can see.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import annotations as _annotations
from . import baseline as _baseline
from .model import Checker, Finding, Module, Project
from .checkers.async_blocking import AsyncBlockingChecker
from .checkers.hot_path import HotPathChecker
from .checkers.kernel_purity import KernelPurityChecker
from .checkers.thread_shared import ThreadSharedStateChecker
from .checkers.wire_discipline import WireDisciplineChecker

ALL_CHECKERS: Tuple[type, ...] = (
    AsyncBlockingChecker,
    WireDisciplineChecker,
    KernelPurityChecker,
    ThreadSharedStateChecker,
    HotPathChecker,
)

RULE_IDS: Tuple[str, ...] = tuple(c.rule_id for c in ALL_CHECKERS)

# Source roots scanned into the project (tests are loaded for the
# cross-reference rules but are never lint *targets* themselves).
SCAN_ROOTS = ("ray_tpu", "scripts", "tests")
SKIP_PARTS = ("__pycache__", ".git", "node_modules")


@dataclass
class LintResult:
    findings: List[Finding]          # reported, non-baselined
    baselined: List[Finding]
    suppressed: int                  # dropped by `# raylint: disable=`
    stale_baseline: List[Tuple[str, str, str, str]]
    files_scanned: int
    parse_errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings


def discover_files(root: str) -> List[str]:
    out: List[str] = []
    for scan in SCAN_ROOTS:
        base = os.path.join(root, scan)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in SKIP_PARTS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def load_project(root: str,
                 files: Optional[Iterable[str]] = None
                 ) -> Tuple[Project, List[str]]:
    """Parse every discovered file into a Project; unparseable files are
    reported, not fatal (the repo's own tests own syntax errors)."""
    errors: List[str] = []
    modules: List[Module] = []
    for path in (files if files is not None else discover_files(root)):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{rel}: {e}")
            continue
        disabled, hotpath = _annotations.parse(source, tree)
        modules.append(Module(relpath=rel, source=source, tree=tree,
                              disabled=disabled, hotpath_lines=hotpath))
    return Project(root, modules), errors


def run_lint(root: str,
             rules: Optional[Sequence[str]] = None,
             paths: Optional[Sequence[str]] = None,
             use_baseline: bool = True,
             project: Optional[Project] = None) -> LintResult:
    """Run the suite. ``rules`` filters checkers by id; ``paths`` filters
    REPORTED findings to those whose path matches one of the (repo
    relative, forward-slash) prefixes; ``project`` lets tests inject a
    synthetic file set."""
    parse_errors: List[str] = []
    if project is None:
        project, parse_errors = load_project(root)

    raw: List[Finding] = []
    for cls in ALL_CHECKERS:
        if rules is not None and cls.rule_id not in rules:
            continue
        raw.extend(cls().run(project))

    suppressed = 0
    kept: List[Finding] = []
    for f in raw:
        mod = project.get(f.path)
        if mod is not None and mod.is_disabled(f.line, f.rule):
            suppressed += 1
            continue
        if paths is not None and not any(
                f.path == p or f.path.startswith(p.rstrip("/") + "/")
                or f.path.startswith(p)
                for p in paths):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if use_baseline:
        base = _baseline.load(root)
        new, old, stale = _baseline.split(kept, base)
    else:
        new, old, stale = kept, [], []
    return LintResult(findings=new, baselined=old, suppressed=suppressed,
                      stale_baseline=stale,
                      files_scanned=len(project.modules),
                      parse_errors=parse_errors)


def rewrite_baseline(root: str,
                     rules: Optional[Sequence[str]] = None) -> str:
    """Record the current finding set as the new baseline; returns the
    baseline path."""
    result = run_lint(root, rules=rules, use_baseline=False)
    return _baseline.save(root, result.findings)
