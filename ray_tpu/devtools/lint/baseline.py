"""Committed baseline suppression file (``.raylint_baseline.json``).

The baseline is the escape hatch for findings that are real debt but out
of scope for the change at hand: ``scripts/lint.py --baseline-rewrite``
records the current finding set; subsequent runs exit 0 as long as no NEW
finding appears. Entries are line-independent fingerprints
(rule, path, enclosing symbol, message) so edits elsewhere in a file do
not invalidate them; an entry whose finding disappears is reported as
stale so the file shrinks over time instead of fossilizing.

``tests/test_lint.py`` asserts a ceiling on the baseline size — the
baseline is a ratchet, not a dumping ground.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Set, Tuple

from .model import Finding

BASELINE_NAME = ".raylint_baseline.json"

Fingerprint = Tuple[str, str, str, str]


def baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_NAME)


def load(root: str) -> List[Fingerprint]:
    path = baseline_path(root)
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: List[Fingerprint] = []
    for ent in data.get("suppressions", []):
        out.append((ent["rule"], ent["path"], ent.get("symbol", ""),
                    ent["message"]))
    return out


def save(root: str, findings: List[Finding]) -> str:
    path = baseline_path(root)
    entries = []
    seen: Set[Fingerprint] = set()
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.symbol,
                                             f.message)):
        fp = f.fingerprint()
        if fp in seen:
            continue
        seen.add(fp)
        entries.append({"rule": f.rule, "path": f.path, "symbol": f.symbol,
                        "message": f.message})
    payload = {
        "comment": ("raylint baseline: known findings suppressed from the "
                    "gate. Shrink me; never grow me without a review. "
                    "Rewrite with scripts/lint.py --baseline-rewrite."),
        "suppressions": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return path


def split(findings: List[Finding], baseline: List[Fingerprint]
          ) -> Tuple[List[Finding], List[Finding], List[Fingerprint]]:
    """Partition into (new, baselined, stale-baseline-entries)."""
    index: Dict[Fingerprint, int] = {}
    for fp in baseline:
        index[fp] = index.get(fp, 0) + 1
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if index.get(fp, 0) > 0:
            index[fp] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [fp for fp, n in index.items() for _ in range(n) if n > 0]
    return new, old, stale
