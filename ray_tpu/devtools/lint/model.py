"""Finding model + checker base for raylint.

A :class:`Finding` is one rule violation at a file:line, carrying the rule
id, a message, and a fix hint. Findings are identified across runs by a
*fingerprint* that deliberately excludes the line number — baselined
findings must survive unrelated edits above them — and instead keys on the
enclosing symbol (function/class qualname) plus the message text.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


@dataclass
class Finding:
    rule: str                 # rule id, e.g. "async-blocking"
    path: str                 # repo-relative, forward slashes
    line: int
    col: int
    message: str
    hint: str = ""            # how to fix (or legitimately suppress) it
    symbol: str = ""          # enclosing qualname, e.g. "GCSServer._snapshot_loop"

    def fingerprint(self) -> Tuple[str, str, str, str]:
        """Line-independent identity used for baseline matching."""
        return (self.rule, self.path, self.symbol, self.message)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}  [{self.rule}]  {self.message}"
        if self.symbol:
            out += f"  (in {self.symbol})"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Module:
    """One parsed source file, shared by every checker."""

    relpath: str              # forward-slash path relative to the project root
    source: str
    tree: ast.Module
    # line -> set of rule ids disabled on that line (from `# raylint:` comments,
    # with def/class-header disables expanded over the whole body span).
    disabled: Dict[int, frozenset] = field(default_factory=dict)
    # def-statement lines annotated `# raylint: hotpath`
    hotpath_lines: frozenset = frozenset()

    def is_disabled(self, line: int, rule: str) -> bool:
        rules = self.disabled.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class Project:
    """The file set one lint run sees.

    Tests build synthetic projects out of tmp dirs with the same relative
    layout (``ray_tpu/cluster/wire.py`` …), so every checker must address
    files only through :meth:`get` / :meth:`glob` — never the real repo.
    """

    def __init__(self, root: str, modules: Iterable[Module]):
        self.root = root
        self.modules: Dict[str, Module] = {m.relpath: m for m in modules}

    def get(self, relpath: str) -> Optional[Module]:
        return self.modules.get(relpath)

    def glob(self, prefix: str) -> List[Module]:
        """All modules whose relpath starts with ``prefix``, sorted."""
        return [self.modules[p] for p in sorted(self.modules)
                if p.startswith(prefix)]


class Checker:
    """Base class: one rule, run over a whole :class:`Project`.

    Subclasses set ``rule_id``/``description`` and implement :meth:`run`.
    The engine applies ``# raylint: disable=`` suppressions and the
    baseline after the checker yields raw findings, so checkers only
    report what they see.
    """

    rule_id: str = ""
    description: str = ""

    def run(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError


def qualname_map(tree: ast.Module) -> Dict[ast.AST, str]:
    """Map every function/class def node to its dotted qualname."""
    out: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}{child.name}"
                out[child] = name
                walk(child, name + ".")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def call_root(node: ast.expr) -> str:
    """Dotted name of a call target: ``a.b.c(x)`` -> ``a.b.c``; '' if not
    a plain name/attribute chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""
