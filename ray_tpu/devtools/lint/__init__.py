"""raylint: AST static analysis enforcing the control plane's invariants.

Five rules, each guarding a load-bearing convention nothing else checks:

  * ``async-blocking``      — no blocking calls reachable from the
                              cluster's event-loop coroutines;
  * ``wire-discipline``     — every wire frame has a paired encoder +
                              decoder, collision-free id, version gate
                              with pickle fallback, handler site, and a
                              codec test;
  * ``kernel-purity``       — every jit'd scheduler pass has a
                              bit-identical scalar reference, a property
                              test naming both, and a pure traced body;
  * ``thread-shared-state`` — cross-thread attribute mutation without a
                              lock;
  * ``hot-path``            — ``# raylint: hotpath`` functions stay free
                              of pickle/json/INFO-logging/eager f-string
                              logs.

Run it with ``python scripts/lint.py`` (``--changed`` for pre-commit,
``--baseline-rewrite`` to re-record known debt). The committed baseline
lives in ``.raylint_baseline.json``; ``tests/test_lint.py`` is the tier-1
gate keeping the repo clean. See docs/devtools.md for the rule catalog
and annotation syntax.
"""

from .engine import (ALL_CHECKERS, RULE_IDS, LintResult, load_project,
                     rewrite_baseline, run_lint)
from .model import Checker, Finding, Module, Project

__all__ = [
    "ALL_CHECKERS", "RULE_IDS", "LintResult", "Checker", "Finding",
    "Module", "Project", "load_project", "rewrite_baseline", "run_lint",
]
