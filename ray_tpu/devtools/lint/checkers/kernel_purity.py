"""kernel-purity: every jit'd scheduler pass stays pure and verifiable.

The data-parallel placement passes are the repo's differentiator vs the
reference's sequential loop (Ray, arXiv:1712.05889) — and the entire
safety argument rests on each jit'd pass having a bit-identical scalar
reference that property tests pin (placement, gang admission, and
pending-reason classification all ship that way today; Tesserae,
arXiv:2508.04953, makes the same argument for evolving policies against a
pinned spec). This checker makes the convention structural:

  1. every ``@jax.jit`` function in ``scheduler/kernel.py`` must have a
     ``<name>_reference`` in ``scheduler/reference.py`` — or be a shared
     spec helper the reference itself imports (directly or via its
     ``<name>_host`` wrapper);
  2. some test module must exercise the pair by naming BOTH the kernel
     entry and its reference (the property-test handle);
  3. jit'd bodies must be pure: no ``time``/``random``/``np.random``
     draws, no host side effects (``print``/``open``/``os.*``) — a trace
     captures those once at compile time and silently freezes them.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..model import Checker, Finding, Module, Project, call_root

KERNEL_PATH = "ray_tpu/scheduler/kernel.py"
REFERENCE_PATH = "ray_tpu/scheduler/reference.py"
TESTS_PREFIX = "tests/"

IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                   "os.", "datetime.")
IMPURE_CALLS = {"print", "open", "input", "eval", "exec"}


def _is_jit_decorator(dec: ast.expr) -> bool:
    """Matches @jax.jit, @jit, @functools.partial(jax.jit, ...),
    @partial(jax.jit, ...)."""
    dotted = call_root(dec)
    if dotted in ("jax.jit", "jit"):
        return True
    if isinstance(dec, ast.Call):
        fn = call_root(dec.func)
        if fn in ("functools.partial", "partial") and dec.args:
            return call_root(dec.args[0]) in ("jax.jit", "jit")
        return fn in ("jax.jit", "jit")
    return False


class KernelPurityChecker(Checker):
    rule_id = "kernel-purity"
    description = ("jit'd scheduler passes: scalar reference mirror, "
                   "property test naming both, no host effects in traces")

    def run(self, project: Project) -> Iterator[Finding]:
        kernel = project.get(KERNEL_PATH)
        if kernel is None:
            return
        reference = project.get(REFERENCE_PATH)

        jit_fns: Dict[str, ast.AST] = {}
        for node in kernel.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(_is_jit_decorator(d) for d in node.decorator_list):
                jit_fns[node.name] = node

        ref_defs: Set[str] = set()
        ref_imports: Set[str] = set()
        if reference is not None:
            for node in ast.walk(reference.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ref_defs.add(node.name)
                elif isinstance(node, ast.ImportFrom) and node.module \
                        and node.module.endswith("kernel"):
                    ref_imports.update(a.name for a in node.names)

        test_sources = [m.source for m in project.glob(TESTS_PREFIX)]

        for name, node in sorted(jit_fns.items()):
            ref_name = f"{name}_reference"
            # Shared-spec helpers (e.g. the threefry draw both sides use)
            # are exempt: the reference imports them (or their _host
            # wrapper), so they ARE the spec rather than mirroring one.
            shared = name in ref_imports or f"{name}_host" in ref_imports
            if not shared and reference is not None \
                    and ref_name not in ref_defs:
                yield Finding(
                    rule=self.rule_id, path=kernel.relpath,
                    line=node.lineno, col=0,
                    message=(f"jit'd pass `{name}` has no `{ref_name}` in "
                             f"{REFERENCE_PATH}"),
                    hint="add the bit-identical scalar mirror (or import "
                         "the helper into reference.py if it IS the spec)",
                    symbol=name)
            elif not shared and reference is not None and test_sources:
                if not any(name in src and ref_name in src
                           for src in test_sources):
                    yield Finding(
                        rule=self.rule_id, path=kernel.relpath,
                        line=node.lineno, col=0,
                        message=(f"no test module names both `{name}` and "
                                 f"`{ref_name}` (bit-identity property "
                                 f"test missing)"),
                        hint="add a property test asserting kernel == "
                             "reference on random + adversarial inputs",
                        symbol=name)
            yield from self._check_purity(kernel, name, node)

    def _check_purity(self, kernel: Module, name: str,
                      fn: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = call_root(node.func)
            if not dotted:
                continue
            impure = dotted in IMPURE_CALLS or any(
                dotted.startswith(p) for p in IMPURE_PREFIXES)
            # jax.random / jax.* are the sanctioned in-trace RNG & ops.
            if impure and not dotted.startswith(("jax.", "jnp.")):
                yield Finding(
                    rule=self.rule_id, path=kernel.relpath,
                    line=node.lineno, col=node.col_offset,
                    message=(f"host call `{dotted}` inside jit'd pass "
                             f"`{name}` (traced once, then frozen)"),
                    hint="hoist host work out of the jit body; use "
                         "jax.random for in-kernel draws",
                    symbol=name)
