"""wire-discipline: static cross-check of the binary wire codec.

``cluster/wire.py`` keeps growing (the version constant lives there, not
here — this checker reads it from the AST); every frame added carries
four obligations that nothing enforced until now:

  1. a frame id that collides with no other id (codes are append-only);
  2. a paired encoder + decoder, and the decoder registered in
     ``_DECODERS``;
  3. a version gate with a pickle fallback when the frame is newer than
     wire v1 — the ``peer_wire < N`` / ``return None`` dance that keeps
     rolling upgrades possible (``FRAME_MIN_WIRE`` is the declarative
     manifest this checker audits against, and its max must equal
     ``WIRE_VERSION`` so adding a frame without bumping the version is a
     lint error);
  4. a round-trip case in ``tests/test_wire_codec.py`` (the static twin
     of PR 7's dynamic coverage lint) and a live handler/dispatch site in
     the cluster sources — a frame nobody handles is dead wire surface.
     The coverage check parses the test module's ``_FRAME_CASES`` keys
     (``wire.<FRAME>`` attributes), so a frame merely *mentioned* in a
     comment or docstring no longer satisfies it.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..model import Checker, Finding, Module, Project

WIRE_PATH = "ray_tpu/cluster/wire.py"
CODEC_TEST_PATH = "tests/test_wire_codec.py"
CLUSTER_PREFIX = "ray_tpu/cluster/"

# Module-level ALL_CAPS int assignments that are NOT frame codes.
NON_FRAME_CONSTANTS = {"MAGIC", "WIRE_VERSION"}
NON_FRAME_PREFIXES = ("_", "MAX_", "SPEC_")

# Message types delivered by client-side push dispatch (RpcClient
# push_handler) rather than a server ``.handler(...)`` registration.
_ENC_PREFIX = "_enc_"
_DEC_PREFIX = "_dec_"


def _int_value(node: ast.expr) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _module_dict(tree: ast.Module, name: str) -> Optional[ast.Dict]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            return node.value
    return None


class WireDisciplineChecker(Checker):
    rule_id = "wire-discipline"
    description = ("wire.py frame ids, encoder/decoder pairing, version "
                   "gates + pickle fallbacks, handler sites, codec tests")

    def run(self, project: Project) -> Iterator[Finding]:
        mod = project.get(WIRE_PATH)
        if mod is None:
            return
        tree = mod.tree

        frame_codes: Dict[str, int] = {}
        frame_lines: Dict[str, int] = {}
        wire_version: Optional[int] = None
        for node in tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            val = _int_value(node.value)
            if val is None:
                continue
            if name == "WIRE_VERSION":
                wire_version = val
                continue
            if not name.isupper() or name in NON_FRAME_CONSTANTS \
                    or name.startswith(NON_FRAME_PREFIXES):
                continue
            frame_codes[name] = val
            frame_lines[name] = node.lineno

        # ---- 1. id collisions ------------------------------------------
        by_value: Dict[int, List[str]] = {}
        for name, val in frame_codes.items():
            by_value.setdefault(val, []).append(name)
        for val, names in sorted(by_value.items()):
            if len(names) > 1:
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=frame_lines[names[1]], col=0,
                    message=(f"frame id collision: {', '.join(sorted(names))}"
                             f" all use code 0x{val:02X}"),
                    hint="codes are append-only; assign the next free code",
                    symbol=names[1])

        # ---- 2. decoder registration + encoder/decoder pairing ---------
        decoders = _module_dict(tree, "_DECODERS")
        decoder_keys: Set[str] = set()
        decoder_fns: Set[str] = set()
        if decoders is None:
            yield Finding(rule=self.rule_id, path=mod.relpath, line=1, col=0,
                          message="no module-level _DECODERS dict found",
                          hint="register every frame's decoder in _DECODERS",
                          symbol="_DECODERS")
        else:
            seen_keys: Set[str] = set()
            for key, val in zip(decoders.keys, decoders.values):
                kname = key.id if isinstance(key, ast.Name) else None
                if kname is None:
                    continue
                if kname in seen_keys:
                    yield Finding(
                        rule=self.rule_id, path=mod.relpath,
                        line=key.lineno, col=key.col_offset,
                        message=f"duplicate _DECODERS entry for {kname}",
                        hint="one decoder per frame code", symbol="_DECODERS")
                seen_keys.add(kname)
                decoder_keys.add(kname)
                if isinstance(val, ast.Name):
                    decoder_fns.add(val.id)
            for name in sorted(frame_codes):
                if name not in decoder_keys:
                    yield Finding(
                        rule=self.rule_id, path=mod.relpath,
                        line=frame_lines[name], col=0,
                        message=f"frame {name} has no _DECODERS entry",
                        hint="every frame id needs a registered decoder",
                        symbol=name)
            for kname in sorted(decoder_keys - set(frame_codes)):
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=decoders.lineno, col=0,
                    message=f"_DECODERS key {kname} is not a frame constant",
                    hint="declare the frame code at module level",
                    symbol="_DECODERS")

        fn_defs = {node.name: node for node in tree.body
                   if isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        registered_encoders: Set[str] = set()
        encoder_types: Dict[str, str] = {}   # msg type -> encoder fn name
        resp_types: Dict[str, str] = {}
        for dict_name, sink in (("_ENCODERS", encoder_types),
                                ("_RESP_ENCODERS", resp_types)):
            table = _module_dict(tree, dict_name)
            if table is None:
                continue
            for key, val in zip(table.keys, table.values):
                if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                        and isinstance(val, ast.Name):
                    sink[key.value] = val.id
                    registered_encoders.add(val.id)

        for enc_name in sorted(n for n in fn_defs if n.startswith(_ENC_PREFIX)):
            suffix = enc_name[len(_ENC_PREFIX):]
            dec_name = _DEC_PREFIX + suffix
            # Frame-level encoders emit a `_head(CODE, ...)` or sit in the
            # dispatch tables; item-level helpers (e.g. the "added"-list
            # sub-encoders) pair by name but never register a frame.
            is_frame_encoder = (enc_name in registered_encoders
                                or self._emitted_frames(fn_defs[enc_name]))
            if dec_name not in fn_defs:
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=fn_defs[enc_name].lineno, col=0,
                    message=f"encoder {enc_name} has no paired {dec_name}",
                    hint="every encoder needs a decoder twin (name-paired)",
                    symbol=enc_name)
            elif is_frame_encoder and dec_name not in decoder_fns \
                    and decoders is not None:
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=fn_defs[dec_name].lineno, col=0,
                    message=f"decoder {dec_name} is not registered in "
                            f"_DECODERS",
                    hint="add it to _DECODERS under its frame code",
                    symbol=dec_name)

        # ---- 3. FRAME_MIN_WIRE manifest + version gates ----------------
        manifest = _module_dict(tree, "FRAME_MIN_WIRE")
        min_wire: Dict[str, int] = {}
        if manifest is None:
            yield Finding(
                rule=self.rule_id, path=mod.relpath, line=1, col=0,
                message="no FRAME_MIN_WIRE manifest in wire.py",
                hint="declare {FRAME_CODE: min peer wire version} for every "
                     "frame so gates are auditable",
                symbol="FRAME_MIN_WIRE")
        else:
            for key, val in zip(manifest.keys, manifest.values):
                if isinstance(key, ast.Name) and _int_value(val) is not None:
                    min_wire[key.id] = _int_value(val)
            missing = sorted(set(frame_codes) - set(min_wire))
            for name in missing:
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=frame_lines[name], col=0,
                    message=f"frame {name} missing from FRAME_MIN_WIRE",
                    hint="declare the frame's minimum peer wire version",
                    symbol=name)
            for name in sorted(set(min_wire) - set(frame_codes)):
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=manifest.lineno, col=0,
                    message=f"FRAME_MIN_WIRE entry {name} is not a frame",
                    hint="remove the stale manifest entry", symbol=name)
            if min_wire and wire_version is not None \
                    and max(min_wire.values()) != wire_version:
                yield Finding(
                    rule=self.rule_id, path=mod.relpath, line=1, col=0,
                    message=(f"WIRE_VERSION is {wire_version} but the newest "
                             f"frame in FRAME_MIN_WIRE is v"
                             f"{max(min_wire.values())}"),
                    hint="bump WIRE_VERSION when adding a frame (and gate "
                         "its encoder on peer_wire)",
                    symbol="WIRE_VERSION")

        # Version-gated encoders: any encoder that can emit a >v1 frame
        # must compare peer_wire and have a `return None` pickle fallback.
        for enc_name, node in sorted(fn_defs.items()):
            if not enc_name.startswith(_ENC_PREFIX):
                continue
            emitted = self._emitted_frames(node)
            gated = [c for c in emitted if min_wire.get(c, 1) > 1]
            if not gated:
                continue
            has_gate = any(
                isinstance(n, ast.Compare) and any(
                    isinstance(x, ast.Name) and x.id == "peer_wire"
                    for x in ast.walk(n))
                for n in ast.walk(node))
            has_fallback = any(
                isinstance(n, ast.Return) and isinstance(n.value, ast.Constant)
                and n.value.value is None
                for n in ast.walk(node))
            if not (has_gate and has_fallback):
                yield Finding(
                    rule=self.rule_id, path=mod.relpath,
                    line=node.lineno, col=0,
                    message=(f"{enc_name} emits v>1 frame(s) "
                             f"{', '.join(sorted(gated))} without a "
                             f"peer_wire gate + `return None` pickle "
                             f"fallback"),
                    hint="check `peer_wire < N` and return None so pickle "
                         "carries the message to older peers",
                    symbol=enc_name)

        # ---- 4a. handler/dispatch sites --------------------------------
        handler_types: Set[str] = set()
        literal_strings: Set[str] = set()
        for other in project.glob(CLUSTER_PREFIX):
            if other.relpath == mod.relpath:
                continue
            for node in ast.walk(other.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "handler" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    handler_types.add(node.args[0].value)
                elif isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    literal_strings.add(node.value)
        if handler_types or literal_strings:
            for mtype, enc_name in sorted(encoder_types.items()):
                if mtype in resp_types and mtype not in handler_types:
                    yield Finding(
                        rule=self.rule_id, path=mod.relpath,
                        line=fn_defs[enc_name].lineno
                        if enc_name in fn_defs else 1, col=0,
                        message=(f"request type '{mtype}' has a response "
                                 f"codec but no .handler(...) site in the "
                                 f"cluster sources"),
                        hint="register a server handler or drop the codec",
                        symbol=enc_name)
                elif mtype not in handler_types \
                        and mtype not in literal_strings:
                    yield Finding(
                        rule=self.rule_id, path=mod.relpath,
                        line=fn_defs[enc_name].lineno
                        if enc_name in fn_defs else 1, col=0,
                        message=(f"message type '{mtype}' has a codec but "
                                 f"no handler or dispatch site in the "
                                 f"cluster sources"),
                        hint="dead wire surface: wire it up or remove it",
                        symbol=enc_name)

        # ---- 4b. codec-test coverage -----------------------------------
        test_mod = project.get(CODEC_TEST_PATH)
        if test_mod is not None:
            case_keys = self._frame_case_keys(test_mod.tree)
            if case_keys is None:
                # No enumerable _FRAME_CASES dict: fall back to the weaker
                # textual check so the rule degrades rather than vanishes.
                for name in sorted(frame_codes):
                    if name not in test_mod.source:
                        yield Finding(
                            rule=self.rule_id, path=mod.relpath,
                            line=frame_lines[name], col=0,
                            message=(f"frame {name} is never referenced in "
                                     f"{CODEC_TEST_PATH}"),
                            hint="add a round-trip + truncation case for it",
                            symbol=name)
            else:
                for name in sorted(set(frame_codes) - case_keys):
                    yield Finding(
                        rule=self.rule_id, path=mod.relpath,
                        line=frame_lines[name], col=0,
                        message=(f"frame {name} has no _FRAME_CASES entry "
                                 f"in {CODEC_TEST_PATH}"),
                        hint="add a wire.<FRAME> round-trip case to "
                             "_FRAME_CASES (textual mentions don't count)",
                        symbol=name)

    @staticmethod
    def _frame_case_keys(tree: ast.Module) -> Optional[Set[str]]:
        """Frame names enumerated as ``wire.<FRAME>`` keys of the test
        module's module-level ``_FRAME_CASES`` dict; None if the dict is
        absent (older layouts)."""
        table = _module_dict(tree, "_FRAME_CASES")
        if table is None:
            return None
        keys: Set[str] = set()
        for key in table.keys:
            if isinstance(key, ast.Attribute) \
                    and isinstance(key.value, ast.Name) \
                    and key.value.id == "wire":
                keys.add(key.attr)
        return keys

    @staticmethod
    def _emitted_frames(fn: ast.AST) -> Set[str]:
        """Frame constants passed to `_head(CODE, ...)` inside ``fn``."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "_head" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    out.add(first.id)
                elif isinstance(first, ast.IfExp):
                    for side in (first.body, first.orelse):
                        if isinstance(side, ast.Name):
                            out.add(side.id)
        return out
