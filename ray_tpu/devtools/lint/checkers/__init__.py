"""The five raylint checkers (one module per rule)."""
