"""hot-path: functions marked ``# raylint: hotpath`` stay lean.

The flight recorder's live 5k-batch profile names the top burners —
``controller.py:pump`` (43% of head self-time), ``protocol.py:_recv_exact``
(14% head / 60% worker) and the worker inner loop. Those functions run
per-frame or per-task at full rate; one "temporary" ``logger.info`` or a
convenience ``json.dumps`` inside them is a multi-percent throughput
regression that no test notices.

Marking a def with ``# raylint: hotpath`` (on the def line or the line
above) forbids, in that function's direct body:

  * any ``pickle`` / ``json`` / ``marshal`` call (serialization belongs
    on the slow path or behind the wire codec);
  * INFO-or-louder logging calls (``logger.info/warning/error`` —
    hot-path logging is DEBUG-gated or counter-based);
  * eager f-string arguments to ANY log call (``logger.debug(f"{x}")``
    formats even when the level is off — pass args lazily).

Nested defs are not covered (annotate them separately if they are hot).
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from ..model import Checker, Finding, Module, Project, call_root, qualname_map

FORBIDDEN_MODULES = ("pickle.", "json.", "marshal.", "cPickle.")
LOUD_LOG_LEVELS = {"info", "warning", "error", "critical", "exception"}
LOG_LEVELS = LOUD_LOG_LEVELS | {"debug", "log"}


def _is_logger_call(dotted: str) -> Tuple[bool, str]:
    """(is a log call, level) for `logger.info`, `logging.warning`,
    `self._log.debug`, ..."""
    if "." not in dotted:
        return False, ""
    head, leaf = dotted.rsplit(".", 1)
    if leaf not in LOG_LEVELS:
        return False, ""
    base = head.rsplit(".", 1)[-1].lower()
    return ("log" in base), leaf


class HotPathChecker(Checker):
    rule_id = "hot-path"
    description = ("`# raylint: hotpath` functions: no pickle/json, no "
                   "INFO logging, no eager f-string log args")
    paths = ("ray_tpu/", "scripts/")

    def run(self, project: Project) -> Iterator[Finding]:
        for prefix in self.paths:
            for mod in project.glob(prefix):
                if not mod.hotpath_lines:
                    continue
                yield from self._check_module(mod)

    def _check_module(self, mod: Module) -> Iterator[Finding]:
        for node, qual in qualname_map(mod.tree).items():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.lineno in mod.hotpath_lines:
                yield from self._check_fn(mod, node, qual)

    def _check_fn(self, mod: Module, fn: ast.AST, qual: str
                  ) -> Iterator[Finding]:
        findings = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.Call):
                dotted = call_root(node.func)
                if dotted:
                    if any(dotted.startswith(p) for p in FORBIDDEN_MODULES):
                        findings.append(Finding(
                            rule=self.rule_id, path=mod.relpath,
                            line=node.lineno, col=node.col_offset,
                            message=f"`{dotted}` call in hot-path "
                                    f"function `{fn.name}`",
                            hint="serialize on the slow path (or via the "
                                 "struct-packed wire codec)",
                            symbol=qual))
                    else:
                        is_log, level = _is_logger_call(dotted)
                        if is_log and level in LOUD_LOG_LEVELS:
                            findings.append(Finding(
                                rule=self.rule_id, path=mod.relpath,
                                line=node.lineno, col=node.col_offset,
                                message=f"{level.upper()}-level log call "
                                        f"in hot-path function "
                                        f"`{fn.name}`",
                                hint="hot paths log at DEBUG behind a "
                                     "level check, or bump a counter",
                                symbol=qual))
                        elif is_log and any(
                                isinstance(a, ast.JoinedStr)
                                for a in node.args):
                            findings.append(Finding(
                                rule=self.rule_id, path=mod.relpath,
                                line=node.lineno, col=node.col_offset,
                                message=f"eager f-string log argument in "
                                        f"hot-path function `{fn.name}`",
                                hint="f-strings format even when the "
                                     "level is off; pass lazy %-args",
                                symbol=qual))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        yield from findings
