"""async-blocking: no blocking calls reachable from event-loop coroutines.

The control plane's throughput ceiling *is* the head's event loop (the
measured ~300 µs/task of ROADMAP item 3 lives in it), so a single stray
``time.sleep`` / sync file read / subprocess wait inside any of the
cluster's ``async def`` handlers stalls every connection at once.

The checker walks every ``async def`` in the cluster sources and flags
blocking primitives in its body — and, because handlers delegate to sync
helper methods, it also follows plain same-module calls (``self.foo()``,
``foo()``) a few hops deep and attributes the blocking site back to the
coroutines that can reach it. Function *references* passed to
``asyncio.to_thread`` / ``run_in_executor`` are not calls and are never
descended into, so the standard off-loop escape hatches come out clean.

``pickle.dumps``/``loads`` are flagged only when written directly in a
coroutine body: a pickle of an unbounded live structure stalls the loop
for as long as the structure is large, which is invisible in code review
precisely because it looks cheap. Bounded/deliberate cases carry a
``# raylint: disable=async-blocking`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..model import Checker, Finding, Module, Project, call_root, qualname_map

# Dotted-name call targets that block the calling thread.
BLOCKING_CALLS: Dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "move to a thread: `await asyncio.to_thread(...)`",
    "subprocess.call": "move to a thread: `await asyncio.to_thread(...)`",
    "subprocess.check_call": "move to a thread: `await asyncio.to_thread(...)`",
    "subprocess.check_output": "move to a thread: `await asyncio.to_thread(...)`",
    "subprocess.Popen": "spawn off-loop: `await asyncio.to_thread(...)` "
                        "(fork+exec blocks for milliseconds)",
    "os.system": "use asyncio.create_subprocess_exec or a thread",
    "socket.create_connection": "connect in a thread or use asyncio streams",
    "open": "file I/O blocks the loop: `await asyncio.to_thread(...)`",
    "os.listdir": "disk metadata I/O: `await asyncio.to_thread(...)`",
    "os.scandir": "disk metadata I/O: `await asyncio.to_thread(...)`",
    "os.stat": "disk metadata I/O: `await asyncio.to_thread(...)`",
    "os.remove": "disk I/O: `await asyncio.to_thread(...)`",
    "os.unlink": "disk I/O: `await asyncio.to_thread(...)`",
    "os.rename": "disk I/O: `await asyncio.to_thread(...)`",
    "os.replace": "disk I/O: `await asyncio.to_thread(...)`",
    "os.makedirs": "disk I/O: `await asyncio.to_thread(...)`",
    "os.fsync": "disk I/O: `await asyncio.to_thread(...)`",
    "shutil.rmtree": "disk I/O: `await asyncio.to_thread(...)`",
}

# Direct-only: flagged when written in the coroutine body itself (see
# module docstring for why transitive pickle would be all noise).
DIRECT_ONLY_CALLS: Dict[str, str] = {
    "pickle.dumps": "loop-thread pickle of an unbounded structure; "
                    "serialize off-loop or bound and annotate",
    "pickle.loads": "loop-thread unpickle of an unbounded blob; "
                    "deserialize off-loop or bound and annotate",
}

# Method names that block when called un-awaited on a non-asyncio object.
BLOCKING_METHODS: Dict[str, str] = {
    "recv": "sync socket read on the event loop",
    "recv_into": "sync socket read on the event loop",
    "recvfrom": "sync socket read on the event loop",
    "sendall": "sync socket write on the event loop",
    "sendmsg": "sync socket write on the event loop",
    "accept": "sync socket accept on the event loop",
    "connect": "sync socket connect on the event loop",
    "join": "thread/process join blocks the loop",
}

# `.join` is shared with str.join: only flag it when the receiver name
# says thread/process (``sep.join(parts)`` must never fire the rule).
_JOIN_RECEIVER_HINTS = ("thread", "proc", "worker", "sampler", "pump")

MAX_DEPTH = 3  # call-graph hops followed out of an async def


def _local_name(node: ast.expr) -> Optional[str]:
    """Resolve a call target to a same-module function key: 'foo' for
    plain calls, 'self.foo' collapsed to 'foo' for method calls."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _FnInfo:
    __slots__ = ("node", "qual", "is_async", "calls", "blocking")

    def __init__(self, node, qual: str, is_async: bool):
        self.node = node
        self.qual = qual
        self.is_async = is_async
        self.calls: List[Tuple[str, int]] = []     # (callee key, line)
        # (line, col, dotted target, hint)
        self.blocking: List[Tuple[int, int, str, str]] = []


def _collect_functions(mod: Module) -> Dict[str, List[_FnInfo]]:
    """Index every def by bare name (methods collapse to their own name so
    ``self.foo()`` resolves across classes in the same module — a tolerable
    over-approximation for lint purposes)."""
    quals = qualname_map(mod.tree)
    by_name: Dict[str, List[_FnInfo]] = {}

    for node, qual in quals.items():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = _FnInfo(node, qual, isinstance(node, ast.AsyncFunctionDef))
        _scan_body(node, info)
        by_name.setdefault(node.name, []).append(info)
    return by_name


def _scan_body(fn: ast.AST, info: _FnInfo) -> None:
    """Record blocking primitives and same-module calls in ``fn``'s own
    body (nested defs are separate functions; entering them here would
    misattribute thread-target closures to the enclosing coroutine)."""
    awaited_calls: Set[ast.Call] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            awaited_calls.add(node.value)
        if isinstance(node, ast.Call):
            dotted = call_root(node.func)
            if node not in awaited_calls and dotted:
                leaf = dotted.rsplit(".", 1)[-1]
                if dotted in BLOCKING_CALLS:
                    info.blocking.append((node.lineno, node.col_offset,
                                          dotted, BLOCKING_CALLS[dotted]))
                elif dotted in DIRECT_ONLY_CALLS:
                    info.blocking.append((node.lineno, node.col_offset,
                                          dotted,
                                          DIRECT_ONLY_CALLS[dotted]))
                elif "." in dotted and leaf in BLOCKING_METHODS \
                        and not dotted.startswith(("asyncio.",)):
                    receiver = dotted.rsplit(".", 1)[0].lower()
                    if leaf != "join" or any(
                            h in receiver for h in _JOIN_RECEIVER_HINTS):
                        info.blocking.append((node.lineno, node.col_offset,
                                              dotted,
                                              BLOCKING_METHODS[leaf]))
            key = _local_name(node.func)
            if key is not None:
                info.calls.append((key, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in getattr(fn, "body", []):
        visit(stmt)


class AsyncBlockingChecker(Checker):
    rule_id = "async-blocking"
    description = ("blocking calls (sleep/file/socket/subprocess/unbounded "
                   "pickle) reachable from cluster async handlers")
    # serve/ is included because the Router is an asyncio actor: one
    # blocking call in its event loop stalls EVERY endpoint's routing.
    # loopmon wraps *every* loop callback, so a blocking call there is a
    # blocking call in all monitored loops at once.
    paths = ("ray_tpu/cluster/", "ray_tpu/serve/",
             "ray_tpu/_private/loopmon.py")

    def run(self, project: Project) -> Iterator[Finding]:
        for prefix in self.paths:
            for mod in project.glob(prefix):
                yield from self._check_module(mod)

    def _check_module(self, mod: Module) -> Iterator[Finding]:
        by_name = _collect_functions(mod)
        all_fns = [f for fns in by_name.values() for f in fns]

        # For each sync function: the set of async-def quals that reach it
        # within MAX_DEPTH same-module hops.
        reached_by: Dict[int, Set[str]] = {}
        # Direct-only findings live where they are written.
        emitted: Set[Tuple[int, int, str]] = set()

        for fn in all_fns:
            if not fn.is_async:
                continue
            seen: Set[int] = {id(fn)}
            frontier = [fn]
            depth = 0
            while frontier and depth <= MAX_DEPTH:
                nxt: List[_FnInfo] = []
                for cur in frontier:
                    reached_by.setdefault(id(cur), set()).add(fn.qual)
                    for callee_key, _line in cur.calls:
                        for cand in by_name.get(callee_key, ()):
                            # Never cross into another coroutine: calling
                            # an async def returns a coroutine object, it
                            # does not run its body here.
                            if cand.is_async or id(cand) in seen:
                                continue
                            seen.add(id(cand))
                            nxt.append(cand)
                frontier = nxt
                depth += 1

        for fn in all_fns:
            sources = reached_by.get(id(fn), set())
            if not sources:
                continue
            direct = fn.is_async
            for line, col, dotted, hint in fn.blocking:
                if dotted in DIRECT_ONLY_CALLS and not direct:
                    continue
                key = (line, col, dotted)
                if key in emitted:
                    continue
                emitted.add(key)
                if direct:
                    origin = "in coroutine body"
                else:
                    names = sorted(sources)
                    origin = f"reachable from async `{names[0]}`"
                    if len(names) > 1:
                        origin += f" (+{len(names) - 1} more)"
                yield Finding(
                    rule=self.rule_id, path=mod.relpath, line=line, col=col,
                    message=f"blocking call `{dotted}` {origin}",
                    hint=hint, symbol=fn.qual)
