"""thread-shared-state: cross-thread attribute mutation wants a lock.

Every process in the control plane runs helper threads beside its main
thread or event loop — the flight-recorder sampler, the driver's
``_stats_flush_loop``, reader threads, log pumps, GCS warm/persist
helpers. A ``self.x`` that both a thread-target method and a main-thread
method mutate without a lock is a data race the GIL merely makes rare
(TSAN catches the native twin of this; nothing caught the Python one).

Per class that starts a thread on one of its own methods
(``threading.Thread(target=self.foo)``), this checker:

  1. closes the set of methods reachable from thread entrypoints via
     ``self.method()`` calls;
  2. collects ``self.attr`` mutations (assign / augassign / del /
     ``self.attr[...] =``) per method, noting whether each occurs inside
     a ``with self.<...lock...>:`` block;
  3. flags attributes mutated on BOTH sides of the thread boundary where
     at least one mutation is unlocked. ``__init__`` doesn't count (the
     thread doesn't exist yet).

Benign cases (GIL-atomic flag stores, monotonic counters tolerating a
lost update) are annotated ``# raylint: disable=thread-shared-state``
with a justification — the annotation is the reviewed contract.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Set, Tuple

from ..model import Checker, Finding, Module, Project, call_root


@dataclass
class _Mutation:
    attr: str
    line: int
    col: int
    locked: bool


@dataclass
class _Method:
    node: ast.AST
    mutations: List[_Mutation] = field(default_factory=list)
    self_calls: Set[str] = field(default_factory=set)
    thread_targets: Set[str] = field(default_factory=set)


def _self_attr(node: ast.expr) -> str:
    """'attr' for a `self.attr` expression (possibly under Subscript)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _is_lock_ctx(expr: ast.expr) -> bool:
    """`with self._lock:` / `with self._counts_lock:` — any self attribute
    whose name smells like a lock."""
    attr = _self_attr(expr)
    low = attr.lower()
    return bool(attr) and ("lock" in low or "mutex" in low or "cond" in low)


def _scan_method(fn: ast.AST) -> _Method:
    info = _Method(fn)

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return  # nested defs are their own (closure) world
        if isinstance(node, ast.With):
            inner = locked or any(_is_lock_ctx(item.context_expr)
                                  for item in node.items)
            for item in node.items:
                visit(item.context_expr, locked)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            flat = []
            for tgt in node.targets:
                if isinstance(tgt, (ast.Tuple, ast.List)):
                    flat.extend(tgt.elts)   # a, self.b = ... unpacking
                else:
                    flat.append(tgt)
            for tgt in flat:
                attr = _self_attr(tgt)
                if attr:
                    info.mutations.append(_Mutation(
                        attr, tgt.lineno, tgt.col_offset, locked))
        elif isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr:
                info.mutations.append(_Mutation(
                    attr, node.target.lineno, node.target.col_offset, locked))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr:
                    info.mutations.append(_Mutation(
                        attr, tgt.lineno, tgt.col_offset, locked))
        elif isinstance(node, ast.Call):
            dotted = call_root(node.func)
            if dotted.endswith("Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt_attr = _self_attr(kw.value)
                        if tgt_attr:
                            info.thread_targets.add(tgt_attr)
            if dotted.startswith("self.") and dotted.count(".") == 1:
                info.self_calls.add(dotted.split(".", 1)[1])
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in getattr(fn, "body", []):
        visit(stmt, False)
    return info


class ThreadSharedStateChecker(Checker):
    rule_id = "thread-shared-state"
    description = ("unlocked self.attr mutations shared between a thread "
                   "entrypoint and main-thread methods")
    paths = ("ray_tpu/cluster/", "ray_tpu/_private/flight_recorder.py",
             "ray_tpu/_private/timeseries.py", "ray_tpu/monitor.py")

    def run(self, project: Project) -> Iterator[Finding]:
        for prefix in self.paths:
            for mod in project.glob(prefix):
                yield from self._check_module(mod)

    def _check_module(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)

    def _check_class(self, mod: Module, cls: ast.ClassDef
                     ) -> Iterator[Finding]:
        methods: Dict[str, _Method] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[item.name] = _scan_method(item)

        entries: Set[str] = set()
        for m in methods.values():
            entries.update(t for t in m.thread_targets if t in methods)
        if not entries:
            return

        # Closure of thread-side methods over self.method() calls.
        thread_side: Set[str] = set()
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            if name in thread_side:
                continue
            thread_side.add(name)
            frontier.extend(c for c in methods[name].self_calls
                            if c in methods and c not in thread_side)

        # Async methods all run on one loop; they are "main side" here.
        per_attr: Dict[str, Dict[str, List[Tuple[str, _Mutation]]]] = {}
        for name, m in methods.items():
            if name == "__init__":
                continue
            side = "thread" if name in thread_side else "main"
            for mut in m.mutations:
                per_attr.setdefault(mut.attr, {}).setdefault(
                    side, []).append((name, mut))

        for attr, sides in sorted(per_attr.items()):
            if "thread" not in sides or "main" not in sides:
                continue
            unlocked = [(name, mut)
                        for muts in sides.values()
                        for name, mut in muts if not mut.locked]
            if not unlocked:
                continue
            name, mut = min(unlocked, key=lambda nm: nm[1].line)
            t_names = sorted({n for n, _ in sides["thread"]})
            m_names = sorted({n for n, _ in sides["main"]})
            yield Finding(
                rule=self.rule_id, path=mod.relpath,
                line=mut.line, col=mut.col,
                message=(f"`self.{attr}` mutated by thread-side "
                         f"{t_names} and main-side {m_names} with an "
                         f"unlocked write in `{name}`"),
                hint="guard every mutation with the owning lock, or "
                     "annotate the benign case with a justification",
                symbol=f"{cls.name}.{name}")
