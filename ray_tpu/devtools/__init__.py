"""Developer tooling that ships with the repo but never imports at runtime.

Nothing under ``ray_tpu.devtools`` may be imported by production modules —
it exists for ``scripts/lint.py``, CI gates, and future codemod tooling.
"""
