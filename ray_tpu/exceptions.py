"""Exception hierarchy for ray_tpu.

Mirrors the user-visible error surface of the reference (reference:
``python/ray/exceptions.py`` and ``src/ray/common/status.h``): task errors wrap
the remote traceback, actor errors mark a dead/restarting actor, object loss and
worker crashes are distinct so retry/recovery layers can react differently.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; re-raised at ``get()`` on the caller.

    Holds the remote traceback text so the driver sees where the failure
    happened (reference behavior: RayTaskError in python/ray/exceptions.py).
    """

    def __init__(self, function_name: str, cause: BaseException,
                 remote_traceback: Optional[str] = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = remote_traceback or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"task {function_name} failed: {type(cause).__name__}: {cause}\n"
            f"remote traceback:\n{self.remote_traceback}"
        )

    def __reduce__(self):
        # Cross-process safe: fall back to a repr stand-in for causes that
        # don't pickle (tracebacks never do; we carry the formatted text).
        import pickle

        try:
            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:  # noqa: BLE001
            cause = RuntimeError(repr(self.cause))
        return (TaskError, (self.function_name, cause, self.remote_traceback))


class ActorError(RayTpuError):
    """An actor task cannot complete because the actor died."""

    def __init__(self, actor_id=None, message="The actor died unexpectedly"):
        self.actor_id = actor_id
        self.message = message
        super().__init__(f"{message} (actor_id={actor_id})")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.message))


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed."""

    def __init__(self, object_id, message="Object lost"):
        self.object_id = object_id
        self.message = message
        super().__init__(f"{message}: {object_id}")

    def __reduce__(self):
        return (type(self), (self.object_id, self.message))


class ObjectStoreFullError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died mid-execution."""


class NodeDiedError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(timeout=...)`` expired before the object was ready."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task was cancelled (task_id={task_id})")

    def __reduce__(self):
        return (type(self), (self.task_id,))


class TaskTimeoutError(RayTpuError):
    """A task exceeded its ``.options(timeout_s=...)`` deadline and was
    killed by the controller (SIGTERM, then SIGKILL). Deadline kills are the
    workload's fault, so they do NOT consume ``max_retries`` unless the task
    opted in with ``retry_on_timeout=True``."""

    def __init__(self, task_id=None, timeout_s=None):
        self.task_id = task_id
        self.timeout_s = timeout_s
        super().__init__(
            f"Task exceeded its deadline of {timeout_s}s and was killed "
            f"(task_id={task_id})")

    def __reduce__(self):
        return (type(self), (self.task_id, self.timeout_s))


class TaskPoisonedError(RayTpuError):
    """The function fingerprint was quarantined after repeated worker-fatal
    failures (``RAY_TPU_POISON_THRESHOLD`` strikes); submissions fail fast
    instead of churning worker respawns. Clear with
    ``cli quarantine --clear <fingerprint>``."""

    def __init__(self, fn_id=None, name=None, strikes=0):
        self.fn_id = fn_id
        self.name = name
        self.strikes = strikes
        super().__init__(
            f"Function {name or '?'} (fingerprint="
            f"{fn_id.hex() if isinstance(fn_id, bytes) else fn_id}) is "
            f"quarantined after {strikes} worker-fatal failures; clear with "
            f"`cli quarantine --clear`")

    def __reduce__(self):
        return (type(self), (self.fn_id, self.name, self.strikes))


class ActorExitError(BaseException):
    """Control-flow exception raised by ``exit_actor()`` — intentionally a
    BaseException so user ``except Exception`` blocks can't swallow it
    (reference: actor.py:920 exit_actor raises via SystemExit)."""


class RuntimeEnvError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    """A placement-group operation failed (removed while tasks were
    pending on it, invalid bundle/strategy, or an unknown group)."""


class ClusterUnavailableError(RayTpuError):
    """Cluster infrastructure failure (no reachable nodes, undeliverable
    task) — distinct from user-code errors so callers can retry safely."""


class ReplicaUnavailableError(RayTpuError):
    """A serve request cannot be (re)placed on any live replica.

    Raised by the serve router when a stream's pinned replica died (streams
    fail fast instead of hanging to the idle timeout), when a whole-response
    call exhausted its retry budget across sibling replicas, or when a
    backend has no routable replica at all. Also raised by a poisoned
    backend (e.g. ``serve.LMBackend`` after an engine-step failure) so the
    router treats it as a replica-infrastructure failure — retryable on a
    sibling — rather than an application error."""

    def __init__(self, backend_tag=None, message="no replica available"):
        self.backend_tag = backend_tag
        self.message = message
        super().__init__(f"{message} (backend={backend_tag})")

    def __reduce__(self):
        return (type(self), (self.backend_tag, self.message))


__all__ = [
    "PlacementGroupError",
    "RayTpuError",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ObjectLostError",
    "ObjectStoreFullError",
    "WorkerCrashedError",
    "NodeDiedError",
    "GetTimeoutError",
    "TaskCancelledError",
    "TaskTimeoutError",
    "TaskPoisonedError",
    "RuntimeEnvError",
    "ClusterUnavailableError",
    "ReplicaUnavailableError",
]
