"""DataStream API (reference: streaming/python/datastream.py).

    ctx = StreamingContext()
    (ctx.from_collection(lines)
        .flat_map(str.split)
        .key_by(lambda w: w)
        .reduce(lambda a, b: a + b)   # pairs are (key, count) after count_by
        .sink())
    results = ctx.submit()

Operators chain into a JobGraph; ``submit()`` materializes JobWorker actors,
wires credit-based channels, streams the source collection through, and
returns the sink's collected output.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, List, Optional

import cloudpickle

import ray_tpu

from .graph import (
    BROADCAST, FORWARD, KEY_HASH, REBALANCE, Edge, JobGraph, Operator,
)
from .worker import BATCH_SIZE, JobWorker


class DataStream:
    def __init__(self, ctx: "StreamingContext", op_id: int, keyed: bool = False):
        self._ctx = ctx
        self._op_id = op_id
        self._keyed = keyed

    def _chain(self, kind: str, fn: Optional[Callable], parallelism: int,
               partition: str, keyed: bool = False) -> "DataStream":
        # broadcast() overrides the partition of the NEXT edge regardless of
        # which operator follows (map/filter/sink/...).
        partition = getattr(self, "_force_partition", partition)
        op = self._ctx._add_op(kind, fn, parallelism)
        self._ctx.graph.add_edge(self._op_id, op.op_id, partition)
        return DataStream(self._ctx, op.op_id, keyed)

    def _default_partition(self) -> str:
        return KEY_HASH if self._keyed else REBALANCE

    def map(self, fn: Callable, parallelism: int = 1) -> "DataStream":
        return self._chain("map", fn, parallelism, self._default_partition())

    def flat_map(self, fn: Callable, parallelism: int = 1) -> "DataStream":
        return self._chain("flat_map", fn, parallelism,
                           self._default_partition())

    def filter(self, fn: Callable, parallelism: int = 1) -> "DataStream":
        return self._chain("filter", fn, parallelism,
                           self._default_partition())

    def key_by(self, key_fn: Callable, parallelism: int = 1) -> "DataStream":
        """Emit (key, value) pairs; downstream sees hash-partitioned keys."""
        return self._chain("key_by", key_fn, parallelism,
                           self._default_partition(), keyed=True)

    def reduce(self, fn: Callable, parallelism: int = 1) -> "DataStream":
        """Keyed running reduction; flushes (key, aggregate) pairs at EOF."""
        if not self._keyed:
            raise ValueError("reduce requires key_by upstream")
        return self._chain("reduce", fn, parallelism, KEY_HASH, keyed=True)

    def union(self, *streams: "DataStream",
              parallelism: int = 1) -> "DataStream":
        """Merge this stream with others into one interleaved stream
        (reference: datastream.py:197 union). The result is keyed only if
        every input is keyed (so a downstream reduce stays legal)."""
        for s in streams:
            if s._ctx is not self._ctx:
                raise ValueError("union requires streams from one context")
        keyed = self._keyed and all(s._keyed for s in streams)
        op = self._ctx._add_op("union", None, parallelism)
        for s in (self, *streams):
            partition = getattr(s, "_force_partition",
                                s._default_partition())
            self._ctx.graph.add_edge(s._op_id, op.op_id, partition)
        return DataStream(self._ctx, op.op_id, keyed)

    def broadcast(self) -> "DataStream":
        out = DataStream(self._ctx, self._op_id, self._keyed)
        out._force_partition = BROADCAST
        return out

    def sink(self, fn: Optional[Callable] = None,
             parallelism: int = 1) -> "DataStream":
        s = self._chain("sink", fn, parallelism, self._default_partition())
        self._ctx._sinks.append(s._op_id)
        return s


class StreamingContext:
    def __init__(self, batch_size: int = BATCH_SIZE):
        import uuid

        self.graph = JobGraph()
        # Channel ids embed a job-unique component: shm channel names are
        # hashes of the channel id, and two concurrent jobs with colliding
        # ids would unlink/attach each other's live rings.
        self._job_uid = uuid.uuid4().hex[:10]
        self._op_counter = itertools.count()
        self._sources: List[tuple] = []  # (op_id, iterable)
        self._sinks: List[int] = []
        self.batch_size = batch_size
        self._workers: Dict[int, List[Any]] = {}

    def _add_op(self, kind: str, fn: Optional[Callable],
                parallelism: int) -> Operator:
        op = Operator(next(self._op_counter), kind, fn,
                      parallelism=max(parallelism, 1))
        self.graph.add_operator(op)
        return op

    def from_collection(self, items: Iterable[Any],
                        parallelism: int = 1) -> DataStream:
        op = self._add_op("source", None, parallelism)
        self._sources.append((op.op_id, items))
        return DataStream(self, op.op_id)

    # ---- physical deployment ----

    def _deploy(self) -> None:
        worker_cls = ray_tpu.remote(num_cpus=0)(JobWorker)
        for op_id, op in self.graph.operators.items():
            blob = cloudpickle.dumps(op.fn) if op.fn is not None else None
            self._workers[op_id] = [
                worker_cls.remote(op.kind, blob, i, op.parallelism)
                for i in range(op.parallelism)
            ]
        ray_tpu.get([w.ready.remote()
                     for ws in self._workers.values() for w in ws])
        # wire edges: senders learn handles, receivers learn channel ids
        for eidx, edge in enumerate(self.graph.edges):
            src_ws = self._workers[edge.src_id]
            dst_ws = self._workers[edge.dst_id]
            # The edge index keeps channel ids unique even for duplicate
            # (src, dst) pairs — e.g. s.union(s) — where a shared prefix
            # would collide shm ring names and dedupe expected inputs.
            prefix = f"{self._job_uid}:e{eidx}:{edge.src_id}-{edge.dst_id}"
            calls = []
            for i, sw in enumerate(src_ws):
                calls.append(sw.add_output.remote(
                    edge.partition, list(dst_ws), prefix))
                for j in range(len(dst_ws)):
                    calls.append(
                        dst_ws[j].expect_input.remote(f"{prefix}:{i}->{j}"))
            ray_tpu.get(calls)

    def submit(self) -> List[Any]:
        """Run the (finite) stream to completion; returns sink results
        concatenated across sink instances."""
        if not self._sources:
            raise ValueError("no sources")
        self._deploy()
        for op_id, items in self._sources:
            instances = self._workers[op_id]
            batch: List[Any] = []
            rr = 0
            for item in items:
                batch.append(item)
                if len(batch) >= self.batch_size:
                    ray_tpu.get(
                        instances[rr % len(instances)].inject.remote(batch))
                    rr += 1
                    batch = []
            if batch:
                ray_tpu.get(
                    instances[rr % len(instances)].inject.remote(batch))
            ray_tpu.get([w.finish.remote() for w in instances])

        results: List[Any] = []
        for sink_id in self._sinks:
            for w in self._workers[sink_id]:
                results.extend(ray_tpu.get(w.sink_results.remote()))
        return results

    def stats(self) -> Dict[str, Dict[str, int]]:
        out = {}
        for op_id, ws in self._workers.items():
            op = self.graph.operators[op_id]
            per = ray_tpu.get([w.stats.remote() for w in ws])
            out[op.name] = {
                "records_in": sum(s["records_in"] for s in per),
                "records_out": sum(s["records_out"] for s in per),
            }
        return out

    def shutdown(self) -> None:
        for ws in self._workers.values():
            for w in ws:
                ray_tpu.kill(w)
        self._workers = {}
