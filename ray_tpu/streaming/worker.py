"""JobWorker actor + two-transport channel protocol
(reference: streaming/python/runtime/worker.py + streaming/src/channel.h,
data_writer/data_reader, ring_buffer, flow_control).

One actor per operator instance. Each edge negotiates its transport at
wiring time:

  native — co-located pairs stream pickled batches through a C++
    shared-memory SPSC ring (``_native/channel.cc``, the reference's
    plasma-queue channel): no per-batch RPC, backpressure = ring capacity,
    EOF ordering by ring close + drain-thread join.
  actor  — cross-host fallback: ``push(channel, seq, items)`` calls with a
    credit budget (max unacked batches); large batches ride the object
    store as refs. A sender with no credits blocks on its oldest ack.

EOF markers propagate when all of an instance's input channels are
exhausted; stateful operators (reduce) flush on EOF.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

from .graph import BROADCAST, FORWARD, KEY_HASH, REBALANCE, JobGraph

BATCH_SIZE = 256
CHANNEL_CREDITS = 4  # max unacked batches per channel before sender blocks
# Batches whose payload is (approximately) larger than this travel as object
# store refs instead of pickled actor-call bodies: the blob moves through the
# shm arena / native C++ transfer plane (reference: streaming/src/channel.h
# data plane on plasma queues), and the actor call carries only the ref.
PUSH_INLINE_MAX = 32 * 1024
# Native-ring backpressure probe window: a full ring with zero reader
# progress across two consecutive windows means the consumer is dead.
BACKPRESSURE_WINDOW_S = 60.0


def _approx_nbytes(items: List[Any]) -> int:
    """Cheap payload-size estimate (sampled; no serialization)."""
    n = len(items)
    if n == 0:
        return 0
    sample = items if n <= 32 else items[:: max(1, n // 32)][:32]
    total = 0
    for x in sample:
        nb = getattr(x, "nbytes", None)
        if nb is not None:
            total += int(nb)
        elif isinstance(x, (bytes, bytearray, str)):
            total += len(x)
        elif isinstance(x, tuple) and len(x) == 2:
            v = x[1]
            total += int(getattr(v, "nbytes", 0) or 64)
        else:
            total += 64
    return total * n // len(sample)


def _stable_hash(key: Any) -> int:
    """Process-stable key hash (Python's hash() is salted per process, which
    would break cross-process key routing)."""
    import zlib

    if isinstance(key, bytes):
        data = key
    elif isinstance(key, str):
        data = key.encode()
    else:
        data = repr(key).encode()
    return zlib.crc32(data)


def _chan_shm_name(channel_id: str) -> str:
    import hashlib

    digest = hashlib.blake2b(channel_id.encode(), digest_size=10).hexdigest()
    return f"rtch-{digest}"


class _OutChannel:
    """Sender side of one edge instance pair (reference: ProducerChannel).

    Two transports, negotiated at wiring time:
      native — a shared-memory SPSC ring (``_native/channel.cc``, the
        reference's plasma-queue channel): batches are pickled straight
        into the ring; backpressure IS the ring capacity; no per-batch RPC
        at all. Used when producer and consumer share a host (the shm
        open succeeds on the consumer side).
      actor  — pickled push() calls with credit-based acks, large batches
        riding the object store as refs. The cross-host fallback.
    """

    def __init__(self, dst_handle, channel_id: str):
        self.dst = dst_handle
        self.channel_id = channel_id
        self.seq = 0
        self.inflight: deque = deque()  # (ack ref, data ref | None)
        self._writer = None
        try:
            from .._native.channel import ChannelWriter

            name = _chan_shm_name(channel_id)
            writer = ChannelWriter(name, capacity=8 * 1024 * 1024)
        except Exception:  # noqa: BLE001 - lib unavailable: actor transport
            return
        try:
            ok = ray_tpu.get(
                self.dst.open_native_channel.remote(channel_id, name))
        except Exception:  # noqa: BLE001 - consumer dead/unreachable
            ok = False
        if ok:
            self._writer = writer
        else:
            writer.close(unlink=True)  # no reader ever attached

    def _write_with_backpressure(self, payload: bytes) -> None:
        """Block indefinitely under backpressure — a slow consumer (or one
        itself blocked on ITS downstream) is normal operation, exactly like
        the actor path blocking on its oldest ack. The only escape is the
        consumer explicitly declaring itself dead (drain thread's error
        path sets the ring's reader_dead flag) — an explicit signal, not a
        progress heuristic, so cascaded backpressure can never be
        misdiagnosed as death."""
        from .._native.channel import ChannelClosed, ChannelTimeout

        while True:
            try:
                self._writer.write(payload, timeout=BACKPRESSURE_WINDOW_S)
                return
            except ChannelTimeout:
                if self._writer.reader_dead():
                    raise ChannelClosed(
                        f"consumer of {self.channel_id} died")

    def send(self, items: List[Any]) -> None:
        if self._writer is not None:
            import pickle as _pickle

            payload = _pickle.dumps(items, protocol=5)
            try:
                self._write_with_backpressure(payload)
            except ValueError:
                # Batch pickles larger than the ring: split and retry so
                # ordering stays on the ring. A single unsplittable item
                # bigger than the ring is a genuine error.
                if len(items) <= 1:
                    raise
                mid = len(items) // 2
                self.send(items[:mid])
                self.send(items[mid:])
                return
            self.seq += 1
            return
        if len(self.inflight) >= CHANNEL_CREDITS:
            # Out of credits: block on the oldest ack (backpressure).
            self._ack_oldest()
        payload: Any = items
        data_ref = None
        if _approx_nbytes(items) > PUSH_INLINE_MAX:
            # Zero-copy data plane: seal the batch in the object store and
            # push only the ref; the consumer's node stages it via the
            # native transfer plane and the consumer reads it zero-copy.
            data_ref = ray_tpu.put(items)
            payload = data_ref
        self.inflight.append(
            (self.dst.push.remote(self.channel_id, self.seq, payload),
             data_ref))
        self.seq += 1

    def _ack_oldest(self) -> None:
        ack, data_ref = self.inflight.popleft()
        ray_tpu.get(ack)
        if data_ref is not None:
            # The ack is the credit return: the consumer has processed the
            # batch, so the sealed blob can be evicted everywhere.
            ray_tpu.free([data_ref])

    def send_eof(self) -> None:
        self.flush()
        if self._writer is not None:
            # Close the ring first: the consumer's push_eof joins its drain
            # thread, which exits only after consuming the full backlog —
            # so EOF can never overtake in-flight ring data.
            self._writer.close()
            self._writer = None
        ray_tpu.get(self.dst.push_eof.remote(self.channel_id))

    def flush(self) -> None:
        while self.inflight:
            self._ack_oldest()


class JobWorker:
    """One operator instance (reference: runtime/worker.py JobWorker)."""

    def __init__(self, op_kind: str, fn_blob, instance_index: int,
                 num_instances: int):
        import cloudpickle

        self.kind = op_kind
        self.fn: Optional[Callable] = (
            cloudpickle.loads(fn_blob) if fn_blob is not None else None)
        self.index = instance_index
        self.num_instances = num_instances
        self._lock = threading.Lock()
        # input channels
        self._expected_inputs: set = set()
        self._eof_inputs: set = set()
        # output routing: list of (partition, [instance _OutChannel...])
        self._outputs: List[Tuple[str, List[_OutChannel]]] = []
        self._rr = 0
        # operator state
        self._reduce_state: Dict[Any, Any] = {}
        self._sink_results: List[Any] = []
        self._out_buffers: Dict[int, List[Any]] = defaultdict(list)
        self._native_readers: Dict[str, Tuple[Any, Any]] = {}
        self._native_errors: Dict[str, str] = {}  # channel -> cause traceback
        self.records_in = 0
        self.records_out = 0

    # ---- wiring (called by the driver before the run) ----

    def add_output(self, partition: str, dst_handles: List[Any],
                   channel_prefix: str) -> None:
        chans = [
            _OutChannel(h, f"{channel_prefix}:{self.index}->{j}")
            for j, h in enumerate(dst_handles)
        ]
        self._outputs.append((partition, chans))

    def expect_input(self, channel_id: str) -> None:
        self._expected_inputs.add(channel_id)

    # ---- data plane ----

    def open_native_channel(self, channel_id: str, shm_name: str) -> bool:
        """Consumer half of the native-transport handshake: attach to the
        producer's shm ring and drain it on a dedicated thread (the
        reference's DataReader loop). Returns False when the segment is
        unreachable (producer on another host) — sender falls back to
        actor-call pushes."""
        import pickle as _pickle

        try:
            from .._native.channel import (
                ChannelClosed, ChannelReader, ChannelTimeout,
            )

            # The writer created the segment BEFORE this call, so a local
            # open succeeds immediately and ENOENT means cross-host — a
            # long retry here would only stall wiring (0.5s covers fs
            # visibility jitter, nothing more).
            reader = ChannelReader(shm_name, open_timeout=0.5)
        except Exception:  # noqa: BLE001 - cross-host or lib unavailable
            return False

        def drain():
            while True:
                try:
                    items = _pickle.loads(reader.read(timeout=60.0))
                    with self._lock:
                        # Inside the try: a user-fn or downstream-send
                        # failure must be RECORDED, not silently end the
                        # thread (push_eof raises on the flag — the actor
                        # path surfaces the same error via its ack).
                        self._process(items)
                except ChannelTimeout:
                    continue        # idle source; the ring is still live
                except ChannelClosed:
                    return
                except Exception:  # noqa: BLE001 - fn error/corrupt frame
                    import traceback

                    traceback.print_exc()
                    # Keep the formatted cause: push_eof re-raises with it,
                    # so a user-fn bug surfaces as ITS traceback instead of
                    # an opaque "reader failed mid-stream".
                    self._native_errors[channel_id] = traceback.format_exc()
                    reader.mark_dead()  # unblock a backpressured producer
                    return

        t = threading.Thread(target=drain, daemon=True,
                             name=f"chan-{channel_id[-12:]}")
        t.start()
        self._native_readers[channel_id] = (reader, t)
        return True

    def push(self, channel_id: str, seq: int, items: List[Any]) -> int:
        """Receive one batch; process synchronously (the actor's ordered
        queue is the inbound buffer; credits bound its depth)."""
        with self._lock:
            self._process(items)
        return seq  # ack

    def push_eof(self, channel_id: str) -> bool:
        native = self._native_readers.pop(channel_id, None)
        if native is not None:
            # The sender closed the ring before this call; the drain thread
            # exits once the backlog is fully consumed. Joining it here
            # guarantees EOF ordering behind every data batch.
            reader, thread = native
            thread.join(timeout=300.0)
            if thread.is_alive():
                # Join timed out: closing would unmap the ring under the
                # live drain thread (segfault). Leak the mapping instead
                # and surface the stall.
                raise RuntimeError(
                    f"native channel {channel_id} still draining after "
                    f"300s; refusing EOF")
            reader.close()
            cause = self._native_errors.pop(channel_id, None)
            if cause:
                raise RuntimeError(
                    f"native channel {channel_id} reader failed mid-stream; "
                    f"cause:\n{cause}")
        with self._lock:
            self._eof_inputs.add(channel_id)
            if self._eof_inputs >= self._expected_inputs:
                self._on_all_inputs_done()
        return True

    def inject(self, items: List[Any]) -> None:
        """Source path: driver feeds the source instances directly."""
        with self._lock:
            self._process(items)

    def finish(self) -> None:
        """Source EOF from the driver."""
        with self._lock:
            self._on_all_inputs_done()

    # ---- operator semantics ----

    def _process(self, items: List[Any]) -> None:
        self.records_in += len(items)
        kind, fn = self.kind, self.fn
        if kind in ("source", "key_by"):
            out = items if kind == "source" else [(fn(x), x) for x in items]
            self._emit(out)
        elif kind == "map":
            self._emit([fn(x) for x in items])
        elif kind == "flat_map":
            out: List[Any] = []
            for x in items:
                out.extend(fn(x))
            self._emit(out)
        elif kind == "filter":
            self._emit([x for x in items if fn(x)])
        elif kind == "reduce":
            # items arrive as (key, value); state holds the running reduction
            for key, value in items:
                if key in self._reduce_state:
                    self._reduce_state[key] = fn(self._reduce_state[key], value)
                else:
                    self._reduce_state[key] = value
        elif kind == "union":
            # Pass-through merge point: records from every upstream edge
            # land here and continue downstream interleaved
            # (reference: datastream.py union -> UnionStream).
            self._emit(list(items))
        elif kind == "sink":
            for x in items:
                if fn is not None:
                    fn(x)
                self._sink_results.append(x)
        else:
            raise ValueError(f"unknown operator kind {kind!r}")

    def _on_all_inputs_done(self) -> None:
        if self.kind == "reduce":
            # flush final (key, aggregate) pairs downstream
            self._emit(list(self._reduce_state.items()))
            self._reduce_state = {}
        self._flush_buffers()
        for _, chans in self._outputs:
            for ch in chans:
                ch.send_eof()

    def _emit(self, items: List[Any]) -> None:
        if not items:
            return
        self.records_out += len(items)
        for partition, chans in self._outputs:
            n = len(chans)
            if partition == BROADCAST:
                for ch in chans:
                    ch.send(list(items))
                continue
            if partition == KEY_HASH:
                groups: Dict[int, List[Any]] = defaultdict(list)
                for kv in items:
                    groups[_stable_hash(kv[0]) % n].append(kv)
                for j, group in groups.items():
                    chans[j].send(group)
                continue
            # forward/rebalance: round-robin batches
            chans[self._rr % n].send(list(items))
            self._rr += 1

    def _flush_buffers(self) -> None:
        for _, chans in self._outputs:
            for ch in chans:
                ch.flush()

    # ---- results / stats ----

    def sink_results(self) -> List[Any]:
        return list(self._sink_results)

    def stats(self) -> Dict[str, int]:
        return {"records_in": self.records_in, "records_out": self.records_out}

    def ready(self) -> bool:
        return True
