"""JobWorker actor + credit-based channel protocol
(reference: streaming/python/runtime/worker.py + streaming/src/channel.h,
data_writer/data_reader, flow_control).

One actor per operator instance. Data moves downstream in batches via
``push(channel, seq, items)`` actor calls; each channel has a credit budget
(max unacked batches, the reference's ring-buffer capacity). A sender with no
credits blocks on its oldest in-flight ack — that's the backpressure path.
EOF markers propagate when all of an instance's input channels are exhausted;
stateful operators (reduce) flush on EOF.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

from .graph import BROADCAST, FORWARD, KEY_HASH, REBALANCE, JobGraph

BATCH_SIZE = 256
CHANNEL_CREDITS = 4  # max unacked batches per channel before sender blocks
# Batches whose payload is (approximately) larger than this travel as object
# store refs instead of pickled actor-call bodies: the blob moves through the
# shm arena / native C++ transfer plane (reference: streaming/src/channel.h
# data plane on plasma queues), and the actor call carries only the ref.
PUSH_INLINE_MAX = 32 * 1024


def _approx_nbytes(items: List[Any]) -> int:
    """Cheap payload-size estimate (sampled; no serialization)."""
    n = len(items)
    if n == 0:
        return 0
    sample = items if n <= 32 else items[:: max(1, n // 32)][:32]
    total = 0
    for x in sample:
        nb = getattr(x, "nbytes", None)
        if nb is not None:
            total += int(nb)
        elif isinstance(x, (bytes, bytearray, str)):
            total += len(x)
        elif isinstance(x, tuple) and len(x) == 2:
            v = x[1]
            total += int(getattr(v, "nbytes", 0) or 64)
        else:
            total += 64
    return total * n // len(sample)


def _stable_hash(key: Any) -> int:
    """Process-stable key hash (Python's hash() is salted per process, which
    would break cross-process key routing)."""
    import zlib

    if isinstance(key, bytes):
        data = key
    elif isinstance(key, str):
        data = key.encode()
    else:
        data = repr(key).encode()
    return zlib.crc32(data)


class _OutChannel:
    """Sender side of one edge instance pair (reference: ProducerChannel)."""

    def __init__(self, dst_handle, channel_id: str):
        self.dst = dst_handle
        self.channel_id = channel_id
        self.seq = 0
        self.inflight: deque = deque()  # (ack ref, data ref | None)

    def send(self, items: List[Any]) -> None:
        if len(self.inflight) >= CHANNEL_CREDITS:
            # Out of credits: block on the oldest ack (backpressure).
            self._ack_oldest()
        payload: Any = items
        data_ref = None
        if _approx_nbytes(items) > PUSH_INLINE_MAX:
            # Zero-copy data plane: seal the batch in the object store and
            # push only the ref; the consumer's node stages it via the
            # native transfer plane and the consumer reads it zero-copy.
            data_ref = ray_tpu.put(items)
            payload = data_ref
        self.inflight.append(
            (self.dst.push.remote(self.channel_id, self.seq, payload),
             data_ref))
        self.seq += 1

    def _ack_oldest(self) -> None:
        ack, data_ref = self.inflight.popleft()
        ray_tpu.get(ack)
        if data_ref is not None:
            # The ack is the credit return: the consumer has processed the
            # batch, so the sealed blob can be evicted everywhere.
            ray_tpu.free([data_ref])

    def send_eof(self) -> None:
        self.flush()
        ray_tpu.get(self.dst.push_eof.remote(self.channel_id))

    def flush(self) -> None:
        while self.inflight:
            self._ack_oldest()


class JobWorker:
    """One operator instance (reference: runtime/worker.py JobWorker)."""

    def __init__(self, op_kind: str, fn_blob, instance_index: int,
                 num_instances: int):
        import cloudpickle

        self.kind = op_kind
        self.fn: Optional[Callable] = (
            cloudpickle.loads(fn_blob) if fn_blob is not None else None)
        self.index = instance_index
        self.num_instances = num_instances
        self._lock = threading.Lock()
        # input channels
        self._expected_inputs: set = set()
        self._eof_inputs: set = set()
        # output routing: list of (partition, [instance _OutChannel...])
        self._outputs: List[Tuple[str, List[_OutChannel]]] = []
        self._rr = 0
        # operator state
        self._reduce_state: Dict[Any, Any] = {}
        self._sink_results: List[Any] = []
        self._out_buffers: Dict[int, List[Any]] = defaultdict(list)
        self.records_in = 0
        self.records_out = 0

    # ---- wiring (called by the driver before the run) ----

    def add_output(self, partition: str, dst_handles: List[Any],
                   channel_prefix: str) -> None:
        chans = [
            _OutChannel(h, f"{channel_prefix}:{self.index}->{j}")
            for j, h in enumerate(dst_handles)
        ]
        self._outputs.append((partition, chans))

    def expect_input(self, channel_id: str) -> None:
        self._expected_inputs.add(channel_id)

    # ---- data plane ----

    def push(self, channel_id: str, seq: int, items: List[Any]) -> int:
        """Receive one batch; process synchronously (the actor's ordered
        queue is the inbound buffer; credits bound its depth)."""
        with self._lock:
            self._process(items)
        return seq  # ack

    def push_eof(self, channel_id: str) -> bool:
        with self._lock:
            self._eof_inputs.add(channel_id)
            if self._eof_inputs >= self._expected_inputs:
                self._on_all_inputs_done()
        return True

    def inject(self, items: List[Any]) -> None:
        """Source path: driver feeds the source instances directly."""
        with self._lock:
            self._process(items)

    def finish(self) -> None:
        """Source EOF from the driver."""
        with self._lock:
            self._on_all_inputs_done()

    # ---- operator semantics ----

    def _process(self, items: List[Any]) -> None:
        self.records_in += len(items)
        kind, fn = self.kind, self.fn
        if kind in ("source", "key_by"):
            out = items if kind == "source" else [(fn(x), x) for x in items]
            self._emit(out)
        elif kind == "map":
            self._emit([fn(x) for x in items])
        elif kind == "flat_map":
            out: List[Any] = []
            for x in items:
                out.extend(fn(x))
            self._emit(out)
        elif kind == "filter":
            self._emit([x for x in items if fn(x)])
        elif kind == "reduce":
            # items arrive as (key, value); state holds the running reduction
            for key, value in items:
                if key in self._reduce_state:
                    self._reduce_state[key] = fn(self._reduce_state[key], value)
                else:
                    self._reduce_state[key] = value
        elif kind == "sink":
            for x in items:
                if fn is not None:
                    fn(x)
                self._sink_results.append(x)
        else:
            raise ValueError(f"unknown operator kind {kind!r}")

    def _on_all_inputs_done(self) -> None:
        if self.kind == "reduce":
            # flush final (key, aggregate) pairs downstream
            self._emit(list(self._reduce_state.items()))
            self._reduce_state = {}
        self._flush_buffers()
        for _, chans in self._outputs:
            for ch in chans:
                ch.send_eof()

    def _emit(self, items: List[Any]) -> None:
        if not items:
            return
        self.records_out += len(items)
        for partition, chans in self._outputs:
            n = len(chans)
            if partition == BROADCAST:
                for ch in chans:
                    ch.send(list(items))
                continue
            if partition == KEY_HASH:
                groups: Dict[int, List[Any]] = defaultdict(list)
                for kv in items:
                    groups[_stable_hash(kv[0]) % n].append(kv)
                for j, group in groups.items():
                    chans[j].send(group)
                continue
            # forward/rebalance: round-robin batches
            chans[self._rr % n].send(list(items))
            self._rr += 1

    def _flush_buffers(self) -> None:
        for _, chans in self._outputs:
            for ch in chans:
                ch.flush()

    # ---- results / stats ----

    def sink_results(self) -> List[Any]:
        return list(self._sink_results)

    def stats(self) -> Dict[str, int]:
        return {"records_in": self.records_in, "records_out": self.records_out}

    def ready(self) -> bool:
        return True
