"""ray_tpu.streaming: actor dataflow streaming (reference: streaming/).

The reference's streaming library is a C++ data plane (credit-based channels
on plasma queues, streaming/src/channel.h) under a Python DataStream API
(streaming/python/datastream.py). Here the DataStream API compiles to a
JobGraph executed by JobWorker actors; channels are credit-based bounded
buffers over actor calls (backpressure propagates upstream when credits run
out), and operator state lives in the worker actors.
"""

from .datastream import StreamingContext  # noqa: F401

__all__ = ["StreamingContext"]
